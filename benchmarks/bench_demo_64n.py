"""DeMo tracked-config bench + op-level profile (VERDICT r3 #4).

Reproduces the BASELINE 64-node DeMo row (docs-char GPT "small",
64 simulated nodes, batch 16, bf16 autocast, top-32 / chunk-64
compression, cosine-warmup lr, clip 1.0) and reports steady-state it/s;
``--profile`` additionally captures an XLA trace over a few steps and
prints the top device ops aggregated by name — the evidence base for
optimizing the compression pipeline (sort/gather/decode vs model).

Usage (on the chip):
    python benchmarks/bench_demo_64n.py --steps 40
    python benchmarks/bench_demo_64n.py --steps 12 --profile
Knobs for lever experiments: --compression_chunk, --segment_bytes,
--delta_bf16, --nodes, --steps_per_call.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, REPO)

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--nodes", type=int, default=64)
    ap.add_argument("--batch_size", type=int, default=16)
    ap.add_argument("--steps_per_call", type=int, default=1)
    ap.add_argument("--compression_topk", type=int, default=32)
    ap.add_argument("--compression_chunk", type=int, default=64)
    ap.add_argument("--segment_bytes", type=int, default=256 * 1024 * 1024)
    ap.add_argument("--delta_bf16", action="store_true")
    ap.add_argument("--profile", action="store_true")
    ap.add_argument("--profile_dir", default="/tmp/demo64_profile")
    ap.add_argument("--device", default=None)
    args = ap.parse_args()

    import jax.numpy as jnp

    from gym_tpu import Trainer
    from gym_tpu.data import get_dataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy import DeMoStrategy, OptimSpec

    ds, vocab = get_dataset("docs", 256, end_pc=0.9)
    cfg = GPTConfig(block_size=256, vocab_size=int(vocab), n_layer=4,
                    n_head=4, n_embd=128, dropout=0.0)
    strat = DeMoStrategy(
        optim_spec=OptimSpec("sgd", lr=1e-3),
        compression_topk=args.compression_topk,
        compression_chunk=args.compression_chunk,
        weight_decay=0.1, max_norm=1.0,
        lr_scheduler="lambda_cosine",
        lr_scheduler_kwargs={"warmup_steps": 100, "cosine_anneal": False},
        segment_bytes=args.segment_bytes,
        delta_dtype=jnp.bfloat16 if args.delta_bf16 else None,
    )
    def one_fit(steps, **kw):
        t0 = time.time()
        res = Trainer(GPT(cfg), ds, None).fit(
            strategy=strat, num_nodes=args.nodes, max_steps=steps,
            batch_size=args.batch_size, minibatch_size=args.batch_size,
            autocast=True, val_size=0, val_interval=0,
            steps_per_call=args.steps_per_call, device=args.device,
            show_progress=False, log_dir="/tmp/demo64_logs", **kw,
        )
        return res, time.time() - t0

    # each fit builds fresh jitted closures, so any single fit's wall
    # time includes a full compile — steady-state it/s is taken as the
    # two-fit DIFFERENCE (identical programs compile in both fits, so
    # the compile term cancels). Neither timing fit carries the profiler
    # (ADVICE r4: tracer overhead in the long fit alone biased
    # it_s_steady low); the trace comes from a separate third fit below.
    short = max(2, args.steps // 4)
    _, t_short = one_fit(short)
    res, t_long = one_fit(args.steps)
    tail_s_per_step = (t_long - t_short) / (args.steps - short)
    print(json.dumps({
        "it_s_steady": round(1.0 / tail_s_per_step, 3),
        "it_s_incl_compile": round(res.steps_per_second, 3),
        "wall_s": round(t_long, 1),
        "final_loss": round(float(res.final_train_loss), 4),
        "steps": args.steps,
    }), flush=True)

    if args.profile:
        import shutil
        shutil.rmtree(args.profile_dir, ignore_errors=True)
        one_fit(short, profile_dir=args.profile_dir)
        _print_top_ops(args.profile_dir)


def _print_top_ops(profile_dir: str, top: int = 28):
    """Aggregate device-plane event durations by op name from the
    xplane.pb JAX wrote (tensorflow protos are available in this
    image)."""
    paths = glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        print("no xplane.pb found under", profile_dir)
        return
    from tensorflow.core.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        totals = {}
        for line in plane.lines:
            for ev in line.events:
                meta = plane.event_metadata[ev.metadata_id]
                totals[meta.name] = (totals.get(meta.name, 0)
                                     + ev.duration_ps)
        rows = sorted(totals.items(), key=lambda kv: -kv[1])[:top]
        tot = sum(totals.values()) or 1
        print(f"== plane: {plane.name} (total {tot/1e12:.1f} ms summed)")
        for name, ps in rows:
            print(f"  {ps/1e9:10.3f} ms  {100*ps/tot:5.1f}%  {name[:110]}")


if __name__ == "__main__":
    main()
