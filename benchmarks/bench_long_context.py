"""Long-context attention benchmarks.

Two claims to substantiate (SURVEY §5.7 — capability the reference lacks):

1. Kernel scaling on one chip: fused/flash attention vs dense XLA as T
   grows (dense materializes the [T, T] probs; the kernels don't).
2. Context-parallel memory scaling: with the sequence sharded over a
   ``seq`` mesh axis (ring attention), per-device score memory is
   O((T/cp)²) — contexts that OOM or crawl on one device run fine sharded.

Usage:
  python benchmarks/bench_long_context.py --mode kernel   # TPU, one chip
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/bench_long_context.py --mode ring --device cpu
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np


def shard_map(f, **kw):
    """Version-portable shard_map (jax >= 0.6 promoted it out of
    experimental; 0.4.x spells check_vma as check_rep, whose checker
    also chokes on scan carries — disabled on both)."""
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as legacy
    kw.pop("check_vma", None)
    return legacy(f, check_rep=False, **kw)


def bench_kernel(T, impl, B=4, H=8, D=64, inner=10, iters=4):
    """`inner` chained attention calls inside ONE jit so per-dispatch
    transport latency (~100 ms on remote tunnels) amortizes away."""
    import jax
    import jax.numpy as jnp
    from gym_tpu.ops.attention import causal_attention

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.bfloat16)
        for _ in range(3)
    )

    @jax.jit
    def f(q, k, v):
        def body(_, x):
            return causal_attention(x, k, v, impl=impl)
        out = jax.lax.fori_loop(0, inner, body, q)
        return jnp.sum(out.astype(jnp.float32))

    try:
        float(f(q, k, v))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            acc = float(f(q, k, v))
        dt = (time.perf_counter() - t0) / (iters * inner)
        return round(dt * 1000, 2)
    except Exception as e:
        return f"{type(e).__name__}"


def bench_ring(T, cp, B=1, H=4, D=32, iters=5, inner=1, dtype="float32",
               layout="contiguous"):
    """``inner`` > 1 chains ring calls inside ONE jit (fori_loop), so
    per-dispatch transport latency (~100 ms on remote tunnels) amortizes
    — required for honest chip timings; CPU-mesh runs are compute-bound
    and fine at inner=1."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from gym_tpu.parallel.ring_attention import ring_causal_attention

    devs = jax.devices()
    if len(devs) < cp:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    assert len(devs) >= cp, f"need {cp} devices"
    mesh = Mesh(np.array(devs[:cp]), ("seq",))
    spec = P(None, None, "seq", None)
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.dtype(dtype))
        for _ in range(3)
    )

    def f(q, k, v):
        return ring_causal_attention(q, k, v, axis_name="seq",
                                     layout=layout)

    # check_vma=False: the kernel-backed block path's pallas out_shapes
    # carry no vma info (same setting as the NodeRuntime programs)
    sm = shard_map(f, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec, check_vma=False)

    @jax.jit
    def g(q, k, v):
        def body(_, x):
            return sm(x, k, v)
        out = jax.lax.fori_loop(0, inner, body, q)
        return jnp.sum(out.astype(jnp.float32))

    try:
        float(g(q, k, v))  # compile + warm, fenced by the value fetch
        t0 = time.perf_counter()
        for _ in range(iters):
            acc = float(g(q, k, v))
        dt = (time.perf_counter() - t0) / (iters * inner)
        return dt * 1000
    except Exception as e:
        return f"{type(e).__name__}"


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["kernel", "ring", "ring_chip"],
                   default="kernel")
    p.add_argument("--device", default=None)
    args = p.parse_args()
    if args.device == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    results = []
    if args.mode == "kernel":
        for T in (512, 1024, 2048, 4096, 8192, 16384, 32768):
            row = {"T": T}
            for impl in ("dense", "flash"):
                row[impl] = bench_kernel(T, impl)
            results.append(row)
            print(json.dumps(row), flush=True)
    elif args.mode == "ring_chip":
        # the ring path on the real chip: a 1-wide ring routes through the
        # tiled flash kernel (ring_attention.py n==1 dispatch), so the
        # T=32k context runs the ring API at kernel speed on one device.
        # dtype/inner recorded: these rows are NOT comparable to the f32
        # inner=1 CPU-mesh ring rows.
        for T in (8192, 16384, 32768):
            ms = bench_ring(T, 1, B=1, H=8, D=64, inner=10,
                            dtype="bfloat16")
            row = {"T": T, "cp": 1, "ms": ms, "dtype": "bfloat16",
                   "inner": 10}
            results.append(row)
            print(json.dumps(row), flush=True)
    else:
        # contiguous vs zig-zag at each (T, cp): the VERDICT r4 #5 claim
        # is zig-zag ≥1.5× at cp≥2 (every ring step does useful work)
        for T, cp in ((2048, 1), (2048, 8), (8192, 8), (16384, 8),
                      (32768, 8)):
            row = {"T": T, "cp": cp, "dtype": "float32", "inner": 1}
            row["ms"] = bench_ring(T, cp)
            if cp > 1:
                row["ms_zigzag"] = bench_ring(T, cp, layout="zigzag")
                if isinstance(row["ms"], float) and isinstance(
                        row["ms_zigzag"], float):
                    row["zigzag_speedup"] = round(
                        row["ms"] / row["ms_zigzag"], 2)
            results.append(row)
            print(json.dumps(row), flush=True)
    os.makedirs("logs", exist_ok=True)
    with open(f"logs/long_context_{args.mode}.json", "w") as f:
        json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
