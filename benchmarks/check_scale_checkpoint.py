"""Checkpoint/resume at realistic scale (the PARITY §5.4 scale claim).

Trains GPT-2 base (124M params + DiLoCo inner AdamW + outer
master/momentum — ~2.5 GB of state) for 4 steps on the chip with
Orbax checkpoints every 2 steps, then calls ``fit`` again with
``max_steps=8``: the second run must restore from step 4 and continue
the loss trajectory at steps 4..7. Takes ~25 min end-to-end on the
remote-transport chip (the async saves dominate).

Usage: python benchmarks/check_scale_checkpoint.py
"""

from __future__ import annotations

import os
import shutil
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> None:
    import numpy as np

    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy import DiLoCoStrategy, OptimSpec
    from gym_tpu.trainer import Trainer

    save_dir = "/tmp/gym_tpu_ckpt_scale"
    shutil.rmtree(save_dir, ignore_errors=True)

    cfg = GPTConfig.gpt2_base()
    cfg.block_size = 512
    cfg.attn_impl = "flash"
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size, 300000, dtype=np.int64)

    def factory(rank, n, is_val):
        return ContiguousGPTTrainDataset(data, block_size=512)

    def fit(steps):
        return Trainer(GPT(cfg), factory, factory).fit(
            strategy=DiLoCoStrategy(OptimSpec("adamw", lr=3e-4), H=2),
            num_nodes=1, max_steps=steps, batch_size=4, minibatch_size=4,
            val_size=0, autocast=True, show_progress=False,
            checkpoint_interval=2, save_dir=save_dir,
            run_name="base_ckpt", log_dir="/tmp/gym_tpu_ckpt_logs", seed=7,
        )

    t0 = time.time()
    r1 = fit(4)
    print("first run losses:",
          [round(l, 4) for _, l in r1.history["train_loss"]], flush=True)
    r2 = fit(8)
    steps = [s for s, _ in r2.history["train_loss"]]
    print("resumed losses:",
          [(s, round(l, 4)) for s, l in r2.history["train_loss"]])
    assert steps == [4, 5, 6, 7], f"expected resume at step 4, got {steps}"
    print(f"GPT-2 base checkpoint/resume ok ({time.time() - t0:.0f} s)")


if __name__ == "__main__":
    main()
