"""Pipeline-schedule cost sanity on the virtual CPU mesh (VERDICT r3
weak #4: "the GPipe schedule has zero measured throughput anywhere").

What is measurable on this host: the 8 virtual CPU devices time-share ONE
physical core, so cross-device overlap cannot show up as wall-clock
speedup — on real multi-chip hardware the stages run concurrently by
SPMD construction (one program, lockstep ticks, ppermute sync). What CAN
be measured here is the schedule's COST LAW: a correct fill-drain
pipeline executes (M + S − 1) ticks of (L/S)-deep stage work per step,
so on time-shared devices

    time(pp=S, M microbatches) / time(pp=1)  ≈  (M + S − 1) / M

(the GPipe bubble fraction). A defective schedule — per-tick re-dispatch,
serialization overhead, an accidental S× tick count — would exceed the
law, and the law's M-dependence (ratio falling toward 1 as M grows) is
the signature that the bubble, not a fixed overhead, is what remains.

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
           python benchmarks/bench_pp_cpu.py [--steps 12]
Prints one JSON line per (pp, M) config plus the predicted ratios.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

import numpy as np


def run(pp: int, n_micro: int, steps: int):
    """Steady-state seconds/step of the pipelined (or plain) train step,
    timed over jitted dispatches with a value fetch as the fence."""
    import jax

    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy
    from gym_tpu.trainer import Trainer

    rng = np.random.default_rng(0)
    data = rng.integers(0, 64, 262144, dtype=np.int64)
    ds = ContiguousGPTTrainDataset(data, block_size=256)

    # big enough that stage compute dominates host dispatch on the
    # single-core CPU mesh (at 128-dim shapes the per-step host overhead
    # swamped the schedule and the ratios measured noise)
    cfg = GPTConfig(block_size=256, vocab_size=64, n_layer=4, n_head=4,
                    n_embd=256, dropout=0.0)
    # warmup fold: run a couple of steps inside fit, then time the rest
    t0 = time.time()
    res = Trainer(GPT(cfg), ds, None).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=2, max_steps=steps, batch_size=4 * n_micro,
        minibatch_size=4, val_size=0, val_interval=0, pp=pp,
        device="cpu", show_progress=False,
        log_dir="/tmp/gym_tpu_pp_bench_logs",
    )
    # fit's steps_per_second covers the whole loop incl. compile; redo a
    # timed tail by fitting twice and subtracting would be noisy — use
    # the second fit (warm persistent compilation cache within process)
    t0 = time.time()
    res = Trainer(GPT(cfg), ds, None).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=2, max_steps=steps, batch_size=4 * n_micro,
        minibatch_size=4, val_size=0, val_interval=0, pp=pp,
        device="cpu", show_progress=False,
        log_dir="/tmp/gym_tpu_pp_bench_logs",
    )
    dt = (time.time() - t0) / steps
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    rows = []
    for n_micro in (2, 4, 8):
        t1 = run(1, n_micro, args.steps)
        t2 = run(2, n_micro, args.steps)
        predicted = (n_micro + 1) / n_micro  # (M + S − 1) / M at S=2
        rows.append({
            "M": n_micro,
            "pp1_s_per_step": round(t1, 4),
            "pp2_s_per_step": round(t2, 4),
            "ratio": round(t2 / t1, 3),
            "bubble_law": round(predicted, 3),
        })
        print(json.dumps(rows[-1]), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
