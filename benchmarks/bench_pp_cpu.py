"""Pipeline-schedule cost sanity on the virtual CPU mesh (VERDICT r3
weak #4: "the GPipe schedule has zero measured throughput anywhere").

What is measurable on this host: the 8 virtual CPU devices time-share ONE
physical core, so cross-device overlap cannot show up as wall-clock
speedup — on real multi-chip hardware the stages run concurrently by
SPMD construction (one program, lockstep ticks, ppermute sync). What CAN
be measured here is the schedule's COST LAW: a correct fill-drain
pipeline executes (M + S − 1) ticks of (L/S)-deep stage work per step,
so on time-shared devices

    time(pp=S, M microbatches) / time(pp=1)  ≈  (M + S − 1) / M

(the GPipe bubble fraction). A defective schedule — per-tick re-dispatch,
serialization overhead, an accidental S× tick count — would exceed the
law.

Timing method: each Trainer.fit builds fresh jitted closures, so ANY
single fit's wall time includes a full XLA compile (larger for the pp=2
scan program, which would contaminate the ratio). Per-step cost is
therefore taken as the DIFFERENCE of two fits in the same process with
different step counts — identical programs compile in both, so the
compile term cancels: s/step = (t(N_long) − t(N_short)) / (N_long −
N_short).

Usage: python benchmarks/bench_pp_cpu.py [--steps 16] [--n_layer 4]
           [--out PATH]
Prints one JSON line per M plus the predicted ratio. The committed
`logs/pp_cpu_schedule.json` rows come from --n_layer 4 and --n_layer 8.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
sys.path.insert(0, REPO)

os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

import numpy as np


def fit_time(pp: int, n_micro: int, steps: int, n_layer: int) -> float:
    """Wall seconds of one full fit (compile + steps) at the config."""
    import jax

    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy
    from gym_tpu.trainer import Trainer

    rng = np.random.default_rng(0)
    data = rng.integers(0, 64, 262144, dtype=np.int64)
    ds = ContiguousGPTTrainDataset(data, block_size=256)
    cfg = GPTConfig(block_size=256, vocab_size=64, n_layer=n_layer,
                    n_head=4, n_embd=256, dropout=0.0)
    t0 = time.time()
    Trainer(GPT(cfg), ds, None).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=2, max_steps=steps, batch_size=4 * n_micro,
        minibatch_size=4, val_size=0, val_interval=0, pp=pp,
        device="cpu", show_progress=False,
        log_dir="/tmp/gym_tpu_pp_bench_logs",
    )
    return time.time() - t0


def s_per_step(pp: int, n_micro: int, steps: int, n_layer: int) -> float:
    """Compile-cancelled steady-state s/step (two-fit difference)."""
    short = max(2, steps // 4)
    t_short = fit_time(pp, n_micro, short, n_layer)
    t_long = fit_time(pp, n_micro, steps, n_layer)
    return (t_long - t_short) / (steps - short)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--n_layer", type=int, default=4)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # never touch accelerators

    rows = []
    for n_micro in (2, 4, 8):
        t1 = s_per_step(1, n_micro, args.steps, args.n_layer)
        t2 = s_per_step(2, n_micro, args.steps, args.n_layer)
        predicted = (n_micro + 1) / n_micro  # (M + S − 1) / M at S=2
        rows.append({
            "n_layer": args.n_layer,
            "M": n_micro,
            "pp1_s_per_step": round(t1, 4),
            "pp2_s_per_step": round(t2, 4),
            "ratio": round(t2 / t1, 3),
            "bubble_law": round(predicted, 3),
        })
        print(json.dumps(rows[-1]), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
