"""Lockstep same-batch ablation for the GPT head-to-head band violation
(VERDICT r4 weak #4 / next #4).

``logs/head_to_head_gpt.json`` showed a 0.038-nat gap (2x the 2-run
same-init band) between the reference and gym_tpu at the tracked
``docs_4n_diloco_gpt_small`` config. The candidate causes divide into
(a) optimizer/model math (torch Adam vs optax adam semantics — reference
``nanogpt.py:362-392`` was the verdict's prime suspect) and (b) stochastic
data-order spread that the 2-run band underestimates.

``--mode adam`` (default) isolates (a) completely: one node, identical
ported init, IDENTICAL explicit batch sequence, plain Adam(lr=1e-3) both
sides, torch stepped manually, ours a jitted optax update. With dropout=0
the two trajectories are the same mathematical map, so any systematic
optimizer discrepancy shows as an immediate, growing per-step bias;
fp-chaos (the null hypothesis) shows as ~1e-6 agreement early, drifting
randomly later.

``--mode diloco [--seed N]`` runs the FULL 4-node DiLoCo pipeline in
lockstep — identical per-node batches, inner Adam + the
average/outer-Nesterov round + final node average on both sides — to
cover the outer loop too. Measured: per-step math identical (1-node,
≤1.1e-4/100 steps); the 4-node trajectory is chaotic with an
fp-reassociation floor of ~±0.01 final-eval across batch seeds with NO
systematic sign (seed 17: +0.0124, seed 18: −0.0009). Full resolution
chain in BENCHMARKS.md "Identical-init GPT row".

Writes logs/h2h_lockstep.json (adam) /
logs/h2h_lockstep_diloco*.json (diloco):
    {"step_abs_diff": {...}, "final_eval_ref": ..., "final_eval_ours": ...}

Usage: python benchmarks/h2h_lockstep.py [--mode adam|diloco]
           [--steps 100] [--batch 8] [--seed 17] [--out PATH]
       (CPU-only: pins jax to the host backend; torch is CPU anyway.)
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

BLOCK = 64


def _setup():
    """Shared preamble for both modes: data, mirrored configs, the
    seed-100 torch prototype, and its ported+DEEP-COPIED flax init.

    The deep copy matters: the porter's ``.detach().numpy()`` views share
    storage with the torch params, which the in-process loops below
    mutate in place (``jnp.asarray`` is NOT enough — the JAX CPU backend
    aliases aligned numpy buffers zero-copy; the h2h harness never hits
    this — its reference side trains in spawned processes)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import torch

    from reference_head_to_head import REF, docs_tokens, port_torch_gpt

    if REF not in sys.path:
        sys.path.insert(0, REF)
    from example.nanogpt.nanogpt import GPT as RefGPT
    from example.nanogpt.nanogpt import GPTConfig as RefConfig

    from gym_tpu.models.nanogpt import GPTConfig

    ds, ev_ds, vocab = docs_tokens(BLOCK)
    rcfg = RefConfig(block_size=BLOCK, vocab_size=vocab, n_layer=4,
                     n_head=4, n_embd=128, dropout=0.0, bias=True)
    ocfg = GPTConfig(block_size=BLOCK, vocab_size=vocab, n_layer=4,
                     n_head=4, n_embd=128, dropout=0.0, bias=True)
    torch.manual_seed(100)
    proto = RefGPT(rcfg)
    ported = port_torch_gpt(proto, ocfg.n_layer)
    params0 = jax.tree.map(np.array, ported)
    return ds, ev_ds, rcfg, ocfg, proto, params0


def _our_eval(lm, params, ev_ds):
    import jax

    rng_e = np.random.default_rng(0)
    eidx = rng_e.integers(0, len(ev_ds), 64)
    ex, ey = ev_ds.take(eidx)
    return float(lm.loss(params, {}, (ex, ey),
                         jax.random.PRNGKey(0), False)[0])


def _write(out, payload):
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps(payload))


def main_adam(args):
    import jax
    import torch

    from reference_head_to_head import TorchTokenDataset, torch_eval_loss_gpt

    from gym_tpu.models.nanogpt import GPT

    ds, ev_ds, rcfg, ocfg, rmodel, params0 = _setup()

    # identical explicit batch sequence, drawn once
    rng = np.random.default_rng(7)
    idxs = rng.integers(0, len(ds), (args.steps, args.batch))

    # ---- torch side: manual Adam loop ----
    opt = torch.optim.Adam(rmodel.parameters(), lr=1e-3)
    ref_losses = []
    for t in range(args.steps):
        x, y = ds.take(idxs[t])
        xb = torch.tensor(np.asarray(x, dtype=np.int64))
        yb = torch.tensor(np.asarray(y, dtype=np.int64))
        opt.zero_grad()
        loss = rmodel((xb, yb))
        loss.backward()
        opt.step()
        ref_losses.append(float(loss))
    ref_eval = torch_eval_loss_gpt(rmodel, TorchTokenDataset(ev_ds), BLOCK)

    # ---- gym_tpu side: jitted optax adam on the ported init ----
    import optax

    from gym_tpu.models.base import LossModel

    lm = LossModel(GPT(ocfg))
    tx = optax.adam(1e-3)
    params = params0
    opt_state = tx.init(params)
    key = jax.random.PRNGKey(0)  # dropout=0: never drawn

    @jax.jit
    def step(params, opt_state, batch):
        (loss, _), grads = jax.value_and_grad(
            lambda p: lm.loss(p, {}, batch, key, True), has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    our_losses = []
    for t in range(args.steps):
        x, y = ds.take(idxs[t])
        params, opt_state, loss = step(params, opt_state, (x, y))
        our_losses.append(float(loss))
    our_eval = _our_eval(lm, params, ev_ds)

    diffs = np.abs(np.array(ref_losses) - np.array(our_losses))
    probe = {str(t): round(float(diffs[t]), 7)
             for t in (0, 1, 2, 5, 9, 24, 49, args.steps - 1)
             if t < args.steps}
    _write(args.out or "logs/h2h_lockstep.json", {
        "config": "lockstep_1n_adam_gpt_small_docs",
        "steps": args.steps,
        "first10_max_abs_diff": round(float(diffs[:10].max()), 7),
        "step_abs_diff": probe,
        "final_train_abs_diff": round(float(diffs[-1]), 6),
        "final_eval_ref": round(ref_eval, 4),
        "final_eval_ours": round(our_eval, 4),
    })


def main_diloco(args, nodes=4, H=50):
    """4-node DiLoCo lockstep: identical per-node batch sequences through
    BOTH frameworks' full DiLoCo pipelines (inner Adam + periodic
    average/outer-Nesterov + final node average). The adam mode exonerated
    the inner optimizer; this covers the outer loop and the averaging.

    The torch side replicates the reference's semantics in-process
    (``exogym/strategy/diloco.py``: inner step; at local_step % H == 0
    and > 0 [pre-increment]: average models -> master outer SGD(0.7,
    nesterov, m=0.9) on (master - avg) -> broadcast master; final =
    node average)."""
    import jax
    import torch

    from reference_head_to_head import TorchTokenDataset, torch_eval_loss_gpt

    from gym_tpu.models.nanogpt import GPT

    ds, ev_ds, rcfg, ocfg, proto, params0 = _setup()
    steps = args.steps
    rng = np.random.default_rng(args.seed)
    idxs = rng.integers(0, len(ds), (steps, nodes, args.batch))

    # ---- torch side: reference DiLoCo replicated in-process ----
    models = [copy.deepcopy(proto) for _ in range(nodes)]
    opts = [torch.optim.Adam(m.parameters(), lr=1e-3) for m in models]
    master = copy.deepcopy(proto)
    outer = torch.optim.SGD(master.parameters(), lr=0.7, nesterov=True,
                            momentum=0.9)
    local_step = 0
    for t in range(steps):
        for n in range(nodes):
            x, y = ds.take(idxs[t, n])
            xb = torch.tensor(np.asarray(x, dtype=np.int64))
            yb = torch.tensor(np.asarray(y, dtype=np.int64))
            opts[n].zero_grad()
            loss = models[n]((xb, yb))
            loss.backward()
            opts[n].step()
        if local_step % H == 0 and local_step > 0:
            with torch.no_grad():
                avg = {k: sum(m.state_dict()[k] for m in models) / nodes
                       for k in models[0].state_dict()}
            outer.zero_grad()
            for k, p in master.named_parameters():
                p.grad = p.data - avg[k]
            outer.step()
            with torch.no_grad():
                msd = master.state_dict()
                for m in models:
                    m.load_state_dict(msd)
        local_step += 1
    with torch.no_grad():
        avg = {k: sum(m.state_dict()[k] for m in models) / nodes
               for k in models[0].state_dict()}
        final = copy.deepcopy(proto)
        final.load_state_dict(avg)
    ref_eval = torch_eval_loss_gpt(final, TorchTokenDataset(ev_ds), BLOCK)

    # ---- gym_tpu side: the REAL strategy/runtime on a 4-node CPU mesh ----
    import jax.numpy as jnp

    from gym_tpu.models.base import LossModel
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_train_step

    devs = jax.devices("cpu")
    runtime = NodeRuntime.create(nodes, devs[:min(nodes, len(devs))])
    lm = LossModel(GPT(ocfg))
    strat = DiLoCoStrategy(OptimSpec("adam", lr=1e-3), H=H)
    strat.finalize(max_steps=steps)
    x0, y0 = ds.take(idxs[0, 0])
    init_fn = make_init_fn(lm, strat, (x0, y0), seed=0,
                           init_params=jax.tree.map(jnp.asarray, params0))
    state = runtime.init_state(init_fn)
    step_fn = runtime.compile(make_train_step(lm, strat, runtime.ctx))
    for t in range(steps):
        xs, ys = [], []
        for n in range(nodes):
            x, y = ds.take(idxs[t, n])
            xs.append(x[None])      # [1(micro), bs, T]
            ys.append(y[None])
        batch_t = runtime.shard_batch((np.stack(xs), np.stack(ys)))
        state, metrics = step_fn(state, batch_t)
    params_avg = runtime.average_over_nodes(state.params)
    our_eval = _our_eval(lm, params_avg, ev_ds)

    default_out = ("logs/h2h_lockstep_diloco.json" if args.seed == 17
                   else f"logs/h2h_lockstep_diloco_s{args.seed}.json")
    _write(args.out or default_out, {
        "config": f"lockstep_{nodes}n_diloco_H{H}_gpt_small_docs",
        "steps": steps,
        "batch_seed": args.seed,
        "final_eval_ref": round(ref_eval, 4),
        "final_eval_ours": round(our_eval, 4),
        "abs_diff": round(abs(ref_eval - our_eval), 5),
    })


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["adam", "diloco"], default="adam")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seed", type=int, default=17,
                    help="batch-sequence seed (diloco mode)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    (main_diloco if args.mode == "diloco" else main_adam)(args)
