"""Lockstep same-batch ablation for the GPT head-to-head band violation
(VERDICT r4 weak #4 / next #4).

``logs/head_to_head_gpt.json`` shows a 0.038-nat gap (2x the measured
same-init band) between the reference and gym_tpu at the tracked
``docs_4n_diloco_gpt_small`` config. The candidate causes divide into
(a) optimizer/model math (torch Adam vs optax adam semantics — reference
``nanogpt.py:362-392`` was the verdict's prime suspect) and (b) stochastic
data-order spread that the 2-run band underestimates.

This script isolates (a) completely: one node, identical ported init,
IDENTICAL explicit batch sequence, plain Adam(lr=1e-3) both sides, torch
stepped manually, ours stepped by a jitted optax update. With dropout=0
the two trajectories are the same mathematical map, so any systematic
optimizer discrepancy shows as an immediate, growing per-step bias;
fp-chaos (the null hypothesis) shows as ~1e-6 agreement early, drifting
randomly later.

Writes logs/h2h_lockstep.json:
    {"step_abs_diff": {...}, "final_eval_ref": ..., "final_eval_ours": ...,
     "first10_max_abs_diff": ...}

Usage: python benchmarks/h2h_lockstep.py [--steps 100] [--batch 8]
       (CPU-only: pins jax to the host backend; torch is CPU anyway.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--out", default="logs/h2h_lockstep.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import torch

    from reference_head_to_head import (REF, docs_tokens, port_torch_gpt,
                                        torch_eval_loss_gpt,
                                        TorchTokenDataset)

    if REF not in sys.path:
        sys.path.insert(0, REF)
    from example.nanogpt.nanogpt import GPT as RefGPT
    from example.nanogpt.nanogpt import GPTConfig as RefConfig

    from gym_tpu.models.nanogpt import GPT, GPTConfig

    block = 64
    ds, ev_ds, vocab = docs_tokens(block)
    rcfg = RefConfig(block_size=block, vocab_size=vocab, n_layer=4,
                     n_head=4, n_embd=128, dropout=0.0, bias=True)
    ocfg = GPTConfig(block_size=block, vocab_size=vocab, n_layer=4,
                     n_head=4, n_embd=128, dropout=0.0, bias=True)

    torch.manual_seed(100)
    rmodel = RefGPT(rcfg)
    ported = port_torch_gpt(rmodel, ocfg.n_layer)
    # deep-copy NOW: the porter's .detach().numpy() views share storage
    # with the torch params, which the in-process Adam loop below mutates
    # in place (jnp.asarray is NOT enough — the JAX CPU backend aliases
    # aligned numpy buffers zero-copy; the h2h harness never hits this —
    # its reference side trains in spawned processes)
    params0 = jax.tree.map(np.array, ported)

    # identical explicit batch sequence, drawn once
    rng = np.random.default_rng(7)
    idxs = rng.integers(0, len(ds), (args.steps, args.batch))

    # ---- torch side: manual Adam loop ----
    opt = torch.optim.Adam(rmodel.parameters(), lr=1e-3)
    ref_losses = []
    for t in range(args.steps):
        x, y = ds.take(idxs[t])
        xb = torch.tensor(np.asarray(x, dtype=np.int64))
        yb = torch.tensor(np.asarray(y, dtype=np.int64))
        opt.zero_grad()
        loss = rmodel((xb, yb))
        loss.backward()
        opt.step()
        ref_losses.append(float(loss))
    ref_eval = torch_eval_loss_gpt(rmodel, TorchTokenDataset(ev_ds), block)

    # ---- gym_tpu side: jitted optax adam on the ported init ----
    import optax

    from gym_tpu.models.base import LossModel

    lm = LossModel(GPT(ocfg))
    tx = optax.adam(1e-3)
    params = params0
    opt_state = tx.init(params)
    key = jax.random.PRNGKey(0)  # dropout=0: never drawn

    @jax.jit
    def step(params, opt_state, batch):
        (loss, _), grads = jax.value_and_grad(
            lambda p: lm.loss(p, {}, batch, key, True), has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    our_losses = []
    for t in range(args.steps):
        x, y = ds.take(idxs[t])
        params, opt_state, loss = step(params, opt_state, (x, y))
        our_losses.append(float(loss))

    rng_e = np.random.default_rng(0)
    eidx = rng_e.integers(0, len(ev_ds), 64)
    ex, ey = ev_ds.take(eidx)
    our_eval = float(lm.loss(params, {}, (ex, ey),
                             jax.random.PRNGKey(0), False)[0])

    diffs = np.abs(np.array(ref_losses) - np.array(our_losses))
    probe = {str(t): round(float(diffs[t]), 7)
             for t in (0, 1, 2, 5, 9, 24, 49, args.steps - 1)
             if t < args.steps}
    out = {
        "config": "lockstep_1n_adam_gpt_small_docs",
        "steps": args.steps,
        "first10_max_abs_diff": round(float(diffs[:10].max()), 7),
        "step_abs_diff": probe,
        "final_train_abs_diff": round(float(diffs[-1]), 6),
        "final_eval_ref": round(ref_eval, 4),
        "final_eval_ours": round(our_eval, 4),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
