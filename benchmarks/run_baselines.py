"""BASELINE.json tracked configs, end to end.

Runs the five configurations the driver tracks (BASELINE.md):
  1. MNIST   2-node  SimpleReduce (AllReduce)
  2. MNIST   8-node  DiLoCo
  3. MNIST   8-node  SPARTA
  4. nanoGPT 16-node FedAvg   (docs-char: real offline English)
  5. nanoGPT 64-node DeMo     (docs-char)

and writes one JSON line per config plus `<log_dir>/baselines.json`
(default `logs/`). The reference's oracle is the same (SURVEY §4): final
loss + it/s of the exact example configurations — convergence, not unit
asserts.

Usage: python benchmarks/run_baselines.py [--steps N] [--device tpu|cpu]
           [--log_dir /tmp/smoke]   # keep smoke runs out of logs/
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np


def mnist_cfg(strategy_name, num_nodes, steps, lr=1e-3):
    from examples.mnist import load_mnist, make_strategy
    from gym_tpu.models import MnistLossModel

    return dict(
        name=f"mnist_{num_nodes}n_{strategy_name}",
        model=MnistLossModel(),
        train=load_mnist(True), val=load_mnist(False),
        strategy=make_strategy(strategy_name, lr),
        num_nodes=num_nodes, batch_size=256, minibatch_size=64,
        max_steps=steps,
    )


def gpt_cfg(strategy_name, num_nodes, steps):
    from gym_tpu.data import get_dataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy import (DeMoStrategy, FedAvgStrategy, OptimSpec)

    block = 256
    # "docs": real English text assembled offline (gym_tpu/data/offline.py);
    # round 1 used the synthetic shakespeare fallback here, which has no
    # resolution as a convergence oracle (VERDICT r1 weak #3)
    ds, vocab = get_dataset("docs", block, end_pc=0.9)
    val, _ = get_dataset("docs", block, start_pc=0.9)
    cfg = GPTConfig.gpt2_size_map("small")
    cfg.vocab_size, cfg.block_size = int(vocab), block
    sched = dict(lr_scheduler="lambda_cosine",
                 lr_scheduler_kwargs={"warmup_steps": min(100, steps // 5)})
    if strategy_name == "fedavg":
        strategy = FedAvgStrategy(
            inner_optim=OptimSpec("adamw", lr=3e-4), H=100, **sched)
    else:
        strategy = DeMoStrategy(
            optim_spec=OptimSpec("sgd", lr=1e-3),
            compression_topk=32, compression_chunk=64, **sched)
    return dict(
        name=f"nanogpt_{num_nodes}n_{strategy_name}",
        model=GPT(cfg), train=ds, val=val, strategy=strategy,
        num_nodes=num_nodes, batch_size=16, minibatch_size=16,
        max_steps=steps,
    )


def run_one(c, device, autocast, log_dir="logs"):
    from gym_tpu import Trainer

    res = Trainer(c["model"], c["train"], c["val"]).fit(
        strategy=c["strategy"], num_nodes=c["num_nodes"],
        max_steps=c["max_steps"], batch_size=c["batch_size"],
        minibatch_size=c["minibatch_size"], device=device,
        autocast=autocast, val_size=256,
        val_interval=max(1, c["max_steps"] // 4),
        show_progress=False, run_name=f"baseline_{c['name']}",
        log_dir=log_dir,
    )
    comm = sum(b for _, b in res.history["comm_bytes"])
    out = {
        "config": c["name"],
        "final_loss": round(res.final_train_loss, 4),
        "it_s": round(res.steps_per_second, 3),
        "steps": res.steps,
        "global_loss": round(res.history["global_loss"][-1][1], 4)
        if res.history["global_loss"] else None,
        "comm_gb_per_node": round(comm / 1e9, 3),
    }
    print(json.dumps(out))
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--gpt_steps", type=int, default=None)
    p.add_argument("--device", default=None)
    p.add_argument("--autocast", action="store_true")
    p.add_argument("--only", default=None,
                   help="substring filter on config names")
    p.add_argument("--log_dir", default="logs",
                   help="where run dirs + baselines.json go; point smoke "
                        "runs at a scratch dir so they don't clobber the "
                        "committed full-horizon evidence")
    args = p.parse_args()
    gpt_steps = args.gpt_steps or args.steps

    configs = [
        mnist_cfg("simple_reduce", 2, args.steps),
        mnist_cfg("diloco", 8, args.steps),
        mnist_cfg("sparta", 8, args.steps),
        gpt_cfg("fedavg", 16, gpt_steps),
        gpt_cfg("demo", 64, gpt_steps),
    ]
    results = []
    for c in configs:
        if args.only and args.only not in c["name"]:
            continue
        results.append(run_one(c, args.device, args.autocast,
                               args.log_dir))
    os.makedirs(args.log_dir, exist_ok=True)
    with open(os.path.join(args.log_dir, "baselines.json"), "w") as f:
        json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
