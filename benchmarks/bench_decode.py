"""Autoregressive decode throughput: full-context resampling (reference
``generate`` semantics, ``example/nanogpt/nanogpt.py:410-439``) vs the
KV-cache ``generate_fast`` path.

Usage: python benchmarks/bench_decode.py [--size base] [--tokens 256]
Prints one JSON line per sampler.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="small",
                    choices=["small", "base", "medium"])
    ap.add_argument("--block", type=int, default=None)
    ap.add_argument("--tokens", type=int, default=256)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--skip-slow", action="store_true")
    args = ap.parse_args()

    import dataclasses

    import jax
    import numpy as np

    from gym_tpu.models.nanogpt import (GPT, GPTConfig, generate,
                                        generate_fast)

    cfg = GPTConfig.gpt2_size_map(args.size)
    if args.block:
        cfg = dataclasses.replace(cfg, block_size=args.block)
    cfg = dataclasses.replace(cfg, dropout=0.0)
    model = GPT(cfg)
    rng = jax.random.PRNGKey(0)
    prompt = np.asarray(jax.random.randint(
        rng, (args.batch, 16), 0, cfg.vocab_size))
    params = model.init({"params": rng}, jnp_prompt(prompt), train=False)[
        "params"]

    def padded_full_context(params, cfg, prompt, n_tokens, top_k, seed):
        """Best static-shape rendering of the reference's sampler: re-run
        the FULL (block_size-padded) context every token — one compile,
        O(block²) attention per token. (The literal reference semantics —
        context grows by one each step — would recompile per length under
        XLA: n_tokens compiles. This baseline is strictly faster.)"""
        import jax.numpy as jnp

        model = GPT(cfg)
        S = cfg.block_size

        @jax.jit
        def step(params, buf, pos, key):
            logits = model.apply({"params": params}, buf, train=False)
            lg = jnp.take_along_axis(
                logits, pos[None, None, None].repeat(buf.shape[0], 0),
                axis=1)[:, 0].astype(jnp.float32)
            kth = jax.lax.top_k(lg, top_k)[0][..., -1]
            lg = jnp.where(lg < kth[:, None], -jnp.inf, lg)
            return jax.random.categorical(key, lg, axis=-1)

        buf = np.zeros((prompt.shape[0], S), np.int32)
        buf[:, :prompt.shape[1]] = prompt
        buf = jnp_prompt(buf)
        key = jax.random.PRNGKey(seed)
        pos = prompt.shape[1] - 1
        for _ in range(n_tokens):
            key, sub = jax.random.split(key)
            nxt = step(params, buf, jnp_prompt(np.int32(pos)), sub)
            pos += 1
            buf = buf.at[:, pos].set(nxt)
        return np.asarray(buf[:, :pos + 1])

    def run_fast(params, cfg, prompt, n, top_k, seed):
        return generate_fast(params, cfg, prompt, n, top_k=top_k,
                             seed=seed)

    results = []
    samplers = [("kv_cache", run_fast)]
    if not args.skip_slow:
        samplers.append(("full_context_padded", padded_full_context))
    for name, fn in samplers:
        fn(params, cfg, prompt, args.tokens, 5, 0)  # warmup/compile
        t0 = time.perf_counter()
        out = fn(params, cfg, prompt, args.tokens, 5, 0)
        dt = time.perf_counter() - t0
        assert out.shape == (args.batch, 16 + args.tokens)
        tps = args.batch * args.tokens / dt
        row = {"metric": f"decode_{name}_tokens_per_sec",
               "value": round(tps, 1), "unit": "tok/s",
               "size": args.size, "block": cfg.block_size,
               "new_tokens": args.tokens, "batch": args.batch,
               "platform": jax.devices()[0].platform}
        print(json.dumps(row))
        results.append(row)

    if len(results) == 2:
        print(json.dumps({
            "metric": "decode_speedup",
            "value": round(results[0]["value"] / results[1]["value"], 2),
            "unit": "x",
        }))


def jnp_prompt(p):
    import jax.numpy as jnp
    return jnp.asarray(p)


if __name__ == "__main__":
    main()
