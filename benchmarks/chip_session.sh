#!/bin/sh
# Round-5 chip session: run the pending on-device measurements in priority
# order the moment the accelerator tunnel is back. Each step appends its
# log under logs/chip_r5/; a step failing must not block the next.
# Priorities mirror VERDICT r4 items 2 (headline + GPT-2-base rider),
# 3 (DeMo 64n vnode-decode payoff), 5 (zig-zag ring step time), and
# 7 (MoE ragged batch 16).
set -x
cd "$(dirname "$0")/.." || exit 1
mkdir -p logs/chip_r5

# 1. headline + GPT-2-base rider (BENCH_r05 material)
python bench.py > logs/chip_r5/bench_headline.json 2> logs/chip_r5/bench_headline.err

# 2. DeMo tracked 64-node config (vnode-decode payoff; profile in a 3rd fit)
python benchmarks/bench_demo_64n.py --steps 12 --profile \
  > logs/chip_r5/demo64.log 2>&1

# 3. long-context kernel scaling regression on the chip (NB: the zig-zag
# cp A/B needs >=2 devices; one chip cannot run it — the CPU-mesh A/B in
# BENCHMARKS.md is the round's layout evidence)
python benchmarks/bench_long_context.py --mode kernel \
  > logs/chip_r5/kernel_scaling.log 2>&1
python benchmarks/bench_long_context.py --mode ring_chip \
  > logs/chip_r5/ring_chip.log 2>&1

# 4. MoE GPT-2 base batch 16 on the chunked ragged path (r4 ceiling was 12)
python benchmarks/bench_gpt2_base.py --n-experts 8 --batch 16 \
  > logs/chip_r5/moe_b16.log 2>&1
python benchmarks/bench_gpt2_base.py --n-experts 8 --batch 12 \
  > logs/chip_r5/moe_b12.log 2>&1

echo DONE
