"""GPT-2 base (124M param) training-step benchmark — perf at realistic scale.

The reference's benchmark model family tops out at its published MNIST table
(``/root/reference/README.md:104-112``); its GPT sizes
(``example/nanogpt/nanogpt.py:160-165``) were never benchmarked. This script
measures our framework's step time and **MFU** on GPT-2 base
(12L/12H/768, block 1024, vocab 50304) — the realistic-scale proof the
round-1 verdict asked for.

Usage (real TPU):
    python benchmarks/bench_gpt2_base.py --batch 8 --steps 20
    python benchmarks/bench_gpt2_base.py --nodes 4 --attn flash --remat

Prints one JSON line with it/s, tokens/s and MFU, and appends the result to
``logs/bench_gpt2_base.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="base",
                    choices=["small", "base", "medium", "large", "xl"])
    ap.add_argument("--nodes", type=int, default=1)
    ap.add_argument("--batch", type=int, default=8,
                    help="per-node batch size (sequences)")
    ap.add_argument("--block", type=int, default=1024)
    ap.add_argument("--attn", default="flash", choices=["dense", "flash"])
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--no-bf16", action="store_true")
    ap.add_argument("--strategy", default="diloco",
                    choices=["diloco", "simple", "demo"])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--spc", type=int, default=5,
                    help="steps per dispatch (scan)")
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="bf16 peak of the chip (v5e: 197)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default="logs/bench_gpt2_base.jsonl")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from gym_tpu.models.base import LossModel
    from gym_tpu.models.nanogpt import GPT, GPTConfig, node_mfu
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_multi_train_step

    cfg = dataclasses.replace(
        GPTConfig.gpt2_size_map(args.size),
        block_size=args.block, dropout=0.0,
        attn_impl=args.attn, remat=args.remat,
    )
    loss_model = LossModel(GPT(cfg), None if args.no_bf16 else jnp.bfloat16)

    if args.strategy == "diloco":
        strategy = DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=3e-4),
                                  H=100)
    elif args.strategy == "demo":
        from gym_tpu.strategy.demo import DeMoStrategy
        strategy = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=1e-3))
    else:
        strategy = SimpleReduceStrategy(OptimSpec("adamw", lr=3e-4))

    spc = args.spc
    warm_calls = max(1, args.warmup // spc + (args.warmup % spc > 0))
    timed_calls = max(1, args.steps // spc)
    strategy.finalize(max_steps=(warm_calls + timed_calls) * spc)

    runtime = NodeRuntime.create(args.nodes, jax.devices())

    rng = np.random.default_rng(0)
    idx = rng.integers(
        0, cfg.vocab_size,
        (args.nodes, spc, 1, args.batch, args.block), dtype=np.int64,
    )
    batches = runtime.shard_batch((idx, np.roll(idx, -1, axis=-1)))

    init_fn = make_init_fn(loss_model, strategy,
                           (idx[0, 0, 0], idx[0, 0, 0]), seed=42)
    state = runtime.init_state(init_fn)
    multi_step = runtime.compile(
        make_multi_train_step(loss_model, strategy, runtime.ctx)
    )

    t_compile = time.perf_counter()
    for _ in range(warm_calls):
        state, metrics = multi_step(state, batches)
    # fetch a chained value as the execution fence (axon transport:
    # block_until_ready resolves early; see .claude/skills/verify)
    float(np.asarray(metrics["loss"]).sum())
    t_compile = time.perf_counter() - t_compile

    t0 = time.perf_counter()
    for _ in range(timed_calls):
        state, metrics = multi_step(state, batches)
    loss = float(np.asarray(metrics["loss"]).mean())
    dt = time.perf_counter() - t0

    steps = timed_calls * spc
    it_s = steps / dt
    assert np.isfinite(loss), f"non-finite loss {loss}"

    seqs_per_iter = args.batch * args.nodes
    mfu = node_mfu(cfg, state.params, seqs_per_iter, dt / steps,
                   peak_flops=args.peak_tflops * 1e12)
    tokens_s = seqs_per_iter * args.block * it_s

    result = {
        "metric": f"gpt2_{args.size}_it_per_sec",
        "value": round(it_s, 3),
        "unit": "it/s",
        "mfu": round(mfu, 4),
        "tokens_per_sec": round(tokens_s, 1),
        "loss": round(loss, 4),
        "nodes": args.nodes,
        "batch_per_node": args.batch,
        "block": args.block,
        "attn": args.attn,
        "remat": args.remat,
        "bf16": not args.no_bf16,
        "strategy": args.strategy,
        "warmup_s": round(t_compile, 1),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result))
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "a") as f:
        f.write(json.dumps(result) + "\n")


if __name__ == "__main__":
    main()
