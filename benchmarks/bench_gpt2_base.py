"""GPT-2 base (124M param) training-step benchmark — perf at realistic scale.

The reference's benchmark model family tops out at its published MNIST table
(``/root/reference/README.md:104-112``); its GPT sizes
(``example/nanogpt/nanogpt.py:160-165``) were never benchmarked. This script
measures our framework's step time and **MFU** on GPT-2 base
(12L/12H/768, block 1024, vocab 50304) — the realistic-scale proof the
round-1 verdict asked for.

Usage (real TPU):
    python benchmarks/bench_gpt2_base.py --batch 8 --steps 20
    python benchmarks/bench_gpt2_base.py --nodes 4 --attn flash --remat

Prints one JSON line with it/s, tokens/s and MFU, and appends the result to
``logs/bench_gpt2_base.jsonl``. ``measure()`` is importable — the repo-root
``bench.py`` reuses it for its realistic-scale rider so the two published
numbers can't drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def measure(size: str = "base", nodes: int = 1, batch: int = 8,
            block: int = 1024, attn: str = "flash", remat: bool = False,
            bf16: bool = True, strategy: str = "diloco", steps: int = 20,
            warmup: int = 3, spc: int = 5,
            peak_tflops: float = 197.0, shard_outer: bool = False,
            n_experts: int = 0, expert_topk: int = 2,
            moe_impl: str = "auto", loss_chunk: int = 0,
            demo_delta_bf16: bool = False) -> dict:
    """Build the GPT-2 ``size`` model, run ``steps`` training steps with
    ``strategy`` over ``nodes`` simulated nodes and return the measured
    {it/s, MFU, tokens/s, loss, ...} dict. Raises on OOM/compile failure
    — callers that must survive (bench.py's rider) catch."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gym_tpu.models.base import LossModel
    from gym_tpu.models.nanogpt import GPT, GPTConfig, node_mfu
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_multi_train_step

    cfg = dataclasses.replace(
        GPTConfig.gpt2_size_map(size),
        block_size=block, dropout=0.0, attn_impl=attn, remat=remat,
        n_experts=n_experts, expert_topk=expert_topk, moe_impl=moe_impl,
        loss_chunk=loss_chunk,
    )
    loss_model = LossModel(GPT(cfg), jnp.bfloat16 if bf16 else None)

    if strategy == "diloco":
        strat = DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=3e-4),
                               H=100, shard_outer=shard_outer)
    elif strategy == "zero":
        from gym_tpu.strategy.zero_reduce import ZeroReduceStrategy
        strat = ZeroReduceStrategy(OptimSpec("adamw", lr=3e-4))
    elif strategy == "demo":
        from gym_tpu.strategy.demo import DeMoStrategy
        strat = DeMoStrategy(
            optim_spec=OptimSpec("sgd", lr=1e-3),
            delta_dtype=jnp.bfloat16 if demo_delta_bf16 else None)
    else:
        strat = SimpleReduceStrategy(OptimSpec("adamw", lr=3e-4))

    warm_calls = max(1, warmup // spc + (warmup % spc > 0))
    timed_calls = max(1, steps // spc)
    strat.finalize(max_steps=(warm_calls + timed_calls) * spc)

    runtime = NodeRuntime.create(nodes, jax.devices())

    rng = np.random.default_rng(0)
    idx = rng.integers(
        0, cfg.vocab_size,
        (nodes, spc, 1, batch, cfg.block_size), dtype=np.int64,
    )
    batches = runtime.shard_batch((idx, np.roll(idx, -1, axis=-1)))

    init_fn = make_init_fn(loss_model, strat,
                           (idx[0, 0, 0], idx[0, 0, 0]), seed=42,
                           ctx=runtime.ctx)
    state = runtime.init_state(init_fn)
    multi_step = runtime.compile(
        make_multi_train_step(loss_model, strat, runtime.ctx)
    )

    t_compile = time.perf_counter()
    for _ in range(warm_calls):
        state, metrics = multi_step(state, batches)
    # fetch a chained value as the execution fence (axon transport:
    # block_until_ready resolves early; see .claude/skills/verify)
    float(np.asarray(metrics["loss"]).sum())
    t_compile = time.perf_counter() - t_compile

    t0 = time.perf_counter()
    for _ in range(timed_calls):
        state, metrics = multi_step(state, batches)
    loss = float(np.asarray(metrics["loss"]).mean())
    dt = time.perf_counter() - t0

    n_steps = timed_calls * spc
    it_s = n_steps / dt
    assert np.isfinite(loss), f"non-finite loss {loss}"

    seqs_per_iter = batch * nodes
    mfu = node_mfu(cfg, state.params, seqs_per_iter, dt / n_steps,
                   peak_flops=peak_tflops * 1e12)

    result_metric = (f"gpt2_{size}_moe{n_experts}_it_per_sec" if n_experts
                     else f"gpt2_{size}_it_per_sec")
    return {
        "metric": result_metric,
        **({"n_experts": n_experts, "expert_topk": expert_topk,
            "moe_impl": moe_impl} if n_experts else {}),
        "value": round(it_s, 3),
        "unit": "it/s",
        "mfu": round(mfu, 4),
        "tokens_per_sec": round(seqs_per_iter * cfg.block_size * it_s, 1),
        "loss": round(loss, 4),
        "nodes": nodes,
        "batch_per_node": batch,
        "block": cfg.block_size,
        "attn": attn,
        "remat": remat,
        "bf16": bf16,
        "strategy": strategy + ("+shard_outer" if shard_outer
                                and strategy == "diloco" else ""),
        **({"loss_chunk": loss_chunk} if loss_chunk else {}),
        **({"demo_delta_bf16": True} if demo_delta_bf16 else {}),
        "warmup_s": round(t_compile, 1),
        "platform": jax.devices()[0].platform,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="base",
                    choices=["small", "base", "medium", "large", "xl"])
    ap.add_argument("--nodes", type=int, default=1)
    ap.add_argument("--batch", type=int, default=8,
                    help="per-node batch size (sequences)")
    ap.add_argument("--block", type=int, default=1024)
    ap.add_argument("--attn", default="flash", choices=["dense", "flash"])
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--shard-outer", action="store_true",
                    help="DiLoCo: ZeRO-shard the outer master/momentum")
    ap.add_argument("--no-bf16", action="store_true")
    ap.add_argument("--strategy", default="diloco",
                    choices=["diloco", "simple", "demo", "zero"])
    ap.add_argument("--n-experts", type=int, default=0,
                    help="MoE: experts per MoE block (0 = dense)")
    ap.add_argument("--expert-topk", type=int, default=2)
    ap.add_argument("--moe-impl", default="auto",
                    choices=["auto", "ragged", "einsum", "dense"])
    ap.add_argument("--demo-delta-bf16", action="store_true",
                    help="DeMo: store the momentum residual + staged "
                         "grads in bf16 (halves strategy state memory)")
    ap.add_argument("--loss-chunk", type=int, default=0,
                    help="chunked cross-entropy rows (0 = one-shot logits;"
                         " needed to fit many-node vmapped simulators)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--spc", type=int, default=5,
                    help="steps per dispatch (scan)")
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="bf16 peak of the chip (v5e: 197)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default="logs/bench_gpt2_base.jsonl")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    result = measure(size=args.size, nodes=args.nodes, batch=args.batch,
                     block=args.block, attn=args.attn, remat=args.remat,
                     bf16=not args.no_bf16, strategy=args.strategy,
                     steps=args.steps, warmup=args.warmup, spc=args.spc,
                     peak_tflops=args.peak_tflops,
                     shard_outer=args.shard_outer,
                     n_experts=args.n_experts, expert_topk=args.expert_topk,
                     moe_impl=args.moe_impl, loss_chunk=args.loss_chunk,
                     demo_delta_bf16=args.demo_delta_bf16)
    print(json.dumps(result))
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "a") as f:
        f.write(json.dumps(result) + "\n")


if __name__ == "__main__":
    main()
