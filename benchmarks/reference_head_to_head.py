"""Same-data, IDENTICAL-INIT head-to-head: the ACTUAL reference (EXO
Gym, torch + gloo, CPU) vs gym_tpu, on identical offline datasets.

VERDICT r2 #5 / r3 #3: the strongest form of the reference's own oracle
(SURVEY §4) needs zero network — run `/root/reference` itself on the
offline digits / docs-char data at the tracked configs and table final
losses side by side. Both frameworks consume byte-identical training
arrays AND byte-identical initial weights: the torch model is built
first and its state_dict ported into a flax tree (conv/linear layout
transposes), which ``Trainer.fit(init_params=...)`` starts from — the
reference side trains whatever the passed module holds, so no hook is
needed there. The remaining noise is data order + dropout draws only;
the per-config ``band`` field measures it as the spread of two gym_tpu
runs from the same init with different data seeds, and the cross-
framework gap must sit inside ~2 bands.

Configs (BASELINE.md tracked trio + one GPT config):
  digits  2n SimpleReduce · 8n DiLoCo(H=50) · 8n SPARTA(p=0.005)
  docs-char 4n DiLoCo(H=50) GPT "small" (block 64)

Usage:  python benchmarks/reference_head_to_head.py
            [--steps N] [--gpt_steps N] [--only substr] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
REF = "/root/reference"
sys.path.insert(0, REPO)
if REF not in sys.path:
    sys.path.insert(0, REF)

# 8 virtual CPU devices for the gym_tpu side; must precede jax import
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

import numpy as np


# -- shared data -------------------------------------------------------------


def digits_arrays():
    """Deterministic (unaugmented) digits train/eval splits — the same
    numpy arrays feed both frameworks."""
    from gym_tpu.data.offline import load_digits_mnist

    tr = load_digits_mnist(True, augment=False)
    ev = load_digits_mnist(False)
    return (tr.arrays[0], tr.arrays[1]), (ev.arrays[0], ev.arrays[1])


def docs_tokens(block: int):
    """The docs-char token stream both frameworks window over."""
    from gym_tpu.data import get_dataset

    ds, vocab = get_dataset("docs", block, end_pc=0.9)
    ev, _ = get_dataset("docs", block, start_pc=0.9)
    return ds, ev, int(vocab)


# -- torch side (the reference) ---------------------------------------------


try:
    import torch as _torch
    import torch.nn as _tnn
    import torch.nn.functional as _tF
except ImportError:  # pragma: no cover
    _torch = None


def _cnn_block(cin, cout):
    return [_tnn.Conv2d(cin, cout, 3, padding=1), _tnn.BatchNorm2d(cout),
            _tnn.ReLU(),
            _tnn.Conv2d(cout, cout, 3, padding=1), _tnn.BatchNorm2d(cout),
            _tnn.ReLU(), _tnn.MaxPool2d(2), _tnn.Dropout2d(0.25)]


class TorchCNNWrapper(_tnn.Module if _torch else object):
    """torch mirror of gym_tpu/models/mnist_cnn.py (itself the reference
    example's architecture): two conv blocks (64, 128; 3x3 convs + BN +
    ReLU x2, maxpool, Dropout2d 0.25) -> Linear 256 -> Dropout 0.5 ->
    Linear 10, wrapped as forward(batch) -> cross-entropy. Module-level
    (mp.spawn pickles the model)."""

    def __init__(self):
        super().__init__()
        self.net = _tnn.Sequential(
            *_cnn_block(1, 64), *_cnn_block(64, 128), _tnn.Flatten(),
            _tnn.Linear(128 * 7 * 7, 256), _tnn.ReLU(), _tnn.Dropout(0.5),
            _tnn.Linear(256, 10))

    def forward(self, batch):
        imgs, labels = batch
        return _tF.cross_entropy(self.net(imgs), labels)


def torch_cnn():
    return TorchCNNWrapper()


class TorchArrayDataset:
    """(x, y) tuples from numpy arrays, NCHW images."""

    def __init__(self, imgs_nhwc, labels):
        import torch
        self.x = torch.tensor(np.transpose(imgs_nhwc, (0, 3, 1, 2)))
        self.y = torch.tensor(labels.astype(np.int64))

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class TorchTokenDataset:
    """Contiguous (x, y) int64 blocks over a token stream — the torch
    twin of gym_tpu ContiguousGPTTrainDataset.

    ``order_seed``: permutes the index→window mapping (same window SET,
    different draw of data order). The reference's DistributedSampler is
    deterministically seeded, so this is the only fair way to measure the
    reference's own data-order noise band — the r5 lockstep ablation
    proved the per-step optimizer math identical, leaving data order as
    the sole noise source in the head-to-head."""

    def __init__(self, ours, order_seed: int = 0):
        import torch
        self.data = torch.tensor(np.asarray(ours.data, dtype=np.int64))
        self.block = ours.block_size
        self.perm = (np.random.default_rng(order_seed).permutation(len(self))
                     if order_seed else None)

    def __len__(self):
        return len(self.data) - self.block - 1

    def __getitem__(self, i):
        if self.perm is not None:
            i = int(self.perm[i])
        x = self.data[i:i + self.block]
        y = self.data[i + 1:i + self.block + 1]
        return x, y


def ref_strategy(name: str):
    import torch
    from exogym.strategy.diloco import DiLoCoStrategy
    from exogym.strategy.optim import OptimSpec
    from exogym.strategy.sparta import SPARTAStrategy
    from exogym.strategy.strategy import SimpleReduceStrategy

    optim = OptimSpec(torch.optim.Adam, lr=1e-3)
    return {
        "simple_reduce": lambda: SimpleReduceStrategy(optim_spec=optim),
        "diloco": lambda: DiLoCoStrategy(optim_spec=optim, H=50),
        "sparta": lambda: SPARTAStrategy(inner_optim=optim, p_sparta=0.005),
    }[name]()


def run_reference(model, train_ds, val_ds, strategy, num_nodes, steps,
                  batch, port):
    from exogym.trainer import LocalTrainer

    trainer = LocalTrainer(model, train_ds, val_ds, start_port=port)
    final = trainer.fit(
        num_epochs=1, strategy=strategy, num_nodes=num_nodes,
        max_steps=steps, device="cpu", batch_size=batch,
        minibatch_size=batch, val_size=max(256, batch),
        val_interval=max(1, steps // 2), run_name="h2h",
        log_dir="/tmp/h2h_ref_logs",
    )
    return final


def torch_eval_loss(model, ds, n=1024, batch=256):
    import torch
    model.eval()
    tot, cnt = 0.0, 0
    with torch.no_grad():
        for lo in range(0, min(n, len(ds)), batch):
            items = [ds[i] for i in range(lo, min(lo + batch, n, len(ds)))]
            xs = torch.stack([a for a, _ in items])
            ys = torch.stack([b for _, b in items])
            tot += float(model((xs, ys))) * len(items)
            cnt += len(items)
    return tot / cnt


# -- torch → flax weight porting (identical-init, VERDICT r3 #3) -------------


def port_torch_cnn(model) -> dict:
    """TorchCNNWrapper state_dict → MnistLossModel flax param tree.

    Layout transposes: conv [out, in, kh, kw] → [kh, kw, in, out]; the
    flatten boundary differs (torch NCHW flattens C-major, flax NHWC
    flattens H-major) so the first Linear's kernel is permuted through
    [out, C, H, W] → [H, W, C, out]; plain Linear transposes. BN running
    stats are fresh zeros/ones in both frameworks at init — only params
    port."""
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}

    def conv(i):
        return {"kernel": np.transpose(sd[f"net.{i}.weight"], (2, 3, 1, 0)),
                "bias": sd[f"net.{i}.bias"]}

    def bn(i):
        return {"scale": sd[f"net.{i}.weight"], "bias": sd[f"net.{i}.bias"]}

    w17 = sd["net.17.weight"]                       # [256, 128*7*7] C-major
    dense0 = {"kernel": np.transpose(
        w17.reshape(256, 128, 7, 7), (2, 3, 1, 0)).reshape(-1, 256),
        "bias": sd["net.17.bias"]}
    dense1 = {"kernel": sd["net.20.weight"].T, "bias": sd["net.20.bias"]}
    return {"CNN_0": {
        "Conv_0": conv(0), "BatchNorm_0": bn(1),
        "Conv_1": conv(3), "BatchNorm_1": bn(4),
        "Conv_2": conv(8), "BatchNorm_2": bn(9),
        "Conv_3": conv(11), "BatchNorm_3": bn(12),
        "Dense_0": dense0, "Dense_1": dense1,
    }}


def port_torch_gpt(ref_model, n_layer):
    """Reuse the parity test's porter (tests/test_reference_parity.py)."""
    tests_dir = os.path.join(REPO, "tests")
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    from test_reference_parity import _port_weights
    return _port_weights(ref_model, n_layer)


# -- gym_tpu side ------------------------------------------------------------


def run_ours(model, train_ds, val_ds, strategy, num_nodes, steps, batch,
             init_params=None, seed=42, device=None):
    """device=None: the default accelerator (the chip when present — a
    K-node fold on one device; the single host core crawls at ~20 s/step
    on the CNN mesh). The comparison is mathematical, not hardware."""
    from gym_tpu import Trainer

    return Trainer(model, train_ds, val_ds).fit(
        strategy=strategy, num_nodes=num_nodes, max_steps=steps,
        batch_size=batch, minibatch_size=batch,
        val_size=256, val_interval=max(1, steps // 2),
        show_progress=False, run_name="h2h", log_dir="/tmp/h2h_logs",
        init_params=init_params, seed=seed, device=device,
    )


def ours_strategy(name: str):
    from gym_tpu.strategy import (DiLoCoStrategy, OptimSpec,
                                  SimpleReduceStrategy, SPARTAStrategy)

    optim = OptimSpec("adam", lr=1e-3)
    return {
        "simple_reduce": lambda: SimpleReduceStrategy(optim),
        "diloco": lambda: DiLoCoStrategy(optim, H=50),
        "sparta": lambda: SPARTAStrategy(optim, p_sparta=0.005),
    }[name]()


def ours_eval_loss_mnist(res, ev):
    import jax
    from gym_tpu.models import MnistLossModel
    from gym_tpu.models.base import LossModel

    lm = LossModel(MnistLossModel())
    imgs, labels = ev
    tot, cnt = 0.0, 0
    for lo in range(0, min(1024, len(imgs)), 256):
        mb = (imgs[lo:lo + 256], labels[lo:lo + 256])
        loss, _ = lm.loss(res.params, res.model_state, mb,
                          jax.random.PRNGKey(0), False)
        tot += float(loss) * len(mb[1])
        cnt += len(mb[1])
    return tot / cnt


def ours_eval_loss_gpt(res, ev, model):
    import jax
    from gym_tpu.models.base import LossModel

    lm = LossModel(model)
    rng = np.random.default_rng(0)
    idxs = rng.integers(0, len(ev), 64)
    xs, ys = ev.take(idxs)
    loss, _ = lm.loss(res.params, res.model_state, (xs, ys),
                      jax.random.PRNGKey(0), False)
    return float(loss)


def torch_eval_loss_gpt(model, ds, block):
    import torch
    model.eval()
    rng = np.random.default_rng(0)
    idxs = rng.integers(0, len(ds), 64)
    with torch.no_grad():
        xs = torch.stack([ds[i][0] for i in idxs])
        ys = torch.stack([ds[i][1] for i in idxs])
        return float(model((xs, ys)))


# -- configs -----------------------------------------------------------------


def main():
    ap = argparse.ArgumentParser()
    # defaults reproduce BENCHMARKS.md "Head-to-head" exactly
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--gpt_steps", type=int, default=100)
    ap.add_argument("--band_seeds", type=int, default=2,
                    help="gym_tpu runs (data seeds 42..42+N-1) whose "
                         "max-min loss spread is the band; 2 reproduces "
                         "the historic band, >=4 gives a spread that a "
                         "2-sigma-ish cross-framework gap can be judged "
                         "against honestly (VERDICT r4 #4)")
    ap.add_argument("--ref_orders", type=int, default=1,
                    help="reference-side GPT runs with index-permuted "
                         "train windows (same window set, different data "
                         "order) — measures the reference's OWN "
                         "data-order band, which its deterministically "
                         "seeded DistributedSampler otherwise hides")
    ap.add_argument("--only", default=None)
    ap.add_argument("--out", default="logs/head_to_head.json")
    ap.add_argument("--device", default=None,
                    help="device for the gym_tpu side (cpu when the chip "
                         "tunnel is down; the comparison is mathematical)")
    args = ap.parse_args()

    if args.device == "cpu":
        # pin the DEFAULT backend too: with the accelerator tunnel down,
        # any stray default-backend touch (jnp.asarray in the weight
        # porters) would hang on the dead axon transport
        import jax
        jax.config.update("jax_platforms", "cpu")

    results = []
    port = 29811

    mnist_cfgs = [("simple_reduce", 2), ("diloco", 8), ("sparta", 8)]
    (tr_imgs, tr_labels), ev = digits_arrays()
    from gym_tpu.data.sampler import ArrayDataset

    for name, nodes in mnist_cfgs:
        cfg_name = f"digits_{nodes}n_{name}"
        if args.only and args.only not in cfg_name:
            continue
        port += 1
        # identical init: the torch model's weights are the run's weights
        import torch
        torch.manual_seed(100)
        model0 = torch_cnn()
        ported = port_torch_cnn(model0)
        print(f"=== {cfg_name} (reference) ===", flush=True)
        ref_model = run_reference(
            model0, TorchArrayDataset(tr_imgs, tr_labels),
            TorchArrayDataset(ev[0], ev[1]), ref_strategy(name),
            nodes, args.steps, 64, port)
        ref_loss = torch_eval_loss(ref_model, TorchArrayDataset(*ev))
        print(f"=== {cfg_name} (gym_tpu) ===", flush=True)
        from gym_tpu.models import MnistLossModel
        res = run_ours(MnistLossModel(), ArrayDataset(tr_imgs, tr_labels),
                       ArrayDataset(*ev), ours_strategy(name), nodes,
                       args.steps, 64, init_params=ported, seed=42,
                       device=args.device)
        our_loss = ours_eval_loss_mnist(res, ev)
        # band: same init, different data seed — the residual noise the
        # cross-framework gap is judged against (data order + dropout)
        res_b = run_ours(MnistLossModel(), ArrayDataset(tr_imgs, tr_labels),
                         ArrayDataset(*ev), ours_strategy(name), nodes,
                         args.steps, 64, init_params=ported, seed=43,
                         device=args.device)
        band = abs(our_loss - ours_eval_loss_mnist(res_b, ev))
        results.append({"config": cfg_name, "reference_loss":
                        round(ref_loss, 4), "gym_tpu_loss":
                        round(our_loss, 4), "band": round(band, 4),
                        "identical_init": True})
        print(json.dumps(results[-1]), flush=True)

    cfg_name = "docs_4n_diloco_gpt_small"
    if not args.only or args.only in cfg_name:
        import torch
        from example.nanogpt.nanogpt import GPT as RefGPT
        from example.nanogpt.nanogpt import GPTConfig as RefConfig

        from gym_tpu.models.nanogpt import GPT, GPTConfig

        block = 64
        ds, ev_ds, vocab = docs_tokens(block)
        rcfg = RefConfig(block_size=block, vocab_size=vocab, n_layer=4,
                         n_head=4, n_embd=128, dropout=0.0, bias=True)
        ocfg = GPTConfig(block_size=block, vocab_size=vocab, n_layer=4,
                         n_head=4, n_embd=128, dropout=0.0, bias=True)
        torch.manual_seed(100)
        rmodel = RefGPT(rcfg)
        ported = port_torch_gpt(rmodel, ocfg.n_layer)
        ref_losses = []
        for order in range(max(1, args.ref_orders)):
            port += 1
            # identical init for every order draw
            torch.manual_seed(100)
            rmodel = RefGPT(rcfg)
            print(f"=== {cfg_name} (reference, order {order}) ===",
                  flush=True)
            tds = TorchTokenDataset(ds, order_seed=order)
            ref_model = run_reference(
                rmodel, tds, TorchTokenDataset(ev_ds),
                ref_strategy("diloco"), 4, args.gpt_steps, 8, port)
            ref_losses.append(
                torch_eval_loss_gpt(ref_model, TorchTokenDataset(ev_ds),
                                    block))
            print(f"  order {order}: {ref_losses[-1]:.4f}", flush=True)
        ref_loss = ref_losses[0]
        print(f"=== {cfg_name} (gym_tpu) ===", flush=True)
        losses = []
        for s in range(max(2, args.band_seeds)):
            res = run_ours(GPT(ocfg), ds, ev_ds, ours_strategy("diloco"), 4,
                           args.gpt_steps, 8, init_params=ported,
                           seed=42 + s, device=args.device)
            losses.append(ours_eval_loss_gpt(res, ev_ds, GPT(ocfg)))
            print(f"  seed {42 + s}: {losses[-1]:.4f}", flush=True)
        our_loss = losses[0]
        band = max(losses) - min(losses)
        row = {"config": cfg_name, "reference_loss": round(ref_loss, 4),
               "gym_tpu_loss": round(our_loss, 4), "band": round(band, 4),
               "band_seeds": len(losses),
               "gym_tpu_losses": [round(l, 4) for l in losses],
               "identical_init": True}
        if len(ref_losses) > 1:
            row["reference_losses"] = [round(l, 4) for l in ref_losses]
            row["reference_band"] = round(max(ref_losses) - min(ref_losses),
                                          4)
            # honest cross-framework statistics from both sides' raw
            # runs: gap of means, each side's mean, and whether the two
            # samples' ranges overlap at all (rank separation at n+n is
            # the strongest small-sample signal of a residual offset —
            # a pooled max−min would be ≥ the mean gap BY CONSTRUCTION
            # and can never flag a violation, so it is not reported)
            rm = sum(ref_losses) / len(ref_losses)
            om = sum(losses) / len(losses)
            row["gap_of_means"] = round(abs(rm - om), 4)
            row["reference_mean"] = round(rm, 4)
            row["gym_tpu_mean"] = round(om, 4)
            row["ranges_overlap"] = bool(
                max(losses) >= min(ref_losses)
                and max(ref_losses) >= min(losses))
        results.append(row)
        print(json.dumps(results[-1]), flush=True)

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
