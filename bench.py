"""Benchmark: nanoGPT DiLoCo at 64 simulated nodes (the BASELINE.json
north-star config — ``example/nanogpt.py`` with ``--strategy diloco``,
64 nodes) on the current accelerator.

Prints ONE JSON line:
    {"metric": ..., "value": it/s, "unit": "it/s", "vs_baseline": ...}

``vs_baseline`` is measured it/s divided by the CPU it/s of the *same*
workload (the north star is ">=10x CPU iterations/sec"). The CPU number is
re-measurable with ``python bench.py --cpu`` and overridable via
``GYM_TPU_BENCH_BASELINE``.

Failure is structured (round-4 lesson: a dead accelerator tunnel produced
``rc=1, parsed:null`` — a 40-line traceback indistinguishable from a
broken bench).  A supervisor process first probes backend init in a
subprocess under a short timeout (init *hangs*, not just raises, when the
transport site hook's tunnel is down), then runs the measurement under a
watchdog; every failure path prints ONE JSON line:
    {"error": "tpu_unavailable" | "bench_failure", "detail": ..., "tail": ...}
``tpu_unavailable`` exits 0 (the bench behaved; the chip was absent);
``bench_failure`` exits 1 (the bench itself is broken — investigate).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Short: just backend init + device enumeration. The sick-tunnel failure
# mode is a silent block with ~0 CPU, so a generous-but-bounded timeout is
# the only detector.
PROBE_TIMEOUT_S = int(os.environ.get("GYM_TPU_BENCH_PROBE_TIMEOUT", 240))
# Long: full measurement incl. compiles (~40s) + GPT-2-base rider.
WATCHDOG_S = int(os.environ.get("GYM_TPU_BENCH_WATCHDOG", 2400))

# Anchored to backend-INIT failure shapes only (the round-4 traceback's
# "Unable to initialize backend 'axon': ... TPU backend setup/compile
# error"). A bare "UNAVAILABLE" substring would also match gRPC status
# lines from a mid-measurement crash on a healthy chip and green a broken
# bench as chip-absent.
_UNAVAILABLE_MARKERS = (
    "Unable to initialize backend",
    "TPU backend setup",
    "failed to connect",
)


def _marker(error: str, detail: str, tail: str = "") -> dict:
    # "status"/"measured" make not-a-measurement EXPLICIT in the emitted
    # JSON: r04/r05 recorded backend-down runs that downstream tooling
    # could mistake for perf data — the trajectory must distinguish
    # "regressed" from "not measured" without parsing error strings.
    return {
        "error": error,
        "status": "not_measured",
        "measured": False,
        "metric": "nanogpt_diloco_64node_iterations_per_sec",
        "detail": detail,
        "tail": tail[-1500:],
    }


def _timeout_tail(e: subprocess.TimeoutExpired) -> str:
    # TimeoutExpired carries bytes (stderr often None) even under text=True
    out = e.stdout or b""
    err = e.stderr or b""
    if isinstance(out, bytes):
        out = out.decode(errors="replace")
    if isinstance(err, bytes):
        err = err.decode(errors="replace")
    return out + err


def _artifact_status(obj) -> tuple:
    """Classify one bench artifact as (result_dict_or_None, status).
    Accepts both the raw one-JSON-line result and the baseline runner's
    wrapper (``BENCH_rNN.json``: ``{"n", "cmd", "rc", "tail",
    "parsed"}``). Artifacts predating the explicit ``status`` field
    (r01–r03) are grandfathered: a parsed result carrying ``value`` and
    no ``error`` was a measurement; anything else is ``not_measured``."""
    if isinstance(obj, dict) and "parsed" in obj:
        obj = obj["parsed"]
    if (isinstance(obj, dict) and len(obj) == 1
            and isinstance(next(iter(obj.values())), dict)
            and "metric" in next(iter(obj.values()))):
        # an --X-only arm wrapper ({"coldstart": {...}}, {"serving":
        # {...}}): the inner object is the artifact
        obj = next(iter(obj.values()))
    if not isinstance(obj, dict):
        return None, "not_measured"
    if obj.get("status"):
        return obj, obj["status"]
    if obj.get("error"):
        return obj, "not_measured"
    if "value" in obj:
        return obj, "measured"
    return obj, "not_measured"


def compare_runs(path_a: str, path_b: str) -> dict:
    """``bench.py --compare A.json B.json`` — the ONLY sanctioned way to
    turn two bench artifacts into a speedup. Refuses (one-line
    ``not_comparable`` note, exit 0) when EITHER arm's status is not
    ``measured``: r04/r05 recorded ``tpu_unavailable`` markers, and
    dividing a marker by a measurement is how a dead transport gets
    reported as a 100% regression (the ROADMAP perf-trajectory
    caveat this closes)."""
    arms = {}
    for name, path in (("a", path_a), ("b", path_b)):
        try:
            with open(path) as f:
                raw = json.load(f)
            obj, status = _artifact_status(raw)
        except (OSError, json.JSONDecodeError) as e:
            obj, status = None, "not_measured"
            arms[name] = {"path": path, "status": status,
                          "error": f"{type(e).__name__}: {e}"}
            continue
        arms[name] = {"path": path, "status": status,
                      "value": (obj or {}).get("value"),
                      "metric": (obj or {}).get("metric"),
                      "error": (obj or {}).get("error")}
    a, b = arms["a"], arms["b"]
    out = {"mode": "compare", "a": a, "b": b}

    def numeric(v):
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    bad = [n for n in ("a", "b") if arms[n]["status"] != "measured"
           or not numeric(arms[n].get("value"))
           or arms[n]["value"] == 0]
    if bad:
        out["comparable"] = False
        out["note"] = "not_comparable"
        out["reason"] = "; ".join(
            f"arm {n} ({arms[n]['path']}): status="
            f"{arms[n]['status']}"
            + ("" if numeric(arms[n].get("value"))
               else f", value={arms[n].get('value')!r}")
            + (f", error={arms[n]['error']}" if arms[n].get("error")
               else "")
            for n in bad)
        return out
    if (a.get("metric") and b.get("metric")
            and a["metric"] != b["metric"]):
        # dividing steps/s by, say, sim-seconds is a confidently wrong
        # number (and inverted for lower-is-better metrics)
        out["comparable"] = False
        out["note"] = "not_comparable"
        out["reason"] = (f"metric mismatch: a={a['metric']!r} "
                         f"b={b['metric']!r}")
        return out
    out["comparable"] = True
    out["speedup"] = round(b["value"] / a["value"], 3)
    return out


def _classify_and_report(blob: str, detail: str) -> int:
    err = ("tpu_unavailable" if any(m in blob for m in _UNAVAILABLE_MARKERS)
           else "bench_failure")
    print(json.dumps(_marker(err, detail, blob)))
    return 0 if err == "tpu_unavailable" else 1


def _supervise() -> int:
    """Probe the accelerator, then run the measurement under a watchdog."""
    # --sim-only / --chaos-only / --fleet-only / --analyze-only /
    # --tracesim-only / --elastic-only / --tenant-only are host-side by
    # construction (modeled network; injected host faults; in-process
    # replica fleet; abstract tracing; trace-replay queueing;
    # vnode-folded CPU mesh; in-process multi-tenant scheduler) — never
    # touch the accelerator
    force_cpu = ("--cpu" in sys.argv or "--sim-only" in sys.argv
                 or "--chaos-only" in sys.argv
                 or "--fleet-only" in sys.argv
                 or "--analyze-only" in sys.argv
                 or "--coldstart-only" in sys.argv
                 or "--tracesim-only" in sys.argv
                 or "--elastic-only" in sys.argv
                 or "--tenant-only" in sys.argv
                 or "--sdc-only" in sys.argv)
    if not force_cpu:
        probe_cmd = [sys.executable, "-c",
                     "import jax; print('PLATFORM=' + jax.devices()[0].platform)"]
        try:
            probe = subprocess.run(probe_cmd, capture_output=True, text=True,
                                   timeout=PROBE_TIMEOUT_S)
        except subprocess.TimeoutExpired as e:
            print(json.dumps(_marker(
                "tpu_unavailable",
                f"backend init hung > {PROBE_TIMEOUT_S}s (transport tunnel "
                "down; site hook blocks all backend init)",
                _timeout_tail(e))))
            return 0
        blob = probe.stdout + probe.stderr
        if probe.returncode != 0:
            return _classify_and_report(blob, "backend init raised")
        if "PLATFORM=cpu" in probe.stdout:
            marker = _marker(
                "tpu_unavailable",
                "default backend resolved to host CPU — no accelerator "
                "attached; headline CPU numbers come from `bench.py --cpu`")
            # the host-overlap ablation (ISSUE 1) is still measurable on
            # the CPU fallback — attach it to the marker
            if os.environ.get("GYM_TPU_BENCH_OVERLAP", "1") == "1":
                marker["host_overlap"] = _overlap_subprocess()
            print(json.dumps(marker))
            return 0
    env = dict(os.environ)
    env["_GYM_TPU_BENCH_CHILD"] = "1"
    if ("--overlap-only" in sys.argv or "--resilience-only" in sys.argv
            or "--sim-only" in sys.argv
            or "--elastic-only" in sys.argv
            or "--sdc-only" in sys.argv) and force_cpu:
        # ablation-only CPU run: same 16-virtual-device layout the test
        # harness and _overlap_subprocess use (pre-init flag)
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=16 "
                            + env.get("XLA_FLAGS", ""))
    cmd = [sys.executable, os.path.abspath(__file__)] + sys.argv[1:]
    # A CPU re-measure legitimately takes ~40 min/window; don't watchdog it
    # at accelerator scale.
    watchdog = None if force_cpu else WATCHDOG_S
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=watchdog)
    except subprocess.TimeoutExpired as e:
        print(json.dumps(_marker(
            "tpu_unavailable",
            f"measurement exceeded {WATCHDOG_S}s watchdog (transport stall "
            "mid-run)", _timeout_tail(e))))
        return 0
    if proc.returncode == 0:
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return 0
    return _classify_and_report(proc.stdout + proc.stderr,
                                f"measurement child rc={proc.returncode}")

CPU_BASELINE_IT_S = 0.008  # measured on this host: `python bench.py --cpu`
# (64-node nanoGPT DiLoCo on 8 virtual CPU devices: ~125 s/step)
CPU_BASELINE_MEASURED_AT = "2026-07-29"  # provenance of the constant above
# (VERDICT r2 weak #8: vs_baseline must not silently trust an undated
# constant — the date is stamped into the JSON; re-measure with --cpu
# and override via GYM_TPU_BENCH_BASELINE, which stamps "env-override")

NUM_NODES = 64
BLOCK_SIZE = 256
VOCAB = 65          # shakespeare char vocab (reference build_dataset.py:8-21)
BATCH_PER_NODE = 16
WARMUP = int(os.environ.get("GYM_TPU_BENCH_WARMUP", 3))
TIMED = int(os.environ.get("GYM_TPU_BENCH_STEPS", 20))


def _interleaved_ab(run, steps: int, windows: int):
    """Median-of-windows A/B with arm order ALTERNATED window to window:
    shared-machine throughput drifts by more than the effect size, so a
    fixed A-then-B order would systematically bias whichever arm runs
    later in each pair, and a max-statistic just samples the drift.
    ``run(arm: bool, steps)`` returns a FitResult; the steady-state rate
    is compared (falls back to the full-run rate for 1-dispatch runs).
    Returns ``(off_median_its, on_median_its, losses_bit_identical)``.
    Shared by the host-overlap and resilience ablations so the two
    measurement protocols cannot drift apart."""
    offs, ons = [], []
    losses_off = losses_on = None
    for w in range(windows):
        order = (False, True) if w % 2 == 0 else (True, False)
        for arm in order:
            res = run(arm, steps)
            its = res.steps_per_second_steady or res.steps_per_second
            (ons if arm else offs).append(its)
            losses = [l for _, l in res.history["train_loss"]]
            if arm:
                losses_on = losses
            else:
                losses_off = losses
    return (sorted(offs)[len(offs) // 2], sorted(ons)[len(ons) // 2],
            losses_off == losses_on)


def measure_host_overlap() -> dict:
    """A/B the Trainer's host-overlap pipeline: the SAME seeded fit run
    with ``prefetch=False`` (every batch assembled + device_put on the
    dispatch critical path) vs ``prefetch=True`` (background double-
    buffered prefetch, deferred metric drains). Reports steady-state
    steps/sec for both and verifies the two loss trajectories are
    bit-identical — the prefetcher's determinism contract.

    The workload exercises the WHOLE host pipeline the overlap layer
    covers: a small dense model fed by a map-style
    (torch-``__getitem__``-like) dataset — the reference framework's
    DataLoader regime — with periodic checkpoint saves. Overlap-off runs
    every piece of host work serially on the dispatch critical path
    (inline assembly, blocking device_get + Orbax write per save);
    overlap-on is the Trainer's default pipeline (background prefetch,
    deferred drains, checkpoint writer thread). Compile cost is kept out
    of the A/B twice over: a warmup fit primes JAX's persistent
    compilation cache, and the comparison uses
    ``steps_per_second_steady`` (clock starts after the first dispatch
    retires).
    """
    import shutil
    import tempfile

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data.sampler import IndexedDataset
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache(
        os.environ.get("GYM_TPU_BENCH_CACHE_DIR"), min_compile_time_secs=0)

    nodes = int(os.environ.get("GYM_TPU_BENCH_OVERLAP_NODES", 8))
    steps = int(os.environ.get("GYM_TPU_BENCH_OVERLAP_STEPS", 192))
    spc = int(os.environ.get("GYM_TPU_BENCH_OVERLAP_SPC", 8))
    ckpt_every = int(os.environ.get("GYM_TPU_BENCH_OVERLAP_CKPT", 24))
    hid = 256  # wide enough that each save moves real bytes (~25 MB of
    # state per node set): the serial arm's device_get + write stall is
    # then signal, not noise, on a loaded shared machine

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            h = nn.relu(nn.Dense(hid)(x))
            logits = nn.Dense(10)(h)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    n = 8192
    xs = rng.normal(0, 1, size=(n, 32, 32)).astype(np.float32)
    ys = rng.integers(0, 10, n).astype(np.int32)

    class PairDataset:  # map-style: per-item host work, like a DataLoader
        def __len__(self):
            return n

        def __getitem__(self, i):
            return xs[i], ys[i]

    ds = IndexedDataset(PairDataset())

    def run(overlap: bool, max_steps: int, ckpt: bool = True):
        save_dir = tempfile.mkdtemp(prefix="gym_tpu_overlap_ckpt_")
        try:
            res = Trainer(MLP(), ds).fit(
                strategy=DiLoCoStrategy(
                    optim_spec=OptimSpec("adamw", lr=1e-3), H=100),
                num_nodes=nodes, max_steps=max_steps, batch_size=64,
                minibatch_size=64, steps_per_call=spc, val_size=0,
                val_interval=0, show_progress=False, seed=7,
                prefetch=overlap, async_checkpoint=overlap,
                checkpoint_interval=ckpt_every if ckpt else None,
                save_dir=save_dir if ckpt else None,
                log_dir=os.environ.get("GYM_TPU_BENCH_LOGDIR",
                                       "/tmp/gym_tpu_bench_logs"))
            if res.preempted:
                # Ctrl-C now returns a normal-looking partial FitResult;
                # a truncated sample must abort the A/B, not pollute it
                raise KeyboardInterrupt("fit preempted mid-benchmark")
            return res
        finally:
            # fresh dir per run: a leftover checkpoint would RESUME the
            # next fit instead of starting it from scratch
            shutil.rmtree(save_dir, ignore_errors=True)

    run(False, 2 * spc, ckpt=False)  # primes the persistent compile cache
    windows = max(1, int(os.environ.get("GYM_TPU_BENCH_OVERLAP_WINDOWS",
                                        5)))
    off_its, on_its, bit_identical = _interleaved_ab(run, steps, windows)
    return {
        "metric": "host_overlap_ablation_steps_per_sec",
        "workload": (f"mlp(1024-{hid}-10) map-style dataset, diloco {nodes}n "
                     f"bs64 spc{spc} x{steps} steps, ckpt every "
                     f"{ckpt_every}"),
        "timing": f"median_of_{windows}_interleaved",
        "overlap_off_it_s": round(off_its, 3),
        "overlap_on_it_s": round(on_its, 3),
        "speedup": round(on_its / off_its, 3) if off_its else None,
        "loss_bit_identical": bit_identical,
    }


def measure_resilience_overhead() -> dict:
    """A/B the ISSUE 2 resilience layer's steady-state cost: the SAME
    seeded fit with the watchdog armed (deadline contexts around every
    drain/prefetch-get/checkpoint region) vs off. The fault-injection
    registry (empty: one attribute read per site) and the retry wrappers
    (no-op on the success path) are active in BOTH arms — they are
    always-on in production too; the watchdog thread + context managers
    are the only toggleable cost. Expected: noise.
    """
    import shutil
    import tempfile

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache(
        os.environ.get("GYM_TPU_BENCH_CACHE_DIR"), min_compile_time_secs=0)

    steps = int(os.environ.get("GYM_TPU_BENCH_RESIL_STEPS", 192))
    spc = int(os.environ.get("GYM_TPU_BENCH_RESIL_SPC", 8))

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            h = nn.relu(nn.Dense(256)(x))
            logits = nn.Dense(10)(h)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    ds = ArrayDataset(
        rng.normal(0, 1, size=(8192, 32, 32)).astype(np.float32),
        rng.integers(0, 10, 8192).astype(np.int32))

    def run(watchdog: bool, max_steps: int):
        save_dir = tempfile.mkdtemp(prefix="gym_tpu_resil_ckpt_")
        try:
            res = Trainer(MLP(), ds).fit(
                strategy=DiLoCoStrategy(
                    optim_spec=OptimSpec("adamw", lr=1e-3), H=100),
                num_nodes=8, max_steps=max_steps, batch_size=64,
                minibatch_size=64, steps_per_call=spc, val_size=0,
                val_interval=0, show_progress=False, seed=7,
                checkpoint_interval=24, save_dir=save_dir,
                # 0.0, not None: None falls back to GYM_TPU_WATCHDOG_S,
                # which would arm the watchdog in the OFF arm too
                watchdog_timeout=300.0 if watchdog else 0.0,
                log_dir=os.environ.get("GYM_TPU_BENCH_LOGDIR",
                                       "/tmp/gym_tpu_bench_logs"))
            if res.preempted:
                raise KeyboardInterrupt("fit preempted mid-benchmark")
            return res
        finally:
            shutil.rmtree(save_dir, ignore_errors=True)

    run(False, 2 * spc)  # primes the persistent compile cache
    windows = max(1, int(os.environ.get("GYM_TPU_BENCH_RESIL_WINDOWS", 5)))
    off_its, on_its, bit_identical = _interleaved_ab(run, steps, windows)
    return {
        "metric": "resilience_overhead_steps_per_sec",
        "workload": (f"mlp(1024-256-10), diloco 8n bs64 spc{spc} "
                     f"x{steps} steps, ckpt every 24"),
        "timing": f"median_of_{windows}_interleaved",
        "watchdog_off_it_s": round(off_its, 3),
        "watchdog_on_it_s": round(on_its, 3),
        "overhead_pct": round(100.0 * (off_its - on_its) / off_its, 2)
        if off_its else None,
        "loss_bit_identical": bit_identical,
    }


def measure_sdc_guard() -> dict:
    """A/B the ISSUE 20 training guard's steady-state cost: the SAME
    seeded fit with ``fit(guard=Guard(...))`` (per-drained-step
    finiteness + worst-node EWMA spike checks, plus the on-device
    state-fingerprint probe at the checkpoint cadence) vs no guard.
    The guard is pure observation — the loss trajectories must stay
    bit-identical — and its host cost is a few float compares per
    drained step, so the budget is < 2% steps/sec. Both arms
    ``status=measured``; the checkpoint sidecar writes are active in
    BOTH arms (always-on, like the fault registry in the resilience
    ablation) — the guard observation layer is the only toggle."""
    import shutil
    import tempfile

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.utils.compile_cache import enable_compilation_cache
    from gym_tpu.utils.integrity import Guard

    enable_compilation_cache(
        os.environ.get("GYM_TPU_BENCH_CACHE_DIR"), min_compile_time_secs=0)

    steps = int(os.environ.get("GYM_TPU_BENCH_SDC_STEPS", 192))
    spc = int(os.environ.get("GYM_TPU_BENCH_SDC_SPC", 8))

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            h = nn.relu(nn.Dense(256)(x))
            logits = nn.Dense(10)(h)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    ds = ArrayDataset(
        rng.normal(0, 1, size=(8192, 32, 32)).astype(np.float32),
        rng.integers(0, 10, 8192).astype(np.int32))

    def run(guard_on: bool, max_steps: int):
        save_dir = tempfile.mkdtemp(prefix="gym_tpu_sdc_ckpt_")
        try:
            res = Trainer(MLP(), ds).fit(
                strategy=DiLoCoStrategy(
                    optim_spec=OptimSpec("adamw", lr=1e-3), H=100),
                num_nodes=8, max_steps=max_steps, batch_size=64,
                minibatch_size=64, steps_per_call=spc, val_size=0,
                val_interval=0, show_progress=False, seed=7,
                checkpoint_interval=24, save_dir=save_dir,
                # fingerprint probe at the checkpoint cadence: the full
                # defense a production run would arm
                guard=Guard(fingerprint_interval=24) if guard_on
                else None,
                watchdog_timeout=0.0,
                log_dir=os.environ.get("GYM_TPU_BENCH_LOGDIR",
                                       "/tmp/gym_tpu_bench_logs"))
            if res.preempted:
                raise KeyboardInterrupt("fit preempted mid-benchmark")
            return res
        finally:
            shutil.rmtree(save_dir, ignore_errors=True)

    run(False, 2 * spc)  # primes the persistent compile cache
    windows = max(1, int(os.environ.get("GYM_TPU_BENCH_SDC_WINDOWS", 5)))
    off_its, on_its, bit_identical = _interleaved_ab(run, steps, windows)
    return {
        "metric": "sdc_guard_overhead_steps_per_sec",
        "status": "measured",
        "measured": True,
        "workload": (f"mlp(1024-256-10), diloco 8n bs64 spc{spc} "
                     f"x{steps} steps, ckpt every 24, fingerprint "
                     f"probe every 24"),
        "timing": f"median_of_{windows}_interleaved",
        "guard_off_it_s": round(off_its, 3),
        "guard_on_it_s": round(on_its, 3),
        "overhead_pct": round(100.0 * (off_its - on_its) / off_its, 2)
        if off_its else None,
        "loss_bit_identical": bit_identical,
    }


def _overlap_subprocess(timeout_s: int = 1800):
    """Run the host-overlap ablation in a fresh CPU subprocess with the
    test harness's 16-virtual-device layout (XLA_FLAGS must be set before
    jax initializes, and a TPU-holding parent must not respawn on the
    chip). Returns the ablation dict or an {"error": ...} stub."""
    env = dict(os.environ)
    env["_GYM_TPU_BENCH_CHILD"] = "1"
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=16 "
                        + env.get("XLA_FLAGS", ""))
    cmd = [sys.executable, os.path.abspath(__file__), "--overlap-only",
           "--cpu"]
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout_s)
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                return json.loads(line)["host_overlap"]
            except (json.JSONDecodeError, KeyError):
                continue
        return {"error": "no ablation JSON",
                "tail": (proc.stdout + proc.stderr)[-500:]}
    except subprocess.TimeoutExpired as e:
        return {"error": f"ablation exceeded {timeout_s}s",
                "tail": _timeout_tail(e)[-500:]}


def measure_network_sim() -> dict:
    """The ISSUE 3 rider, grown by ISSUE 10 and ISSUE 12: the
    low-communication strategy family — now codec × outer loop — vs
    AllReduce in simulated wall-clock on the WAN, datacenter and
    federated presets, via a tiny real sweep (measured compute, modeled
    comm) through ``gym_tpu.sim.sweep``. Per preset, each cell's
    simulated speedup over AllReduce plus whether every cell's declared
    trace reconciled with its logged ``cum_comm_bytes``; the federated
    preset carries the ISSUE 12 headline key
    ``compressed_gossip_speedup`` (best NoLoCo × non-dense-codec
    cell)."""
    import contextlib
    import tempfile

    from gym_tpu.sim.sweep import SweepConfig, run_sweep

    out = (os.environ.get("GYM_TPU_BENCH_SIM_DIR")
           or tempfile.mkdtemp(prefix="gym_tpu_sim_bench_"))
    cfg = SweepConfig(
        strategies=["diloco", "noloco", "demo_outer", "dynamiq_int8",
                    "simple_reduce"],
        presets=["wan", "datacenter", "federated"],
        codecs=["dense", "int8", "int4"],
        nodes=[int(os.environ.get("GYM_TPU_BENCH_SIM_NODES", 4))],
        H=[int(os.environ.get("GYM_TPU_BENCH_SIM_H", 10))],
        steps=int(os.environ.get("GYM_TPU_BENCH_SIM_STEPS", 30)),
        out=out,
    )
    with contextlib.redirect_stdout(sys.stderr):  # keep stdout one JSON line
        rows = run_sweep(cfg)

    def cell(strategy, preset, codec=None):
        return next(r for r in rows if r["strategy"] == strategy
                    and r["topology"] == preset
                    and r.get("codec") == codec)

    result = {"metric": "network_sim_low_comm_vs_allreduce",
              "status": "measured",
              "measured": True,
              "workload": (f"2-layer GPT, {cfg.nodes[0]} nodes, "
                           f"{cfg.steps} steps, H={cfg.H[0]}, "
                           f"codecs {'+'.join(cfg.codecs)}"),
              "out_dir": out}
    for preset in cfg.presets:
        a = cell("simple_reduce", preset)
        entry = {"allreduce_sim_s": round(a["sim_total_s"], 3),
                 "traces_reconcile": bool(a["reconciled"])}
        # every (strategy, codec) cell the grid runs is reported — a
        # trained-but-unreported cell would be wasted fit time
        for name, key, codec in (
                ("diloco", "diloco", None),
                ("diloco", "diloco_int8", "int8"),
                ("diloco", "diloco_int4", "int4"),
                ("noloco", "noloco", None),
                ("noloco", "noloco_int8", "int8"),
                ("noloco", "noloco_int4", "int4"),
                ("demo_outer", "demo_outer", None),
                ("demo_outer", "demo_outer_int8", "int8"),
                ("demo_outer", "demo_outer_int4", "int4"),
                ("dynamiq", "dynamiq_int8", "int8")):
            r = cell(name, preset, codec)
            entry[f"{key}_sim_s"] = round(r["sim_total_s"], 3)
            entry[f"{key}_speedup"] = (
                round(a["sim_total_s"] / r["sim_total_s"], 2)
                if r["sim_total_s"] else None)
            entry[f"{key}_final_loss"] = round(r["final_train_loss"], 4)
            entry["traces_reconcile"] &= bool(r["reconciled"])
        # back-compat key: r03-era artifacts called this "speedup"
        entry["speedup"] = entry["diloco_speedup"]
        result[preset] = entry
    # the ISSUE 12 headline: best compressed-gossip cell on the
    # federated preset, end to end vs AllReduce
    fed = result.get("federated", {})
    result["compressed_gossip_speedup"] = max(
        (fed[k] for k in ("noloco_int8_speedup", "noloco_int4_speedup")
         if fed.get(k)), default=None)
    return result


def measure_serving() -> dict:
    """The ISSUE 4 headline: aggregate tokens/s of the continuous-batching
    engine (``gym_tpu.serve``) vs sequentially looping ``generate_fast``
    over the SAME mixed prompt/output-length request set.

    The workload is genuinely mixed — every request draws a DISTINCT
    ``(prompt_len, max_new_tokens)`` signature, which is what live
    traffic looks like. That regime is exactly what the engine exists
    for: ``generate_fast`` compiles one program per signature (N
    requests → N multi-second XLA compiles; its lru cache never
    saturates under live traffic), while the engine's compile set is
    BOUNDED — one decode program plus at most ``⌈log2(block_size)⌉ + 1``
    prefill buckets — so the headline times each arm END TO END from a
    cold program cache, compiles included, the way a serving process
    actually experiences the workload. (The JAX persistent compile cache
    is disabled for this measurement; see main().)

    A second, warm pass of each arm is reported alongside
    (``*_warm_tok_s``): it isolates steady-state decode mechanics with
    every program already compiled. On this 2-core CPU the warm arms are
    within ~1.25x of each other — a b=8 decode step costs ~5x a b=1 step
    here (per-row attention over the static cache dominates; there is no
    under-utilized MXU to fill), so batching pays modestly; on an
    accelerator the batch dimension is where the win scales."""
    import math

    import numpy as np

    from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
    from gym_tpu.serve.engine import InferenceEngine, SamplingParams
    from gym_tpu.serve.scheduler import Scheduler

    num_slots = int(os.environ.get("GYM_TPU_BENCH_SERVE_SLOTS", 8))
    n_req = int(os.environ.get("GYM_TPU_BENCH_SERVE_REQUESTS", 12))
    chunk = int(os.environ.get("GYM_TPU_BENCH_SERVE_CHUNK", 8))
    cfg = GPTConfig(block_size=256, vocab_size=65, n_layer=4, n_head=4,
                    n_embd=128, dropout=0.0, bias=True)
    model = GPT(cfg)
    import jax
    import jax.numpy as jnp
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64), train=False)["params"]

    # distinct (prompt_len, max_new) per request — live-traffic shape mix
    rng = np.random.default_rng(0)
    sigs = set()
    while len(sigs) < n_req:
        sigs.add((int(rng.integers(4, 48)), int(rng.integers(8, 40))))
    workload = [
        (rng.integers(0, cfg.vocab_size, plen), SamplingParams(
            max_new_tokens=mnew, temperature=0.9, top_k=16, seed=i))
        for i, (plen, mnew) in enumerate(sorted(sigs))
    ]
    total_new = sum(sp.max_new_tokens for _, sp in workload)

    def run_sequential():
        for prompt, sp in workload:
            out = generate_fast(params, cfg, prompt[None],
                                sp.max_new_tokens,
                                temperature=sp.temperature,
                                top_k=sp.top_k, seed=sp.seed)
            assert out.shape[1] == len(prompt) + sp.max_new_tokens

    engine = InferenceEngine(params, cfg, num_slots=num_slots,
                             decode_chunk=chunk)

    def run_engine():
        sched = Scheduler(engine, max_queue=len(workload))
        handles = [sched.submit(p, sp) for p, sp in workload]
        while any(h.status.value in ("queued", "running")
                  for h in handles):
            sched.step()
        for h in handles:
            assert len(h.result()) == h.sampling.max_new_tokens

    def timed(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    # cold pass per arm (the headline: serve the workload end to end,
    # compiles included), then a warm pass (steady-state mechanics)
    seq_cold = timed(run_sequential)
    eng_cold = timed(run_engine)
    seq_warm = timed(run_sequential)
    eng_warm = timed(run_engine)

    # ---- shared-prefix workload (ISSUE 7): paged prefix-shared KV vs
    # the PR-4 per-slot engine on the realistic chatbot/agent shape —
    # N requests dominated by one long common system prompt. The paged
    # engine prefills the shared blocks ONCE and admits the rest
    # through the prefix cache; the PR-4 engine re-prefills the full
    # prompt every time. Aggregate tok/s and p99 TTFT are the headline;
    # the structural assert is that prefill WORK (padded tokens
    # dispatched) drops.
    n_shared = int(os.environ.get("GYM_TPU_BENCH_SERVE_SHARED_REQS", 12))
    sys_len, tail_len, shared_mnew = 224, 8, 8
    shared_sys = rng.integers(0, cfg.vocab_size, sys_len)
    shared_workload = [
        (np.concatenate([shared_sys,
                         rng.integers(0, cfg.vocab_size, tail_len)]),
         SamplingParams(max_new_tokens=shared_mnew, temperature=0.9,
                        top_k=16, seed=500 + i))
        for i in range(n_shared)]
    shared_new = sum(sp.max_new_tokens for _, sp in shared_workload)

    def shared_arm(paged: bool, spec: int = 0, arm_cfg=None,
                   arm_params=None) -> dict:
        arm_cfg = cfg if arm_cfg is None else arm_cfg
        arm_params = params if arm_params is None else arm_params

        def mk():
            return InferenceEngine(arm_params, arm_cfg,
                                   num_slots=num_slots,
                                   decode_chunk=chunk, paged=paged,
                                   page_size=16, spec_tokens=spec)

        def serve(sched, wl):
            handles = [sched.submit(p, sp) for p, sp in wl]
            while any(h.status.value in ("queued", "running")
                      for h in handles):
                sched.step()
            for h in handles:
                assert len(h.result()) == h.sampling.max_new_tokens
            return handles

        # compile pass on a THROWAWAY engine: the measured burst must
        # meet a COLD prefix cache (first request pays the full
        # prefill) but warm programs — the global LRUs carry them over
        serve(Scheduler(mk(), max_queue=n_shared), shared_workload[:2])
        eng = mk()
        sched = Scheduler(eng, max_queue=n_shared)
        t0 = time.perf_counter()
        handles = serve(sched, shared_workload)
        wall = time.perf_counter() - t0
        ttfts = [h.ttft_s for h in handles]
        out = {
            "tok_s": round(shared_new / wall, 1),
            "p50_ttft_s": round(float(np.percentile(ttfts, 50)), 4),
            "p99_ttft_s": round(float(np.percentile(ttfts, 99)), 4),
            "prefills": eng.stats.prefills,
            "prefill_tokens": eng.stats.prefill_tokens,
            "prefix_hit_blocks": eng.stats.prefix_hit_blocks,
        }
        if spec:
            out["spec_accept_rate"] = eng.stats.spec_accept_rate()
        return out

    pr4_arm = shared_arm(paged=False)
    paged_arm = shared_arm(paged=True)
    spec_arm = shared_arm(paged=True, spec=4)
    # structural acceptance (ISSUE 7): the shared blocks are measurably
    # ELIDED from prefill dispatch work, not just faster by luck
    assert paged_arm["prefill_tokens"] < pr4_arm["prefill_tokens"], (
        paged_arm, pr4_arm)
    assert paged_arm["prefix_hit_blocks"] > 0, paged_arm

    # ---- quantized serving (ISSUE 11): int8 weights + int8 paged KV.
    # The HEADLINE here is the deterministic capacity metric — resident
    # shared prefix blocks at a fixed KV payload byte budget — plus the
    # prefill-work elision it buys; tok/s is reported next to it but on
    # this 2-core CPU box it is noise-prone (±10%, see BENCH_r06) and
    # carries its own status field.
    import dataclasses as _dc

    from gym_tpu.serve.load import quantize_params

    qcfg = _dc.replace(cfg, weights_dtype="int8", kv_dtype="int8")
    qparams = quantize_params(params, qcfg)
    f32_param_bytes = sum(int(x.size * x.dtype.itemsize)
                          for x in jax.tree.leaves(params))
    q_param_bytes = sum(int(np.asarray(x).nbytes)
                        for x in jax.tree.leaves(qparams))

    def capacity_arm(arm_cfg, arm_params, kv_pages: int):
        """Sequential distinct one-block prompts through a small pool:
        every request content-registers its prompt block; the resident
        (refcount-0 cached) block count at the end IS the pool's
        prefix-holding capacity — deterministic, no timing anywhere."""
        eng = InferenceEngine(arm_params, arm_cfg, num_slots=2,
                              paged=True, page_size=16,
                              kv_pages=kv_pages)
        for i in range(80):
            slot, ev = eng.admit(
                rng.integers(0, cfg.vocab_size, 16),
                SamplingParams(max_new_tokens=2, seed=900 + i))
            while not ev.finished:
                evs = [e for e in eng.step() if e.slot == slot]
                ev = evs[-1]
        return eng

    # smallest legal f32 pool (null + one full window + CoW headroom);
    # the int8 arm gets exactly the same PAYLOAD byte budget — 4 pages
    # per f32 page — and must hold >= 4x the resident prefixes
    f32_kv_pages = 2 + cfg.block_size // 16           # 18 → 17 usable
    int8_kv_pages = 1 + (f32_kv_pages - 1) * 4        # 69: equal payload
    cap_f32 = capacity_arm(cfg, params, f32_kv_pages)
    cap_int8 = capacity_arm(qcfg, qparams, int8_kv_pages)
    # structural acceptance (ISSUE 11): the int8 pool's PAYLOAD fits the
    # f32 byte budget (scale sidecar reported, not hidden) and holds
    # >= 4x the resident prefix blocks
    assert (cap_int8.kv_pool_bytes()["payload"]
            <= cap_f32.kv_pool_bytes()["payload"]), (
        cap_int8.kv_pool_bytes(), cap_f32.kv_pool_bytes())
    assert (cap_int8.stats.kv_blocks_cached
            >= 4 * cap_f32.stats.kv_blocks_cached), (
        cap_int8.stats.kv_blocks_cached, cap_f32.stats.kv_blocks_cached)

    # token-stream divergence vs f32, per sampling config (int8 streams
    # are exact vs their own quantized reference — pinned in
    # tests/test_serve_paged.py — so what is measured here is the honest
    # f32-vs-int8 QUALITY delta, not a correctness bug)
    div_prompt = rng.integers(0, cfg.vocab_size, 24)
    div_new = 32
    divergence = {}
    for name, kw in (("greedy", dict(top_k=1)),
                     ("temp0.9_topk16", dict(temperature=0.9, top_k=16)),
                     ("topp0.9", dict(top_p=0.9))):
        ref = generate_fast(params, cfg, div_prompt[None], div_new,
                            seed=7, **kw)[0, 24:]
        got = generate_fast(qparams, qcfg, div_prompt[None], div_new,
                            seed=7, **kw)[0, 24:]
        diff = np.asarray(ref) != np.asarray(got)
        first = int(np.argmax(diff)) if diff.any() else None
        divergence[name] = {
            "tokens": div_new,
            "diverged_frac": round(float(diff.mean()), 4),
            "first_divergence_index": first,
        }

    # perplexity delta: mean CE of the SAME forward under f32 vs
    # quantized weights (eval mode; random-init model, so the absolute
    # level is meaningless — the DELTA is the codec's quality cost)
    ev = rng.integers(0, cfg.vocab_size, (4, 65))
    ev_batch = (jnp.asarray(ev[:, :-1]), jnp.asarray(ev[:, 1:]))
    loss_f32 = float(GPT(cfg).apply({"params": params}, ev_batch,
                                    train=False))
    loss_q = float(GPT(qcfg).apply({"params": qparams}, ev_batch,
                                   train=False))

    # tok/s: the shared-prefix workload on the quantized engine (weights
    # dequant fused into the matmuls + int8 KV), vs the f32 paged arm
    quant_arm = shared_arm(paged=True, arm_cfg=qcfg, arm_params=qparams)

    capacity_ratio = round(cap_int8.stats.kv_blocks_cached
                           / max(cap_f32.stats.kv_blocks_cached, 1), 2)
    quantized = {
        # self-describing artifact: --compare'able on the DETERMINISTIC
        # capacity ratio (write {"parsed": {"quantized": ...}} wrappers
        # and two rounds compare cleanly; tok/s stays a side column)
        "metric": "quantized_serving_capacity_ratio_int8_vs_f32",
        "value": capacity_ratio,
        "status": "measured",
        "measured": True,
        "config": "weights int8 (per-tile codec, dequant fused) + "
                  "kv int8 (per-(page-slot, head) scales); embedding "
                  "f32",
        "weights_bytes_f32": f32_param_bytes,
        "weights_bytes_int8": q_param_bytes,
        "weights_bytes_ratio": round(f32_param_bytes
                                     / max(q_param_bytes, 1), 2),
        "capacity": {
            # the deterministic headline: resident shared prefixes at a
            # FIXED KV payload byte budget (18-page f32 pool vs 69-page
            # int8 pool — equal payload bytes; no timing anywhere)
            "workload": "80 distinct 1-block prompts, page 16, "
                        "sequential",
            "f32_kv_pages": f32_kv_pages,
            "int8_kv_pages": int8_kv_pages,
            "f32_pool_bytes": cap_f32.kv_pool_bytes(),
            "int8_pool_bytes": cap_int8.kv_pool_bytes(),
            "f32_resident_prefix_blocks":
                int(cap_f32.stats.kv_blocks_cached),
            "int8_resident_prefix_blocks":
                int(cap_int8.stats.kv_blocks_cached),
            "capacity_ratio": capacity_ratio,
            "prefill_tokens_f32_arm": int(cap_f32.stats.prefill_tokens),
            "prefill_tokens_int8_arm":
                int(cap_int8.stats.prefill_tokens),
        },
        "shared_prefix_quantized": quant_arm,
        "tok_s_vs_f32_paged": round(
            quant_arm["tok_s"] / max(paged_arm["tok_s"], 1e-9), 2),
        "tok_s_note": "2-core CPU box: tok/s drifts +-10% — the "
                      "capacity metric above is the headline; on an "
                      "accelerator the int8 weight traffic is where "
                      "dequant-fused matmuls win",
        "divergence_vs_f32": divergence,
        "quality": {
            "eval_loss_f32": round(loss_f32, 6),
            "eval_loss_int8": round(loss_q, 6),
            "loss_delta": round(loss_q - loss_f32, 6),
            "perplexity_f32": round(math.exp(loss_f32), 4),
            "perplexity_int8": round(math.exp(loss_q), 4),
            "perplexity_delta": round(math.exp(loss_q)
                                      - math.exp(loss_f32), 4),
        },
    }

    return {
        "metric": "serving_continuous_batching_vs_sequential_tokens_per_s",
        "status": "measured",
        "measured": True,
        "workload": (f"{n_req} requests, distinct (prompt_len in [4,48), "
                     f"max_new in [8,40)) signatures, gpt "
                     f"{cfg.n_layer}L/{cfg.n_embd}d block "
                     f"{cfg.block_size}, {num_slots} slots, "
                     f"chunk {chunk}"),
        "timing": "cold_process_compiles_included; warm = second pass",
        "sequential_tok_s": round(total_new / seq_cold, 1),
        "engine_tok_s": round(total_new / eng_cold, 1),
        "speedup": round(seq_cold / eng_cold, 2),
        "sequential_warm_tok_s": round(total_new / seq_warm, 1),
        "engine_warm_tok_s": round(total_new / eng_warm, 1),
        "warm_speedup": round(seq_warm / eng_warm, 2),
        "sequential_programs_compiled": len(workload),
        "engine_prefill_compiles": engine.stats.prefill_compiles,
        "prefill_bound": (cfg.block_size - 1).bit_length() + 1,
        "shared_prefix": {
            "workload": (f"{n_shared} requests = {sys_len}-token shared "
                         f"system prompt + {tail_len}-token distinct "
                         f"tail, max_new {shared_mnew}, page 16, "
                         f"{num_slots} slots, chunk {chunk}; programs "
                         f"warm, prefix cache cold"),
            "pr4_engine": pr4_arm,
            "paged_engine": paged_arm,
            "paged_spec_engine": spec_arm,
            "tok_s_speedup": round(paged_arm["tok_s"] / pr4_arm["tok_s"],
                                   2),
            "p99_ttft_speedup": round(
                pr4_arm["p99_ttft_s"] / paged_arm["p99_ttft_s"], 2),
            "prefill_tokens_elided": (pr4_arm["prefill_tokens"]
                                      - paged_arm["prefill_tokens"]),
        },
        "quantized": quantized,
    }


def _coldstart_worker() -> None:
    """Child process for ``measure_coldstart`` — one genuinely fresh
    process per regime (a cold start is a PROCESS property: registry,
    jit caches and the XLA client all start empty).

    argv: ``--coldstart-worker <cache_dir|-> <warmup 0|1>``.  Builds the
    serving engine, optionally enables the registry's persistent
    executable tier and/or runs background warmup TO COMPLETION, then
    serves one burst of bucket-spanning prompts submitted all at t=0 —
    the worst-case cold arrival — and prints per-request TTFTs plus the
    registry counters as one JSON line."""
    i = sys.argv.index("--coldstart-worker")
    cache_dir, warmup = sys.argv[i + 1], sys.argv[i + 2] == "1"

    import numpy as np

    import jax

    from gym_tpu import programs
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.serve.engine import InferenceEngine, SamplingParams
    from gym_tpu.serve.scheduler import Scheduler

    if cache_dir != "-":
        programs.enable_disk_tier(cache_dir)

    cfg = GPTConfig(block_size=256, vocab_size=65, n_layer=4, n_head=4,
                    n_embd=128, dropout=0.0, bias=True)
    params = GPT(cfg).init({"params": jax.random.PRNGKey(0)},
                           np.zeros((1, 8), np.int64),
                           train=False)["params"]
    eng = InferenceEngine(params, cfg, num_slots=4, decode_chunk=8)

    warm_s = 0.0
    if warmup:
        w = programs.warm_engine_programs(eng, start=True)
        assert w.wait(timeout=1800), "warmup did not finish"
        assert w.stats()["warmed"] == w.stats()["total"], w.stats()
        warm_s = w.seconds

    builds0 = programs.default_registry().counters()["builds"]
    # one prompt per power-of-two prefill bucket (4..256 at block 256):
    # a cold engine pays one compile per bucket ON the request path
    rng = np.random.default_rng(0)
    burst = [(rng.integers(0, cfg.vocab_size, n),
              SamplingParams(max_new_tokens=8, temperature=0.9,
                             top_k=16, seed=i))
             for i, n in enumerate((3, 6, 12, 24, 48, 96, 190))]
    sched = Scheduler(eng, max_queue=len(burst))
    t0 = time.perf_counter()
    handles = [sched.submit(p, sp) for p, sp in burst]
    while any(h.status.value in ("queued", "running") for h in handles):
        sched.step()
    wall = time.perf_counter() - t0
    for h in handles:
        assert len(h.result(timeout=30)) == h.sampling.max_new_tokens

    ttfts = sorted(h.ttft_s for h in handles)
    c = programs.default_registry().counters()
    print(json.dumps({
        "ttfts_s": [round(t, 4) for t in ttfts],
        "p50_ttft_s": round(ttfts[len(ttfts) // 2], 4),
        "p99_ttft_s": round(ttfts[-1], 4),     # 7 samples: p99 == max
        "burst_wall_s": round(wall, 3),
        "on_path_builds": c["builds"] - builds0,
        "counters": c,
        "xla_compiles": programs.xla_compile_counter(),
        "warmup_s": round(warm_s, 3),
    }))


def measure_coldstart() -> dict:
    """The ISSUE 9 headline: first-burst TTFT of a fresh serving process
    under the device-program registry's three cold-start regimes —

    - ``cold_disk``     — empty persistent tier, no warmup: every
      program XLA-compiles ON the request path (the pre-registry cold
      start, and this run seeds the disk tier for the next two);
    - ``warm_disk``     — process restart against the seeded tier, no
      warmup: builds deserialize instead of compiling, still on-path;
    - ``warmed``        — restart + background AOT warmup completed
      before traffic: zero on-path builds (the shipped server default).

    Each regime is a fresh subprocess (cold starts are process
    properties).  Structural pins ride along with the timings: the
    warm-disk restart reports ``xla_compiles == 0`` and the warmed
    server's burst triggers ``on_path_builds == 0``."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="gym_tpu_coldstart_")
    cache = os.path.join(tmp, "progcache")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)                 # plain 1-device children
    for k in ("GYM_TPU_PROGRAM_CACHE_DIR", "JAX_COMPILATION_CACHE_DIR"):
        env.pop(k, None)                       # regime = argv, not env

    def run(cache_dir: str, warmup: bool) -> dict:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--coldstart-worker", cache_dir, "1" if warmup else "0"],
            env=env, capture_output=True, text=True, timeout=1800)
        assert p.returncode == 0, (p.stdout + p.stderr)[-2000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    try:
        cold = run(cache, warmup=False)
        warm_disk = run(cache, warmup=False)
        warmed = run(cache, warmup=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # structural acceptance — the timings above must come from the
    # mechanism claimed, not from noise on a shared 2-core host
    assert cold["xla_compiles"] == cold["on_path_builds"] > 0, cold
    assert warm_disk["xla_compiles"] == 0, warm_disk
    assert warm_disk["on_path_builds"] > 0, warm_disk
    assert warmed["on_path_builds"] == 0, warmed

    return {
        "metric": "serving_coldstart_first_burst_ttft_s",
        "status": "measured",
        "measured": True,
        # the comparable headline (bench.py --compare): p99 TTFT of the
        # shipped default — restart, warm disk, warmup done. LOWER is
        # better; --compare reports b/a, so read speedup as a ratio of
        # TTFTs, not a rate
        "value": warmed["p99_ttft_s"],
        "unit": "s_p99_ttft_warmed_lower_is_better",
        "workload": ("7-request burst at t=0, one per prefill bucket "
                     "(prompt 3..190), max_new 8, gpt 4L/128d block "
                     "256, 4 slots, chunk 8; fresh process per regime"),
        "cold_disk": cold,
        "warm_disk": warm_disk,
        "warmed": warmed,
        "p99_ttft_speedup_warm_disk": round(
            cold["p99_ttft_s"] / warm_disk["p99_ttft_s"], 2),
        "p99_ttft_speedup_warmed": round(
            cold["p99_ttft_s"] / warmed["p99_ttft_s"], 2),
        "warmup_cost_s": warmed["warmup_s"],
    }


def measure_chaos() -> dict:
    """The ISSUE 5 rider: the serving stack under injected faults — the
    SAME mixed-request workload served (a) clean and (b) with a delay
    fault on every decode dispatch plus one injected HANG mid-run (the
    supervisor recovery drill) and a burst of infeasible-deadline
    submissions (the admission-control shed). Reports tail latencies
    (p50/p95/p99 TTFT + per-token) for both arms and the shed /
    quarantined / restart counters — the "serving under fire" headline.

    Host-side by construction (the faults are host faults); always
    CPU-forced like --sim-only. Both arms run warm (a warmup request
    precedes them) so the deltas are fault cost, not compile cost."""
    import tempfile

    import numpy as np

    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.serve.engine import InferenceEngine, SamplingParams
    from gym_tpu.serve.metrics import ServeMetrics
    from gym_tpu.serve.scheduler import (AdmissionRejectedError,
                                         Scheduler)
    from gym_tpu.serve.supervisor import Supervisor
    from gym_tpu.utils.resilience import faults

    import jax

    num_slots = int(os.environ.get("GYM_TPU_BENCH_CHAOS_SLOTS", 4))
    n_req = int(os.environ.get("GYM_TPU_BENCH_CHAOS_REQUESTS", 16))
    cfg = GPTConfig(block_size=128, vocab_size=65, n_layer=2, n_head=2,
                    n_embd=64, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64), train=False)["params"]

    rng = np.random.default_rng(0)
    sigs = set()
    while len(sigs) < n_req:
        sigs.add((int(rng.integers(4, 32)), int(rng.integers(8, 24))))
    workload = [
        (rng.integers(0, cfg.vocab_size, plen), SamplingParams(
            max_new_tokens=mnew, temperature=0.9, top_k=16, seed=i))
        for i, (plen, mnew) in enumerate(sorted(sigs))
    ]

    def engine_factory():
        return InferenceEngine(params, cfg, num_slots=num_slots,
                               decode_chunk=2)

    def run_arm(fault_spec: str) -> dict:
        faults.reset()
        if fault_spec:
            faults.configure(fault_spec)
        out = tempfile.mkdtemp(prefix="gym_tpu_chaos_")
        metrics = ServeMetrics(out, engine_log_every=10)
        sched = Scheduler(engine_factory(), max_queue=64, metrics=metrics)
        sup = Supervisor(sched, engine_factory, dispatch_timeout_s=1.0,
                         max_restarts=4, metrics=metrics,
                         log=lambda *a, **k: None)
        sup.start()
        handles = [sched.submit(p, sp, deadline_s=120.0)
                   for p, sp in workload]
        # wait out half the workload so the tokens/s EWMA is live, then
        # fire the admission-control shed: deliberately infeasible
        # deadlines must be rejected up front, not queued to die
        for h in handles[:n_req // 2]:
            try:
                h.result(timeout=300)
            except (RuntimeError, OSError):   # OSError covers
                pass                          # TimeoutError + IO faults
        rejected = 0
        for k in range(3):
            try:
                sched.submit(workload[0][0], SamplingParams(
                    max_new_tokens=48, seed=100 + k), deadline_s=1e-4)
            except AdmissionRejectedError:
                rejected += 1
        outcomes = {"ok": 0, "failed": 0}
        for h in handles:
            try:
                h.result(timeout=300)
                outcomes["ok"] += 1
            except (RuntimeError, OSError):
                outcomes["failed"] += 1
        # post-chaos probe: faults off, the engine must serve cleanly
        faults.reset()
        post_ok = False
        try:
            post = sched.submit(workload[0][0], SamplingParams(
                max_new_tokens=8, seed=999), deadline_s=60.0)
            post_ok = len(post.result(timeout=60)) == 8
        except (RuntimeError, OSError):
            post_ok = False
        sup.stop(join_timeout_s=30)
        sched.shutdown(finish_running=False)
        head = metrics.headline()
        metrics.close()
        return {
            "requests_ok": outcomes["ok"],
            "requests_failed_typed": outcomes["failed"],
            "shed_at_admission": rejected,
            "requests_shed": head["requests_shed"],
            "requests_quarantined": head["requests_quarantined"],
            "engine_restarts": sup.restarts,
            "post_chaos_request_ok": post_ok,
            "tokens_per_s": head["tokens_per_s"],
            "ttft_p50_s": head["ttft_p50_s"],
            "ttft_p95_s": head["ttft_p95_s"],
            "ttft_p99_s": head["ttft_p99_s"],
            "token_lat_p50_s": head["token_lat_p50_s"],
            "token_lat_p95_s": head["token_lat_p95_s"],
            "token_lat_p99_s": head["token_lat_p99_s"],
        }

    # warm the global program LRUs — one request PER PREFILL BUCKET the
    # workload can hit, so neither arm's tail latency absorbs a compile
    warm_sched = Scheduler(engine_factory(), max_queue=8)
    warm = [warm_sched.submit(np.ones(n, np.int32),
                              SamplingParams(max_new_tokens=4))
            for n in (4, 8, 16, 31)]
    while any(w.status.value in ("queued", "running") for w in warm):
        warm_sched.step()

    clean = run_arm("")
    # delay every decode dispatch 20 ms + one 4 s hang mid-run (the 1 s
    # watchdog reaps it; the abandoned thread wakes while the arm is
    # still running and is discarded by the scheduler epoch)
    faulted = run_arm("serve.decode:delay=0.02,serve.decode:hang=4@9")
    return {
        "metric": "serving_under_faults_tail_latency",
        "workload": (f"{n_req} requests, distinct (prompt_len in [4,32), "
                     f"max_new in [8,24)) signatures, gpt "
                     f"{cfg.n_layer}L/{cfg.n_embd}d block "
                     f"{cfg.block_size}, {num_slots} slots, chunk 2, "
                     f"watchdog 1s"),
        "fault_spec": "serve.decode:delay=0.02 + serve.decode:hang=4@9",
        "clean": clean,
        "faulted": faulted,
        "recovered": bool(faulted["engine_restarts"] >= 1
                          and faulted["post_chaos_request_ok"]),
    }


def measure_fleet() -> dict:
    """The ISSUE 8 rider: the 2-replica fleet under fire — (a) a
    replica KILLED mid-stream under concurrent traffic (hard engine
    death: every dispatch raises, restart budget 0) with every client
    request still answered via sibling failover, and (b) a rolling
    weight HOT-SWAP under sustained traffic with zero failed requests,
    zero XLA recompiles (pinned by the device-program registry's build
    counter) and post-swap generations provably from the new params.
    Host-side by construction; always CPU-forced like --chaos-only."""
    import concurrent.futures
    import tempfile
    import threading

    import numpy as np

    from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
    from gym_tpu.programs import compile_counter
    from gym_tpu.serve.engine import InferenceEngine, SamplingParams
    from gym_tpu.serve.metrics import ServeMetrics
    from gym_tpu.serve.router import build_fleet

    import jax

    n_req = int(os.environ.get("GYM_TPU_BENCH_FLEET_REQUESTS", 16))
    cfg = GPTConfig(block_size=128, vocab_size=65, n_layer=2, n_head=2,
                    n_embd=64, dropout=0.0, bias=True)
    model = GPT(cfg)
    params_a = model.init({"params": jax.random.PRNGKey(0)},
                          np.zeros((1, 8), np.int64), train=False)["params"]
    params_b = model.init({"params": jax.random.PRNGKey(7)},
                          np.zeros((1, 8), np.int64), train=False)["params"]

    rng = np.random.default_rng(0)
    workload = [
        (rng.integers(0, cfg.vocab_size, int(rng.integers(4, 24))),
         SamplingParams(max_new_tokens=int(rng.integers(12, 28)),
                        temperature=0.9, top_k=16, seed=i))
        for i in range(n_req)]

    def serve_all(router, wl, kill_after=None):
        """Drive the workload through handler-thread-style clients;
        optionally hard-kill the busiest replica once `kill_after`
        requests have completed. Returns (ok, failed, wall_s)."""
        done = {"n": 0}

        def client(arg):
            prompt, sp = arg
            try:
                fr = router.submit(prompt, sp, timeout=60.0,
                                   deadline_s=120.0)
                toks = fr.result(timeout=120.0)
                done["n"] += 1
                return len(toks) == sp.max_new_tokens
            except (RuntimeError, OSError):
                return False

        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(4) as ex:
            futs = [ex.submit(client, w) for w in wl]
            if kill_after is not None:
                while done["n"] < kill_after:
                    time.sleep(0.01)
                victim = max(router.replicas,
                             key=lambda r: r.scheduler.backlog_tokens())

                def boom(*a, **k):
                    raise RuntimeError(
                        "bench: injected hard engine death")

                victim.scheduler.engine.step = boom
            results = [f.result() for f in futs]
        ok = sum(results)
        return ok, len(results) - ok, time.perf_counter() - t0

    def fresh_router(max_restarts):
        m = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_fleet_"),
                         engine_log_every=10)
        r = build_fleet(params_a, cfg, replicas=2, num_slots=4,
                        decode_chunk=2, max_restarts=max_restarts,
                        dispatch_timeout_s=5.0, metrics=m,
                        weights_tag="v1",
                        log=lambda *a, **k: None).start()
        return r, m

    # warm the programs once so neither arm absorbs a compile
    warm, wm = fresh_router(max_restarts=2)
    serve_all(warm, workload[:4])
    warm.close(drain_deadline_s=30)
    wm.close()

    # arm (a): replica kill mid-traffic, restart budget exhausted
    router, m = fresh_router(max_restarts=0)
    ok, failed, wall = serve_all(router, workload, kill_after=2)
    kill_status = router.status()
    assert kill_status["failovers"] >= 1, kill_status
    assert sum(r["dead"] for r in kill_status["replicas"]) == 1, \
        kill_status
    kill_arm = {
        "requests_ok": ok,
        "requests_failed": failed,
        "failovers": kill_status["failovers"],
        "dead_replicas": sum(r["dead"]
                             for r in kill_status["replicas"]),
        "tok_s": round(sum(sp.max_new_tokens
                           for _, sp in workload) / wall, 1),
    }
    router.close(drain_deadline_s=30)
    m.close()

    # arm (b): rolling hot-swap under sustained traffic
    router, m = fresh_router(max_restarts=2)
    probe = workload[0]
    ref_b = generate_fast(params_b, cfg, probe[0][None],
                          probe[1].max_new_tokens, temperature=0.9,
                          top_k=16, seed=probe[1].seed
                          )[0, len(probe[0]):].tolist()
    compiles_before = compile_counter()
    reload_result = {}

    def do_reload():
        time.sleep(0.15)      # let traffic occupy both replicas first
        reload_result.update(router.reload(params_b, weights_tag="v2",
                                           drain_timeout_s=60.0))

    swapper = threading.Thread(target=do_reload)
    swapper.start()
    ok, failed, wall = serve_all(router, workload * 2)
    swapper.join(timeout=120)
    compiles_after = compile_counter()
    fr = router.submit(probe[0], probe[1], timeout=60.0)
    post_tokens = fr.result(timeout=120.0)
    assert failed == 0, f"hot-swap dropped {failed} requests"
    assert sorted(reload_result.get("swapped", [])) == [0, 1], \
        reload_result
    assert compiles_after == compiles_before, (
        f"hot-swap recompiled: {compiles_after - compiles_before} "
        f"new program(s)")
    assert post_tokens == ref_b, "post-swap tokens not from new params"
    swap_arm = {
        "requests_ok": ok,
        "requests_failed": failed,
        "reload_wall_s": reload_result.get("wall_s"),
        "swapped_replicas": reload_result.get("swapped"),
        "recompiles_during_swap": compiles_after - compiles_before,
        "post_swap_params_verified": post_tokens == ref_b,
        "tok_s": round(sum(sp.max_new_tokens
                           for _, sp in workload * 2) / wall, 1),
    }
    router.close(drain_deadline_s=30)
    m.close()

    # arm (c): the OUT-OF-PROCESS A/B (ISSUE 13) — aggregate tok/s for
    # 2 in-process thread replicas vs 2 worker SUBPROCESSES, streamed
    # end to end, on the paged 2-slot config where per-token host work
    # (paged block bookkeeping, stream fan-out, scheduler loops) is a
    # first-order cost: that host work shares ONE GIL in the thread
    # fleet and parallelizes across processes in the subprocess fleet —
    # the honest 2-core parallelism win. Protocol per the perf-noise
    # convention: both arms fully warmed (the thread arm seeds the
    # persistent program tier, so workers spawn at programs_compiled=0),
    # 5 interleaved passes with alternating order, MEDIANS reported.
    # The streamed passes also yield the TTFB observable: p99 time to
    # FIRST BYTE (first chunk at the client) sits next to p99 TTFT
    # (first token in the engine) and must track it — NOT completion
    # time, which is what `/generate` cost before streaming.
    import statistics

    from gym_tpu import programs as programs_mod
    from gym_tpu.serve.router import build_process_fleet

    cache_dir = tempfile.mkdtemp(prefix="gym_tpu_fleet_cache_")
    programs_mod.enable_disk_tier(cache_dir)
    ab_rng = np.random.default_rng(1)
    ab_wl = [
        (ab_rng.integers(0, cfg.vocab_size,
                         int(ab_rng.integers(16, 48))),
         SamplingParams(max_new_tokens=int(ab_rng.integers(12, 28)),
                        temperature=0.9, top_k=16, seed=100 + i))
        for i in range(48)]
    ab_tokens = sum(sp.max_new_tokens for _, sp in ab_wl)
    ab_kw = dict(replicas=2, num_slots=2, decode_chunk=1, max_queue=64,
                 page_size=16, kv_pages=64, dispatch_timeout_s=60.0)

    def run_streamed(router, wl, collect=None):
        """Drive the workload through streaming clients; optionally
        collect (ttfb, ttft, completion) triples. Returns wall_s."""

        def client(arg):
            prompt, sp = arg
            fr = router.submit(prompt, sp, timeout=120.0)
            got = 0
            for chunk in fr.stream(timeout=180.0):
                got += len(chunk)
            if collect is not None and fr.ttft_s is not None:
                done = getattr(fr, "done_frame", None) or {}
                ttft = done.get("ttft_s") or fr.ttft_s
                collect.append((fr.ttft_s, ttft,
                                fr.done_t - fr.submit_t))
            return got == sp.max_new_tokens

        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(6) as ex:
            oks = list(ex.map(client, wl))
        assert all(oks), "process-fleet A/B dropped a stream"
        return time.perf_counter() - t0

    tm = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_abt_"),
                      engine_log_every=10)
    thread_router = build_fleet(
        params_a, cfg, paged=True, metrics=tm,
        log=lambda *a, **k: None, **ab_kw).start()
    run_streamed(thread_router, ab_wl)     # warm + seed the disk tier
    run_streamed(thread_router, ab_wl)
    pm = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_abp_"),
                      engine_log_every=10)
    proc_router = build_process_fleet(
        params_a, cfg, tempfile.mkdtemp(prefix="gym_tpu_abf_"),
        metrics=pm, program_cache_dir=cache_dir, no_warmup=True,
        log=lambda *a, **k: None, **ab_kw)
    proc_router.start()
    proc_router.wait_ready(timeout_s=240)
    run_streamed(proc_router, ab_wl)       # warm the wire path
    run_streamed(proc_router, ab_wl)
    lat = []       # (ttfb, ttft, completion) from proc streamed passes
    t_rates, p_rates = [], []
    for i in range(5):
        arms = ([("p", proc_router), ("t", thread_router)]
                if i % 2 == 0 else
                [("t", thread_router), ("p", proc_router)])
        for tag, r in arms:
            wall = run_streamed(r, ab_wl,
                                collect=lat if tag == "p" else None)
            (p_rates if tag == "p" else t_rates).append(
                ab_tokens / wall)
    thread_tok_s = statistics.median(t_rates)
    proc_tok_s = statistics.median(p_rates)
    ttfbs = np.asarray([x[0] for x in lat])
    ttfts = np.asarray([x[1] for x in lat])
    comps = np.asarray([x[2] for x in lat])
    p99_ttfb = float(np.percentile(ttfbs, 99))
    p99_ttft = float(np.percentile(ttfts, 99))
    p99_completion = float(np.percentile(comps, 99))
    p50_completion = float(np.percentile(comps, 50))
    # PER-REQUEST delta between first byte at the client and first
    # token in the engine: wire + dispatch overhead only. Tail-vs-tail
    # comparisons use the same request population on both sides.
    delta_med = float(np.median(ttfbs - ttfts))
    # structural: streamed TTFB is FIRST-TOKEN time, not completion
    # time — the whole point of streaming. It must track TTFT (a small
    # per-request wire/dispatch delta; tails aligned) and precede the
    # completion tail.
    assert delta_med <= 0.1, (
        f"median TTFB-TTFT delta {delta_med:.3f}s — chunk delivery is "
        f"lagging the engine")
    assert p99_ttfb <= p99_ttft * 1.5 + 0.2, (
        f"p99 TTFB {p99_ttfb:.3f}s does not track p99 TTFT "
        f"{p99_ttft:.3f}s")
    assert p99_ttfb < p99_completion, (
        f"p99 TTFB {p99_ttfb:.3f}s not under p99 completion "
        f"{p99_completion:.3f}s — streaming is buffering")
    proc_status = proc_router.status()
    worker_compiles = [r.get("programs_compiled")
                       for r in proc_status["replicas"]
                       if not r["retired"]]
    thread_router.close(drain_deadline_s=30)
    proc_router.close(drain_deadline_s=30)
    tm.close()
    pm.close()
    process_ab = {
        "status": "measured",
        "measured": True,
        "workload": ("48 streamed requests (prompt_len in [16,48), "
                     "max_new in [12,28)), paged page 16, 2 replicas "
                     "x 2 slots, chunk 1, 6 client threads; medians "
                     "of 5 interleaved passes after 2 warm passes "
                     "per arm"),
        "thread_fleet_tok_s": round(thread_tok_s, 1),
        "process_fleet_tok_s": round(proc_tok_s, 1),
        "process_over_thread": round(proc_tok_s / thread_tok_s, 3),
        "p99_ttfb_s": round(p99_ttfb, 5),
        "p99_ttft_s": round(p99_ttft, 5),
        "ttfb_minus_ttft_median_s": round(delta_med, 5),
        "p99_completion_s": round(p99_completion, 5),
        "p50_completion_s": round(p50_completion, 5),
        "worker_programs_compiled": worker_compiles,
        "streams_spliced_failovers": proc_status["failovers"],
    }

    return {
        "metric": "fleet_failover_and_hot_swap",
        "status": "measured",
        "measured": True,
        "workload": (f"{n_req} requests (prompt_len in [4,24), max_new "
                     f"in [12,28)), gpt {cfg.n_layer}L/{cfg.n_embd}d "
                     f"block {cfg.block_size}, 2 replicas x 4 slots, "
                     f"chunk 2"),
        "replica_kill": kill_arm,
        "hot_swap": swap_arm,
        "process_ab": process_ab,
    }


def measure_tracesim() -> dict:
    """The ISSUE 15 acceptance bench: sim-vs-live agreement on one
    trace × policy point. The SAME seeded flash-crowd trace (deep
    overload: the flash offers ~2× the replica's capacity, every
    request deadlined — admission control and queue sheds both fire)
    runs through (a) a REAL single-replica fleet via the open-loop
    replayer and (b) the discrete-event cost model over a calibrated
    ``ServiceProfile`` (two-point slope/intercept + saturated-burst
    aggregate). Gate: the model's p99 TTFT within [0.5×, 2×] of live
    (or 0.3 s absolute) and shed rate within 0.15 absolute — the
    agreement contract that makes ``servesim/sweep.py``'s policy
    frontier trustworthy. Both arms ``status=measured``; host-side by
    construction (CPU-forced like --chaos-only)."""
    import tempfile

    import numpy as np

    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.serve.engine import SamplingParams
    from gym_tpu.serve.metrics import ServeMetrics
    from gym_tpu.serve.router import build_fleet
    from gym_tpu.servesim import (FleetCostModel, calibrate_router,
                                  flash_crowd_trace, replay_router)

    import jax

    cfg = GPTConfig(block_size=128, vocab_size=48, n_layer=4, n_head=4,
                    n_embd=128, dropout=0.0, bias=True)
    params = GPT(cfg).init({"params": jax.random.PRNGKey(0)},
                           np.zeros((1, 8), np.int64),
                           train=False)["params"]
    metrics = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_tsim_"),
                           engine_log_every=10)
    router = build_fleet(params, cfg, replicas=1, num_slots=1,
                         decode_chunk=1, metrics=metrics,
                         log=lambda *a, **k: None).start()
    # warm every prefill bucket the trace can hit (8/16/32) — a compile
    # inside the replay would poison BOTH the live tail and the
    # calibration the model is anchored to
    for n in (8, 16, 32):
        router.submit(np.arange(1, n + 1, dtype=np.int32) % 48,
                      SamplingParams(max_new_tokens=8, seed=n)
                      ).result(timeout=300)
    profile = calibrate_router(router, 48, num_slots=1,
                               saturate_burst=8)

    trace = flash_crowd_trace(
        duration_s=24, base_rps=1.5, flash_at_s=6, flash_mult=24,
        flash_len_s=6, seed=5, prompt_lens=(8, 32), max_news=(24, 56),
        deadline_s=1.5, deadline_frac=1.0)
    live = replay_router(router, trace, vocab_size=48,
                         time_scale=1.0)["report"]
    router.close(drain_deadline_s=60)
    metrics.close()

    model = FleetCostModel(profile, initial_replicas=1,
                           autoscale=False).run(trace).report()

    # the stated tolerances (the ci_deploy gate):
    p99_l, p99_m = live["ttft_p99_s"], model["ttft_p99_s"]
    shed_l, shed_m = live["shed_rate"], model["shed_rate"]
    ttft_ok = (p99_l is not None and p99_m is not None
               and (abs(p99_m - p99_l) <= 0.3
                    or 0.5 <= p99_m / p99_l <= 2.0))
    shed_ok = abs(shed_m - shed_l) <= 0.15
    agreement = {
        "ok": bool(ttft_ok and shed_ok),
        "ttft_ok": bool(ttft_ok),
        "shed_ok": bool(shed_ok),
        "tolerance": ("model p99 TTFT within [0.5x, 2x] of live or "
                      "0.3s abs; shed rate within 0.15 abs"),
        "p99_ttft_ratio": (round(p99_m / p99_l, 3)
                           if p99_l and p99_m else None),
        "shed_rate_delta": round(abs(shed_m - shed_l), 4),
    }
    assert agreement["ok"], {"agreement": agreement,
                             "live": live, "model": model}
    return {
        "metric": "tracesim_live_p99_ttft_s",
        "status": "measured",
        "measured": True,
        # the --compare headline: LIVE p99 TTFT under the overload
        # trace (lower is better, like the coldstart metric)
        "value": p99_l,
        "unit": "s_p99_ttft_live_lower_is_better",
        "workload": ("flash-crowd trace: 24s, base 1.5 rps, 24x flash "
                     "for 6s, prompt [8,32), max_new [24,56), 1.5s "
                     "deadline on all; 1 replica x 1 slot chunk 1, "
                     "gpt 4L/128d block 128; open-loop replay vs "
                     "cost model on the calibrated profile"),
        "requests": live["requests"],
        "profile": {
            "tokens_per_s": round(profile.tokens_per_s, 1),
            "request_overhead_s": round(profile.request_overhead_s, 5),
        },
        "live": live,
        "model": model,
        "agreement": agreement,
    }


def measure_analysis() -> dict:
    """Static-analysis summary (ISSUE 6): the full suite — lint, static
    trace reconciliation, jaxpr audit — as one JSON line, the
    machine-readable twin of `python -m gym_tpu.analysis`. Pure host
    tracing; 'violations' == 0 is the shipped-tree invariant."""
    from gym_tpu.analysis.__main__ import run_all

    report = run_all()
    sections = report["sections"]
    trace = sections["trace"]["strategies"]
    return {
        "violations": report["violations"],
        "lint_total": sections["lint"]["total"],
        "lint_suppressed": sections["lint"]["suppressed"],
        "strategies_reconciled": sum(1 for s in trace.values() if s["ok"]),
        "strategies_checked": len(trace),
        "programs_audited": len(sections["audit"]["programs"]),
        "program_keys": sections["audit"]["recompile_guard"]["n_keys"],
        "seconds": round(sum(s.get("seconds", 0)
                             for s in sections.values()), 2),
    }


def measure_elastic() -> dict:
    """The Elastic ZeRO acceptance bench (ROADMAP: Elastic ZeRO): the
    sweep's 2-layer GPT workload trained for real, measured three ways —
    (a) live per-node optimizer-state bytes, ZeRO-sharded vs replicated
    AdamW at K nodes (the ÷K headline, read off the final device
    state); (b) on-disk checkpoint bytes, the ZeRO-2 sharded layout vs
    the stacked replicated layout (one K-node fit each, same steps);
    (c) the membership change itself: ``fit(resume="auto",
    num_nodes=K-1)`` over the K-sharded checkpoint (restore → collective
    reshard → finish the last step) vs a cold restart replaying every
    step from 0. Both timing arms run twice; the warm pass — persistent
    compile cache hit, registry hot — is the steady-state number an
    autoscale-driven membership change sees. Host-side by construction
    (vnode-folded CPU mesh, like --sim-only); every arm is a real fit,
    status=measured."""
    import contextlib
    import tempfile
    import time

    import numpy as np

    from gym_tpu.data import ArrayDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy import (OptimSpec, SimpleReduceStrategy,
                                  ZeroReduceStrategy)
    from gym_tpu.trainer import Trainer

    import jax

    k = int(os.environ.get("GYM_TPU_BENCH_ELASTIC_NODES", 4))
    k_new = k - 1
    steps = int(os.environ.get("GYM_TPU_BENCH_ELASTIC_STEPS", 30))
    interval = 10
    cfg_m = GPTConfig(block_size=64, vocab_size=65, n_layer=2, n_head=2,
                      n_embd=64, dropout=0.0, bias=True, attn_impl="dense")
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 65, (2048, 65), dtype=np.int64)
    ds = ArrayDataset(np.ascontiguousarray(toks[:, :-1]),
                      np.ascontiguousarray(toks[:, 1:]))

    root = (os.environ.get("GYM_TPU_BENCH_ELASTIC_DIR")
            or tempfile.mkdtemp(prefix="gym_tpu_elastic_bench_"))
    common = dict(batch_size=16, minibatch_size=16, val_interval=0,
                  show_progress=False, seed=3, checkpoint_interval=interval,
                  async_checkpoint=False, devices=[0, 1],
                  log_dir=os.path.join(root, "logs"))

    def leaf_bytes(tree):
        return int(sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree)))

    def du(path):
        return sum(os.path.getsize(os.path.join(d, f))
                   for d, _, files in os.walk(path) for f in files)

    def fit(**kw):
        t0 = time.perf_counter()
        with contextlib.redirect_stdout(sys.stderr):  # stdout: 1 JSON line
            res = Trainer(GPT(cfg_m), ds).fit(**kw)
        return res, round(time.perf_counter() - t0, 3)

    adamw = lambda: OptimSpec("adamw", lr=1e-3)
    # (a)+(b): one K-node fit per layout — live opt-state bytes off the
    # final device state, checkpoint bytes off the written tree
    res_z, _ = fit(strategy=ZeroReduceStrategy(adamw()), num_nodes=k,
                   max_steps=steps, run_name="el",
                   save_dir=os.path.join(root, "zero"), **common)
    res_r, _ = fit(strategy=SimpleReduceStrategy(adamw()), num_nodes=k,
                   max_steps=steps, run_name="el_repl",
                   save_dir=os.path.join(root, "repl"), **common)
    n_params = int(sum(x.size for x in jax.tree.leaves(res_z.params)))
    opt_z = leaf_bytes(res_z.node_state.strategy_state) // k
    opt_r = leaf_bytes(res_r.node_state.strategy_state) // k
    ckpt_z, ckpt_r = du(os.path.join(root, "zero")), du(
        os.path.join(root, "repl"))
    # the O(model/K) invariant, asserted on the measured bytes (padding
    # and the scalar count leave a little slack below the ideal ÷K; the
    # on-disk ratio additionally absorbs fixed per-checkpoint metadata
    # a 108K-param payload does not amortize)
    assert opt_r / opt_z > k - 1, (opt_r, opt_z, k)
    assert ckpt_r / ckpt_z > 1.5, (ckpt_r, ckpt_z, k)

    # (c) membership change: resume the ZeRO-2 checkpoint at K-1 (1 step
    # past the durable save) vs retraining those steps from scratch.
    # Twice each — on a fresh COPY of the sharded tree per resume, since
    # a finished resume writes its own final K'-shaped checkpoint; the
    # warm pass is the autoscaler's steady state. The cold arm
    # checkpoints at the same interval (a real restart re-saves too).
    import shutil

    times = {}
    for arm in ("cold_first", "cold_warm"):
        res_c, times[arm] = fit(strategy=ZeroReduceStrategy(adamw()),
                                num_nodes=k_new, max_steps=steps + 1,
                                run_name=arm,
                                save_dir=os.path.join(root, arm), **common)
        assert res_c.steps == steps + 1
    for arm in ("reshard_first", "reshard_warm"):
        arm_dir = os.path.join(root, arm)
        shutil.copytree(os.path.join(root, "zero"), arm_dir)
        res_e, times[arm] = fit(strategy=ZeroReduceStrategy(adamw()),
                                num_nodes=k_new, max_steps=steps + 1,
                                resume="auto", run_name="el",
                                save_dir=arm_dir, **common)
        assert res_e.steps == steps + 1
        # resumed at the durable step-6 save, did not replay from 0
        assert res_e.history["train_loss"][0][0] == steps, (
            res_e.history["train_loss"])
    # the acceptance claim, on the measured clocks: resharding beats
    # replaying the lost steps
    assert times["reshard_warm"] < times["cold_warm"], times
    speedup = round(times["cold_warm"] / times["reshard_warm"], 2)
    return {
        "metric": "elastic_zero_reshard_vs_cold_restart_speedup",
        "status": "measured",
        "measured": True,
        "value": speedup,
        "unit": "x_warm_wall_clock_higher_is_better",
        "workload": (f"2-layer GPT (n_embd=64, block 64, {n_params} "
                     f"params), {k} nodes vnode-folded on 2 CPU "
                     f"devices, {steps} steps, ckpt interval "
                     f"{interval}; membership change {k}->{k_new}"),
        "nodes": k,
        "nodes_after": k_new,
        "n_params": n_params,
        "opt_state_bytes_per_node": {
            "replicated_adamw": opt_r,
            "zero_sharded": opt_z,
            "reduction": round(opt_r / opt_z, 2),
        },
        "ckpt_bytes": {
            "stacked_replicated": ckpt_r,
            "zero2_sharded": ckpt_z,
            "reduction": round(ckpt_r / ckpt_z, 2),
        },
        "membership_change": {
            "reshard_resume_s": times["reshard_warm"],
            "reshard_resume_first_s": times["reshard_first"],
            "cold_restart_s": times["cold_warm"],
            "cold_restart_first_s": times["cold_first"],
            "steps_replayed_cold": steps,
            "steps_replayed_reshard": 0,
            "speedup": speedup,
        },
        "out_dir": root,
    }


def measure_tenant() -> dict:
    """The ISSUE 17 rider: tenant isolation, measured — the SAME
    noisy-neighbor workload (tenant B's batch flood already decoding
    when tenant A's interactive requests arrive) served twice:

    - ``baseline``: isolation OFF (no quotas, no preemption) — the
      victim's TTFT is whatever slot the flood deigns to free;
    - ``isolated``: isolation ON (batch token quota + preemptible
      decode) — arrivals park a flood slot at a chunk boundary and the
      quota sheds the flood's tail typed (429 + Retry-After).

    Reports the victim's TTFT tail in both arms plus preempt / shed
    counters. Two structural asserts ride in the bench itself: (1) the
    victim's p99 TTFT under isolation stays within 5% of the baseline
    (in practice it collapses — the improvement factor is the
    headline), and (2) EVERY completed stream — including every
    preempted-then-resumed batch request — equals its solo
    ``generate_fast`` run token-for-token, so the park/resume
    round-trip is provably invisible. Host-side by construction;
    always CPU-forced like --chaos-only."""
    import numpy as np

    from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
    from gym_tpu.serve.engine import InferenceEngine, SamplingParams
    from gym_tpu.serve.scheduler import (ClassQuota, QuotaExceededError,
                                         RequestStatus, Scheduler)

    import jax

    n_flood = int(os.environ.get("GYM_TPU_BENCH_TENANT_FLOOD", 6))
    n_victims = int(os.environ.get("GYM_TPU_BENCH_TENANT_VICTIMS", 6))
    flood_new, victim_new = 48, 8
    cfg = GPTConfig(block_size=128, vocab_size=65, n_layer=2, n_head=2,
                    n_embd=64, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64), train=False)["params"]
    engine_kw = dict(num_slots=2, paged=True, page_size=16, kv_pages=64)

    rng = np.random.default_rng(17)
    flood_wl = [(rng.integers(0, cfg.vocab_size, int(rng.integers(16, 32))),
                 SamplingParams(max_new_tokens=flood_new, temperature=0.9,
                                top_k=16, seed=i))
                for i in range(n_flood)]
    victim_wl = [(rng.integers(0, cfg.vocab_size, 8),
                  SamplingParams(max_new_tokens=victim_new,
                                 temperature=0.9, top_k=16, seed=100 + i))
                 for i in range(n_victims)]
    # the exactness oracle: every request's solo generate_fast stream
    refs = {id(sp): generate_fast(params, cfg, p[None],
                                  sp.max_new_tokens, temperature=0.9,
                                  top_k=16, seed=sp.seed)[0, len(p):]
            .tolist() for p, sp in flood_wl + victim_wl}

    def run_arm(isolated: bool) -> dict:
        eng = InferenceEngine(params, cfg, **engine_kw)
        # quota: cap = 48 tok/s x 4 s burst = 192 tokens — admits 4 of
        # the 6 flood submissions back-to-back, sheds the tail typed
        sched = Scheduler(
            eng, max_queue=64,
            quotas=({"batch": ClassQuota(tokens_per_s=48.0, burst_s=4.0)}
                    if isolated else None),
            preempt=isolated)
        flood, shed = [], 0
        for p, sp in flood_wl:
            try:
                flood.append(sched.submit(p, sp, tenant="tenant_b",
                                          slo_class="batch"))
            except QuotaExceededError:
                shed += 1
        for _ in range(2000):
            sched.step()
            if flood and len(flood[0].tokens) >= 4:
                break
        victims = []
        for p, sp in victim_wl:
            victims.append(sched.submit(p, sp, tenant="tenant_a",
                                        slo_class="interactive"))
            for _ in range(4):
                sched.step()
        for _ in range(20000):
            if all(h.status in (RequestStatus.DONE, RequestStatus.FAILED)
                   for h in flood + victims):
                break
            sched.step()
        # quota sheds strictly from the tail, so the admitted handles
        # line up with the workload prefix
        pairs = list(zip(flood, flood_wl)) + list(zip(victims, victim_wl))
        exact = all(h.result(timeout=1) == refs[id(sp)]
                    for h, (p, sp) in pairs)
        ttfts = sorted(h.ttft_s for h in victims)
        sched.shutdown(finish_running=False)
        return {
            "victim_ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4),
            "victim_ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4),
            "flood_shed_typed": shed,
            "flood_tokens_out": sum(len(h.tokens) for h in flood),
            "preemptions": sched.preemptions,
            "resumes": sched.resumes,
            "all_streams_exact": exact,
        }

    baseline = run_arm(isolated=False)
    isolated = run_arm(isolated=True)
    # structural asserts — an isolation bench that lets these slide is
    # measuring noise, not isolation
    assert isolated["all_streams_exact"] and baseline["all_streams_exact"], \
        "a served stream diverged from its solo generate_fast run"
    assert isolated["preemptions"] >= 1 and isolated["resumes"] >= 1, \
        "isolated arm never exercised preemptible decode"
    assert (isolated["victim_ttft_p99_s"]
            <= baseline["victim_ttft_p99_s"] * 1.05), \
        "isolation made the victim's p99 TTFT worse"
    assert isolated["flood_shed_typed"] == 2, \
        "quota admitted the wrong number of flood requests"
    return {
        "metric": "tenant_isolation_noisy_neighbor_victim_ttft_p99",
        "status": "measured",
        "measured": True,
        "workload": (f"{n_flood} batch flood (max_new {flood_new}) vs "
                     f"{n_victims} interactive victims (max_new "
                     f"{victim_new}), gpt {cfg.n_layer}L/{cfg.n_embd}d, "
                     f"2 paged slots, quota 48 tok/s x 4 s burst"),
        "baseline": baseline,
        "isolated": isolated,
        "victim_p99_improvement": round(
            baseline["victim_ttft_p99_s"]
            / max(isolated["victim_ttft_p99_s"], 1e-9), 2),
        "preempted_resume_exact": isolated["all_streams_exact"],
    }


def main() -> None:
    force_cpu = ("--cpu" in sys.argv or "--sim-only" in sys.argv
                 or "--chaos-only" in sys.argv
                 or "--fleet-only" in sys.argv
                 or "--analyze-only" in sys.argv
                 or "--coldstart-only" in sys.argv
                 or "--tracesim-only" in sys.argv
                 or "--elastic-only" in sys.argv
                 or "--tenant-only" in sys.argv
                 or "--sdc-only" in sys.argv)
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")

    # Persistent XLA compile cache: a repeated bench invocation of the
    # same program skips the ~40 s warmup compile entirely. Opt out with
    # GYM_TPU_BENCH_COMPILE_CACHE=0 (e.g. to measure cold compiles).
    # --serve-only NEVER uses it: its headline measures exactly the
    # compile behavior a serving process sees (a warm persistent cache
    # would quietly turn the cold arms warm on the second invocation).
    if (os.environ.get("GYM_TPU_BENCH_COMPILE_CACHE", "1") == "1"
            and "--serve-only" not in sys.argv
            and "--coldstart-only" not in sys.argv):
        from gym_tpu.utils.compile_cache import enable_compilation_cache
        enable_compilation_cache(os.environ.get("GYM_TPU_BENCH_CACHE_DIR"))

    if "--overlap-only" in sys.argv:
        print(json.dumps({"host_overlap": measure_host_overlap()}))
        return

    if "--resilience-only" in sys.argv:
        print(json.dumps(
            {"resilience_overhead": measure_resilience_overhead()}))
        return

    if "--sdc-only" in sys.argv:
        print(json.dumps({"sdc_guard": measure_sdc_guard()}))
        return

    if "--sim-only" in sys.argv:
        print(json.dumps({"network_sim": measure_network_sim()}))
        return

    if "--serve-only" in sys.argv:
        print(json.dumps({"serving": measure_serving()}))
        return

    if "--coldstart-only" in sys.argv:
        print(json.dumps({"coldstart": measure_coldstart()}))
        return

    if "--chaos-only" in sys.argv:
        print(json.dumps({"chaos": measure_chaos()}))
        return

    if "--fleet-only" in sys.argv:
        print(json.dumps({"fleet": measure_fleet()}))
        return

    if "--tracesim-only" in sys.argv:
        print(json.dumps({"tracesim": measure_tracesim()}))
        return

    if "--analyze-only" in sys.argv:
        print(json.dumps({"analysis": measure_analysis()}))
        return

    if "--elastic-only" in sys.argv:
        print(json.dumps({"elastic": measure_elastic()}))
        return

    if "--tenant-only" in sys.argv:
        print(json.dumps({"tenant": measure_tenant()}))
        return

    import numpy as np

    from gym_tpu.models.base import LossModel
    from gym_tpu.models.nanogpt import GPT, GPTConfig, node_mfu
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_multi_train_step

    import jax.numpy as jnp

    attn = os.environ.get("GYM_TPU_BENCH_ATTN",
                          "dense" if force_cpu else "flash")
    cfg = GPTConfig(block_size=BLOCK_SIZE, vocab_size=VOCAB, n_layer=4,
                    n_head=4, n_embd=128, dropout=0.0, bias=True,
                    attn_impl=attn)
    # bf16 forward (params stay f32; loss/softmax accumulate f32) — the
    # TPU-native analog of the reference's autocast, default ON for the
    # benchmark since MXU bf16 is the intended number format.
    bf16 = os.environ.get("GYM_TPU_BENCH_BF16", "1") == "1"
    loss_model = LossModel(GPT(cfg), jnp.bfloat16 if bf16 else None)

    spc = int(os.environ.get("GYM_TPU_BENCH_SPC", 20))
    warm_calls = max(1, WARMUP // spc)
    timed_calls = max(1, TIMED // spc)

    strategy = DiLoCoStrategy(
        optim_spec=OptimSpec("adamw", lr=3e-4), H=100,
        lr_scheduler="lambda_cosine",
        lr_scheduler_kwargs={"warmup_steps": 100},
    )
    strategy.finalize(max_steps=(warm_calls + timed_calls) * spc)

    runtime = NodeRuntime.create(NUM_NODES, jax.devices())

    # S steps per dispatch: amortizes host→device dispatch latency (large
    # over remote transports) across a lax.scan of compiled steps.
    rng = np.random.default_rng(0)
    idx = rng.integers(
        0, VOCAB, (NUM_NODES, spc, 1, BATCH_PER_NODE, BLOCK_SIZE),
        dtype=np.int64,
    )
    batches = runtime.shard_batch((idx, np.roll(idx, -1, axis=-1)))

    init_fn = make_init_fn(loss_model, strategy,
                           (idx[0, 0, 0], idx[0, 0, 0]), seed=42)
    state = runtime.init_state(init_fn)
    multi_step = runtime.compile(
        make_multi_train_step(loss_model, strategy, runtime.ctx)
    )

    for _ in range(warm_calls):
        state, metrics = multi_step(state, batches)
    # NB: device_get, not block_until_ready — some transport backends
    # (e.g. the axon tunnel) resolve block_until_ready before execution
    # finishes; fetching a value that depends on the whole step chain is
    # the only honest fence.
    float(np.asarray(metrics["loss"]).sum())

    # best-of-N windows: the remote transport adds run-to-run jitter of
    # ~±5%; max throughput over independent windows is the standard way
    # to report a device rate (each window is fenced by a value fetch).
    # CPU runs skip the extra window — the jitter source (remote
    # transport) is absent there and a CPU window takes ~40 min, so the
    # 0.008 it/s baseline stays measured the way it always was.
    default_windows = "1" if force_cpu else "2"
    windows = max(1, int(os.environ.get("GYM_TPU_BENCH_WINDOWS",
                                        default_windows)))
    best_dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(timed_calls):
            state, metrics = multi_step(state, batches)
        loss = float(np.asarray(metrics["loss"]).mean())
        best_dt = min(best_dt, time.perf_counter() - t0)

    it_s = timed_calls * spc / best_dt
    assert np.isfinite(loss), f"non-finite loss {loss}"

    baseline_env = os.environ.get("GYM_TPU_BENCH_BASELINE")
    baseline = float(baseline_env) if baseline_env else CPU_BASELINE_IT_S
    baseline_prov = ("env-override" if baseline_env
                     else CPU_BASELINE_MEASURED_AT)
    # MFU of the whole 64-node workload (seqs/iter = nodes × per-node batch)
    mfu = node_mfu(cfg, state.params, NUM_NODES * BATCH_PER_NODE, 1.0 / it_s)
    result = {
        "metric": "nanogpt_diloco_64node_iterations_per_sec",
        "status": "measured",
        "measured": True,
        "value": round(it_s, 3),
        "unit": "it/s",
        "vs_baseline": round(it_s / baseline, 2),
        "cpu_baseline_it_s": baseline,
        "cpu_baseline_measured_at": baseline_prov,
        "mfu": round(mfu, 4),
        # timing method is part of the metric's identity: values up to
        # r2 were single-window; best-of-2 removes transport jitter and
        # can read up to ~5% above the old method
        "timing": f"best_of_{windows}",
    }

    # Realistic-scale rider: GPT-2 base (124M) single-replica MFU — the
    # perf-credibility number (BENCHMARKS.md "GPT-2 base" table), measured
    # by the same code path as benchmarks/bench_gpt2_base.py. Skipped on
    # CPU (a base-model step takes minutes there). Disable with
    # GYM_TPU_BENCH_BASE=0. Failures (e.g. HBM OOM on a smaller chip)
    # must not discard the headline result above.
    if (not force_cpu and jax.devices()[0].platform != "cpu"
            and os.environ.get("GYM_TPU_BENCH_BASE", "1") == "1"):
        try:
            sys.path.insert(
                0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmarks"))
            from bench_gpt2_base import measure

            base = measure(size="base", nodes=1, batch=16, attn="flash",
                           remat=False, strategy="diloco",
                           steps=15, warmup=5, spc=5)
            result["gpt2_base_it_per_sec"] = base["value"]
            result["gpt2_base_mfu"] = base["mfu"]
            result["gpt2_base_tokens_per_sec"] = base["tokens_per_sec"]
        except Exception as e:  # noqa: BLE001 — headline must survive
            result["gpt2_base_error"] = f"{type(e).__name__}: {e}"[:200]

    # Host-overlap ablation rider (ISSUE 1): prefetch on/off A/B. On an
    # accelerator it runs in-process (the chip is single-tenant); on CPU
    # it runs in a fresh subprocess pinned to the 16-virtual-device
    # harness layout. Failures must not discard the headline result.
    if os.environ.get("GYM_TPU_BENCH_OVERLAP", "1") == "1":
        try:
            if force_cpu or jax.devices()[0].platform == "cpu":
                result["host_overlap"] = _overlap_subprocess()
            else:
                result["host_overlap"] = measure_host_overlap()
        except Exception as e:  # noqa: BLE001 — headline must survive
            result["host_overlap_error"] = f"{type(e).__name__}: {e}"[:200]

    print(json.dumps(result))


if __name__ == "__main__":
    if "--compare" in sys.argv:
        # artifact comparison is pure host-side JSON work: no jax, no
        # probe, no supervisor child
        i = sys.argv.index("--compare")
        if len(sys.argv) < i + 3:
            print(json.dumps({"mode": "compare", "comparable": False,
                              "note": "not_comparable",
                              "reason": "--compare needs two artifact "
                                        "paths"}))
            sys.exit(1)
        print(json.dumps(compare_runs(sys.argv[i + 1], sys.argv[i + 2])))
        sys.exit(0)
    if "--coldstart-worker" in sys.argv:
        # measure_coldstart's child: runs directly (the parent bench is
        # already supervised; env is prepared by measure_coldstart)
        _coldstart_worker()
        sys.exit(0)
    if os.environ.get("_GYM_TPU_BENCH_CHILD"):
        main()
    else:
        sys.exit(_supervise())
