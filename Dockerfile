# gym-tpu development/runtime container.
#
# Role parity with the reference's Dockerfile (CUDA 12.4 + torch dev
# container, /root/reference/Dockerfile:1-44), re-targeted at TPU hosts:
# on a Cloud TPU VM the TPU runtime (libtpu) is provided by the host image;
# this container carries the Python stack + native toolchain. For CPU-only
# CI the same image runs the whole test suite on a virtual 8-device mesh.
#
#   docker build -t gym-tpu .
#   docker run --rm gym-tpu pytest tests/ -q          # CPU mesh tests
#   docker run --rm --privileged --net=host \
#     -e JAX_PLATFORMS=tpu gym-tpu python bench.py     # on a TPU VM

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make git \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /workspace/gym-tpu

# Pinned stack (versions this repo is developed and benchmarked against —
# see requirements.lock). On a TPU VM install jax[tpu] instead of the CPU
# wheel: pip install 'jax[tpu]==0.9.0' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
COPY requirements.lock .
RUN pip install --no-cache-dir -r requirements.lock

COPY pyproject.toml .
COPY gym_tpu/ gym_tpu/
COPY tests/ tests/
COPY examples/ examples/
COPY benchmarks/ benchmarks/
COPY bench.py .
RUN pip install --no-cache-dir -e .

# default: prove the build works (8 virtual CPU devices, same as CI)
ENV XLA_FLAGS=--xla_force_host_platform_device_count=8
ENV JAX_PLATFORMS=cpu
CMD ["python", "-m", "pytest", "tests/", "-q"]
