#!/usr/bin/env bash
# Tier-1 gate — the EXACT command from ROADMAP.md, so builders and CI run
# the identical check. CPU-only (JAX_PLATFORMS=cpu; conftest.py adds the
# 16-virtual-device layout), quick suite (-m 'not slow'), survives
# collection errors, prints DOTS_PASSED=<n> for trend tracking.
#
# Usage: scripts/ci_tier1.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
