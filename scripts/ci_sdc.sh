#!/usr/bin/env bash
# Silent-data-corruption gate (ISSUE 20) — the ninth gate, run NEXT TO
# scripts/ci_tier1.sh and the others. End-to-end integrity defense:
#
#   1. the integrity unit suite (tests/test_integrity.py): crc32c,
#      checkpoint sidecars written-at-save / verified-at-restore with
#      quarantine through `.corrupt-k` (collisions included), per-frame
#      wire crc with typed FrameCorruptError, the corruption fault
#      actions (bitflip/truncate) at checkpoint.bytes / wire.frame /
#      dispatch.state, and the watchdog's in-flight program attribution;
#   2. the training guard (tests/test_guard_rollback.py): anomaly
#      detection (non-finite / EWMA spike on the worst-node loss /
#      state-fingerprint drift) and rollback-and-replay whose oracle is
#      a train.csv BYTE-IDENTICAL to an uninterrupted run;
#   3. seeded chaos campaigns (tests/test_chaos_campaign.py): >= 5
#      seeds of random fault mixes over every compatible train-pipeline
#      site driven through the subprocess kill-harness worker — no
#      silent divergence, every failure typed, recovery completes;
#   4. wire-corruption failover (tests/test_sdc_wire_failover.py): a
#      replica emitting bit-flipped frames dies TYPED and the stream
#      completes byte-exact through the sibling — never a wrong token.
#
# CPU-only, sized for the 2-core container (suite runs in ~2 min warm;
# the timeout leaves headroom for cold compile caches).
#
# Usage: scripts/ci_sdc.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_sdc.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_integrity.py tests/test_guard_rollback.py \
    tests/test_chaos_campaign.py tests/test_sdc_wire_failover.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_sdc.log
rc=${PIPESTATUS[0]}
echo SDC_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_sdc.log | tr -cd . | wc -c)
exit $rc
