#!/usr/bin/env bash
# Run every CI gate in sequence with a per-gate pass/fail + wall-time
# summary (ISSUE 20 satellite). Exits nonzero at the FIRST failing gate
# — later gates are reported as skipped so the summary still prints.
#
# Order: tier1 first (the broad net), then the per-subsystem gates
# roughly by how much earlier-gate machinery they lean on.
#
# Usage: scripts/ci_all.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."

GATES=(tier1 faults sim serve chaos analyze deploy elastic sdc)

declare -A RESULT TIME
failed=""
for g in "${GATES[@]}"; do
    if [ -n "$failed" ]; then
        RESULT[$g]="skipped"
        TIME[$g]="-"
        continue
    fi
    echo "==== gate: $g ===================================================="
    t0=$SECONDS
    "scripts/ci_${g}.sh"
    rc=$?
    TIME[$g]=$((SECONDS - t0))
    if [ $rc -eq 0 ]; then
        RESULT[$g]="pass"
    else
        RESULT[$g]="FAIL (rc=$rc)"
        failed=$g
    fi
done

echo
echo "==== gate summary ================================================="
for g in "${GATES[@]}"; do
    printf '  %-8s %-12s %ss\n' "$g" "${RESULT[$g]}" "${TIME[$g]}"
done
if [ -n "$failed" ]; then
    echo "FIRST FAILING GATE: $failed"
    exit 1
fi
echo "ALL ${#GATES[@]} GATES GREEN"
