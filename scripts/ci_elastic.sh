#!/usr/bin/env bash
# Elastic-membership gate (ROADMAP: Elastic ZeRO) — the 8th CI gate,
# run NEXT TO ci_tier1/ci_faults/ci_sim/ci_serve/ci_chaos/ci_deploy/
# ci_analyze. Three layers:
#
# 1. the elastic suites: K→K'→K redistribution bit-identity (params AND
#    optimizer state, padded tail included, uneven K', vnode-folded
#    mesh), the typed mismatched-K error, zero recompiles on re-reshard
#    at a warm registry, the O(model/K) sharded-checkpoint bytes — plus
#    the kill drill: train → SIGKILL at a dispatch boundary → resume at
#    K-1 and K+1 → tolerance-bounded loss, pre-kill CSV rows verbatim.
# 2. the jaxpr audit restricted to the elastic redistribution programs:
#    registered under canonical keys, donation-clean, callback-free,
#    zero violations.
# 3. the deterministic reshard-vs-cold-restart frontier gate against the
#    committed baseline (logs/frontier/elastic_frontier.json).
#
# CPU-only, sized for the 2-core container (~2 min).
#
# Usage: scripts/ci_elastic.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_elastic.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_elastic.py tests/test_elastic_drill.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_elastic.log
rc=${PIPESTATUS[0]}
echo ELASTIC_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_elastic.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# the reshard program family audits clean: canonical registry keys,
# nothing donated (checkpoint host arrays), no callbacks, no f64
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
from gym_tpu.analysis.jaxpr_audit import (audit_program,
                                          elastic_program_specs)
audits = [audit_program(s) for s in elastic_program_specs()]
assert len(audits) >= 6, [a.name for a in audits]
bad = {a.name: a.findings for a in audits if a.findings}
assert not bad, bad
print(f"ci_elastic: {len(audits)} reshard programs audit clean "
      "(violations=0)")
EOF
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# deterministic membership-event frontier vs the committed baseline
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m gym_tpu.sim.elastic_frontier \
    --baseline logs/frontier/elastic_frontier.json
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"
echo "ci_elastic: OK"
exit 0
