#!/usr/bin/env bash
# Network-simulation gate (ISSUE 3) — the sim/sweep unit suites plus one
# tiny 2-strategy × 2-topology smoke sweep through the CLI entry point,
# run NEXT TO scripts/ci_tier1.sh and scripts/ci_faults.sh. The unit
# suites pin the cost-model closed forms, per-strategy traces, and the
# trace-vs-cum_comm_bytes reconciliation on a real fit; the CLI sweep
# proves `python -m gym_tpu.sim.sweep` end to end (grid, per-cell run
# dirs, report with the DiLoCo-vs-AllReduce headline). CPU-only; the
# smoke sweep is sized for <60 s on the 2-core container.
#
# Usage: scripts/ci_sim.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."

rm -f /tmp/_sim.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_sim.py tests/test_sweep.py tests/test_compress.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_sim.log
rc=${PIPESTATUS[0]}
echo SIM_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_sim.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# CLI smoke sweep: fresh out dir (a stale one would resume-skip every
# cell and test nothing), 4 strategies × 2 topologies, tiny steps —
# including the ISSUE 10 low-communication cells (noloco gossip,
# dynamiq-int8 compressed all-reduce) and the ISSUE 12 codec axis
# (dense + int4 cells for the CompressedLink family).
SWEEP_OUT=${GYM_TPU_CI_SWEEP_OUT:-/tmp/gym_tpu_ci_sweep}
rm -rf "$SWEEP_OUT"
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m gym_tpu.sim.sweep \
    --preset wan,datacenter \
    --strategies diloco,simple_reduce,noloco,dynamiq_int8 \
    --codecs dense,int4 \
    --nodes 2 --steps 8 --batch_size 4 --block_size 32 \
    --n_layer 1 --n_embd 32 --out "$SWEEP_OUT"
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"
grep -q "Headline: DiLoCo" "$SWEEP_OUT/report.md" || {
    echo "ci_sim: sweep report missing the DiLoCo headline"; exit 1; }
grep -q "RECONCILIATION FAILURES" "$SWEEP_OUT/report.md" && {
    echo "ci_sim: trace/cum_comm_bytes reconciliation failed"; exit 1; }
# the low-comm cells ran, reconciled, and reached the frontier artifact
for cell in noloco_H10_n2_wan noloco_H10_int4_n2_wan \
            diloco_H10_int4_n2_wan dynamiq_int8_n2_wan; do
    grep -q "\"cell\": \"$cell\"" "$SWEEP_OUT/results.json" || {
        echo "ci_sim: sweep missing cell $cell"; exit 1; }
done
grep -q "^wan,2,noloco" "$SWEEP_OUT/frontier.csv" || {
    echo "ci_sim: frontier.csv missing the noloco verdict row"; exit 1; }
grep -q "noloco H=10 int4" "$SWEEP_OUT/frontier.csv" || {
    echo "ci_sim: frontier.csv missing the compressed-gossip row"; exit 1; }
grep -q "^wan,2,dynamiq int8" "$SWEEP_OUT/frontier.csv" || {
    echo "ci_sim: frontier.csv missing the dynamiq verdict row"; exit 1; }

# ISSUE 12 frontier regression gate: re-price the federated family via
# the cost-model fast path and fail if the best compressed-gossip
# speedup dropped below the recorded baseline (committed beside the
# acceptance sweep's frontier.csv under logs/frontier/).
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m gym_tpu.sim.frontier_gate \
    --baseline logs/frontier/frontier_baseline.json || {
    echo "ci_sim: frontier regression gate failed"; exit 1; }
echo "ci_sim: OK (report at $SWEEP_OUT/report.md)"
exit 0
