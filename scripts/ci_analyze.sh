#!/usr/bin/env bash
# Static-analysis gate (ISSUE 6) — the analysis unit suite plus the CLI
# over the real package, run NEXT TO ci_tier1/ci_faults/ci_sim/ci_serve/
# ci_chaos. The unit suite pins the walker/auditor/linter semantics on
# crafted programs and snippets; the CLI run proves the shipped tree is
# clean end to end: jaxpr audit (zero unconsumed donations, zero
# hot-path host callbacks, zero f64 upcasts for trainer + engine
# programs), static comm reconciliation for all 16 strategy configs
# (incl. the ISSUE 10 noloco/dynamiq low-comm family and the ISSUE 12
# compressed outer loops), and the
# host-concurrency lint with zero unsuppressed violations. Pure host
# work — nothing is compiled or executed on a device; <90 s on the
# 2-core container.
#
# Usage: scripts/ci_analyze.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."

rm -f /tmp/_analyze.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_analyze.log
rc=${PIPESTATUS[0]}
echo ANALYZE_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_analyze.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# CLI over the real package: machine-readable summary, grep the gate.
OUT=${GYM_TPU_CI_ANALYZE_OUT:-/tmp/gym_tpu_ci_analysis.json}
rm -f "$OUT"
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m gym_tpu.analysis \
    --json "$OUT"
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_analyze: CLI reported violations"; exit "$rc"; }
python - "$OUT" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report["violations"] == 0, report
sections = report["sections"]
assert set(sections) == {"lint", "trace", "audit"}
for name, summ in sections["trace"]["strategies"].items():
    assert summ["ok"], (name, summ)
# ISSUE 12 bump: + the compressed outer loops (diloco int8/topk,
# noloco int4, decoupled-momentum outer)
assert len(sections["trace"]["strategies"]) >= 16
# ISSUE 11 bump: + the quantized serving family (int8 weights + int8
# paged KV — paged prefill x2, CoW, paged decode, spec decode);
# ISSUE 12: + the 4 compressed-outer-loop trainer steps;
# ISSUE 16: + the 6 elastic redistribution programs (reshard_flat x3,
# replicate_rows x2, unshard_params)
assert len(sections["audit"]["programs"]) >= 36
# ISSUE 9 gate: the auditor's serve+elastic key set and the
# device-program registry's key set are THE SAME set — enumeration and
# acquisition cannot drift apart
recon = sections["audit"]["registry"]
assert recon["key_set_match"], recon
assert recon["n_registry_keys"] == recon["n_audit_serve_keys"] >= 20, recon
# ISSUE 16 gate: the elastic reshard family is enumerated, audited and
# donation-clean (violations==0 above covers the findings)
enames = [p["name"] for p in sections["audit"]["programs"]
          if p["name"].startswith("elastic.")]
assert len(enames) >= 6, enames
# ISSUE 11 gate: quantized programs are registered + audited with
# dtype-tagged names, donation-clean (violations==0 above covers them)
qnames = [p["name"] for p in sections["audit"]["programs"]
          if "w=int8" in p["name"]]
assert len(qnames) >= 4, qnames
print("ci_analyze: violations=0 across",
      len(sections["trace"]["strategies"]), "strategy configs and",
      len(sections["audit"]["programs"]), "programs;",
      "registry reconciliation:", recon["n_registry_keys"], "keys match")
EOF
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"
echo "ci_analyze: OK (report at $OUT)"
exit 0
