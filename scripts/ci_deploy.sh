#!/usr/bin/env bash
# Continuous-deployment gate (ISSUE 15) — the seventh CI gate, run NEXT
# TO ci_tier1 / ci_faults / ci_sim / ci_serve / ci_chaos / ci_analyze:
#
# 1. the servesim unit suite (trace determinism, cost-model policy
#    invariants, replay, serve.csv schema satellites);
# 2. the serving-policy FRONTIER regression gate against the committed
#    baseline (logs/servesim/frontier_baseline.json) — deterministic
#    cost-model path, seconds;
# 3. the CLOSED TRAIN->DEPLOY LOOP drill: a live trainer (SIGKILLed
#    mid-run and resumed — the PR-2 kill harness) streams checkpoints
#    into a reload-watching OUT-OF-PROCESS fleet while a trace replays
#    open-loop. Gates: zero dropped requests, zero recompiles across
#    every hot-swap (per-worker program counters), post-swap streams
#    byte-exact vs generate_fast;
# 4. the tracesim bench (`bench.py --tracesim-only`): sim-vs-live
#    agreement on one trace x policy point, both arms measured;
# 5. the TENANT frontier gate (ISSUE 17): the class-mix x quota-policy
#    grid re-priced on the cost model against the committed baseline
#    (logs/servesim/tenant/tenant_baseline.json) — every workload group
#    that met the interactive SLO must still meet it, batch goodput
#    must not collapse, and isolation ON must not hurt the victim.
#
# CPU-only; sized for the 2-core container.
#
# Usage: scripts/ci_deploy.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
REPO="$(pwd)"

rm -f /tmp/_deploy.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_servesim.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_deploy.log
rc=${PIPESTATUS[0]}
echo DEPLOY_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_deploy.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# policy-frontier regression gate (deterministic cost-model path)
timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    python -m gym_tpu.servesim.frontier_gate \
    --baseline logs/servesim/frontier_baseline.json || {
    echo "ci_deploy: serving frontier regression"; exit 1; }

# tenant-isolation frontier gate (ISSUE 17, deterministic cost-model
# path): per-class SLO attainment + kept batch goodput vs the baseline
timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    python -m gym_tpu.servesim.tenant_gate \
    --baseline logs/servesim/tenant/tenant_baseline.json || {
    echo "ci_deploy: tenant-isolation frontier regression"; exit 1; }

# the closed train->deploy loop: trainer (killed + resumed) ->
# --reload-watch process fleet -> open-loop trace replay; the drill
# asserts zero dropped / zero recompiles / post-swap streams exact and
# exits nonzero otherwise
OUT=${GYM_TPU_CI_DEPLOY_OUT:-/tmp/gym_tpu_ci_deploy}
rm -rf "$OUT"; mkdir -p "$OUT"
timeout -k 10 900 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    python -m gym_tpu.servesim.drill --out "$OUT/drill" \
    --replicas 2 --out-of-process --kill-trainer \
    2>&1 | tee "$OUT/drill.log" | grep -v '"POST /generate'
rc=${PIPESTATUS[0]}
[ "$rc" -ne 0 ] && { echo "ci_deploy: closed-loop drill failed";
    tail -40 "$OUT/drill.log"; exit "$rc"; }
grep -q '"ok": true' "$OUT/drill.log" || {
    echo "ci_deploy: drill reported not-ok"; tail -40 "$OUT/drill.log";
    exit 1; }
pgrep -f "gym_tpu.serve.worker" > /dev/null && {
    echo "ci_deploy: leaked worker processes:";
    pgrep -af "gym_tpu.serve.worker"; exit 1; }

# tracesim bench: the sim-vs-live agreement contract, one JSON line
timeout -k 10 900 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    python "$REPO/bench.py" --tracesim-only > "$OUT/tracesim.json" || {
    echo "ci_deploy: tracesim bench failed"; cat "$OUT/tracesim.json";
    exit 1; }
python - "$OUT/tracesim.json" <<'EOF'
import json, sys
with open(sys.argv[1]) as f:
    line = f.read().strip().splitlines()[-1]
ts = json.loads(line)["tracesim"]
assert ts["status"] == "measured", ts.get("status")
assert ts["agreement"]["ok"], ts["agreement"]
print("ci_deploy: tracesim agreement —",
      "p99 ttft live", ts["live"]["ttft_p99_s"],
      "model", ts["model"]["ttft_p99_s"],
      "| shed live", ts["live"]["shed_rate"],
      "model", ts["model"]["shed_rate"])
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_deploy: tracesim agreement failed";
    cat "$OUT/tracesim.json"; exit "$rc"; }

echo "ci_deploy: OK"
exit 0
