#!/usr/bin/env bash
# Serving gate (ISSUE 4 + ISSUE 7) — the serve/decode/paged unit suites
# plus one CLI smoke run through the real HTTP entry point, run NEXT TO
# scripts/ci_tier1.sh, ci_faults.sh and ci_sim.sh. The unit suites pin
# the engine-vs-generate_fast parity oracle (unpaged AND paged/prefix-
# shared/speculative), teacher-forcing logits, bounded prefill
# compilation and the params-only restore; the smoke run proves
# `python -m gym_tpu.serve` end to end: train a tiny checkpoint, serve
# it (paged by default), answer 4 CONCURRENT requests, prove PREFIX
# SHARING live (two requests sharing a prompt prefix ->
# prefix_hit_blocks > 0 in /stats), then the SIGTERM drill — the server
# must exit rc=0 with a clean-shutdown line and a tokens_per_s
# headline. A second pass re-serves QUANTIZED (ISSUE 11: --quant int8
# --kv-quant int8 → 200s, /stats echoes the dtypes, and a warmed
# restart serves with programs_compiled=0). CPU-only; sized for the
# 2-core container.
#
# Usage: scripts/ci_serve.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
REPO="$(pwd)"

rm -f /tmp/_serve.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_serve.py tests/test_serve_paged.py tests/test_decode.py \
    -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_serve.log
rc=${PIPESTATUS[0]}
echo SERVE_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_serve.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# CLI smoke: tiny checkpoint -> HTTP server -> 4 concurrent requests ->
# SIGTERM drill. Fresh dir per run.
OUT=${GYM_TPU_CI_SERVE_OUT:-/tmp/gym_tpu_ci_serve}
PORT=${GYM_TPU_CI_SERVE_PORT:-8741}
rm -rf "$OUT"; mkdir -p "$OUT"

timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python - "$OUT" <<'EOF'
import sys, numpy as np
from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.strategy.optim import OptimSpec
from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy

out = sys.argv[1]
cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                n_embd=32, dropout=0.0)
rng = np.random.default_rng(0)
toks = rng.integers(0, 48, (64, 33))
ds = ArrayDataset(toks[:, :-1].astype(np.int64),
                  toks[:, 1:].astype(np.int64))
Trainer(GPT(cfg), ds).fit(
    strategy=SimpleReduceStrategy(optim_spec=OptimSpec("adamw", lr=1e-3)),
    num_nodes=1, max_steps=4, batch_size=4, val_size=0, val_interval=0,
    show_progress=False, seed=1, checkpoint_interval=4,
    save_dir=out + "/ckpts", run_name="ci", log_dir=out + "/logs")
print("ci_serve: checkpoint trained")
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: training the smoke ckpt failed"; exit "$rc"; }

# bare `python ... &` so $! is the server pid, not a subshell's.
# --program-cache-dir seeds the device-program registry's persistent
# executable tier — the restart drill below re-serves against it.
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT" --num_slots 2 --device cpu \
    --program-cache-dir "$OUT/progcache" \
    > "$OUT/server.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/server.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_serve: server died at startup";
        cat "$OUT/server.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/server.log" || {
    echo "ci_serve: server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 180 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import concurrent.futures, json, os, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]

def gen(seed):
    body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6,
                       "top_k": 4, "seed": seed}).encode()
    r = urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", body,
        {"Content-Type": "application/json"}), timeout=120)
    return json.loads(r.read())

with concurrent.futures.ThreadPoolExecutor(4) as ex:
    outs = list(ex.map(gen, range(4)))
assert len(outs) == 4
for o in outs:
    assert len(o["tokens"]) == 6, o
    print("ci_serve: completion", o["tokens"], "ttft", o["ttft_s"])
stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=10).read())
assert stats["requests_done"] == 4, stats
print("ci_serve: tokens_per_s =", stats["tokens_per_s"])

# ISSUE 7 smoke: two requests sharing a 16-token prefix (one page at
# the default page_size 16 on this block-32 checkpoint) -> the second
# admit must hit the prefix cache, observable via /stats
assert stats.get("paged"), f"server not paged: {stats}"
shared = list(range(1, 17))
for tail in ([17], [18]):
    body = json.dumps({"prompt": shared + tail, "max_new_tokens": 4,
                       "top_k": 4, "seed": 9}).encode()
    r = urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", body,
        {"Content-Type": "application/json"}), timeout=120)
    assert len(json.loads(r.read())["tokens"]) == 4
stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=10).read())
assert stats["prefix_hit_blocks"] > 0, stats
print("ci_serve: prefix_hit_blocks =", stats["prefix_hit_blocks"],
      "kv_blocks_in_use =", stats["kv_blocks_in_use"])
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: HTTP requests failed";
    cat "$OUT/server.log"; kill -9 "$SRV"; exit "$rc"; }

# let the background AOT warmup finish before killing the server: the
# restart drill needs EVERY program persisted to the cache dir, not
# just the ones the requests above happened to touch
timeout -k 10 120 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import json, os, time, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]
deadline = time.monotonic() + 110
while time.monotonic() < deadline:
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/stats", timeout=10).read())
    w = stats.get("warmup")
    if w is None or w.get("done"):
        print("ci_serve: warmup done:", w)
        break
    time.sleep(1)
else:
    raise SystemExit(f"warmup never finished: {stats.get('warmup')}")
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: warmup wait failed";
    cat "$OUT/server.log"; kill -9 "$SRV"; exit "$rc"; }

# SIGTERM drill: clean exit 0, shutdown line, headline line
kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: server exit rc=$rc after SIGTERM";
    cat "$OUT/server.log"; exit 1; }
grep -q "shut down cleanly" "$OUT/server.log" || {
    echo "ci_serve: no clean-shutdown line"; cat "$OUT/server.log"; exit 1; }
grep -q "tokens_per_s" "$OUT/server.log" || {
    echo "ci_serve: no tokens_per_s headline"; cat "$OUT/server.log"; exit 1; }
head -1 "$OUT/ckpts/ci/serve/serve.csv" | grep -q "ts_s,kind" || {
    echo "ci_serve: serve.csv missing/markerless"; exit 1; }

# Restart drill (ISSUE 9): re-serve the SAME config against the seeded
# program cache — the device-program registry must deserialize every
# executable instead of compiling. Gate: first request returns 200 AND
# /stats reports programs_compiled=0 (zero XLA compiles in the whole
# restarted process).
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT" --num_slots 2 --device cpu \
    --program-cache-dir "$OUT/progcache" \
    > "$OUT/server2.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/server2.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_serve: restarted server died";
        cat "$OUT/server2.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/server2.log" || {
    echo "ci_serve: restarted server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 180 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import json, os, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]
body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6,
                   "top_k": 4, "seed": 0}).encode()
r = urllib.request.urlopen(urllib.request.Request(
    f"http://127.0.0.1:{port}/generate", body,
    {"Content-Type": "application/json"}), timeout=120)
assert r.status == 200, r.status
assert len(json.loads(r.read())["tokens"]) == 6
stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=10).read())
assert stats["programs_compiled"] == 0, (
    f"restart recompiled {stats['programs_compiled']} programs "
    f"(registry: {stats.get('programs')})")
print("ci_serve: restart drill — first request 200,",
      "programs_compiled =", stats["programs_compiled"])
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: restart drill failed";
    cat "$OUT/server2.log"; kill -9 "$SRV"; exit "$rc"; }
kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: restarted server exit rc=$rc";
    cat "$OUT/server2.log"; exit 1; }

# Quantized live smoke (ISSUE 11): serve the SAME checkpoint with
# --quant int8 --kv-quant int8 — requests answer 200, /stats echoes the
# dtypes and the f32-normalized pool capacity, and after the background
# warmup a process RESTART against the quantized program cache serves
# with programs_compiled=0 (the warmup family covers the quantized
# programs too).
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT" --num_slots 2 --device cpu \
    --quant int8 --kv-quant int8 \
    --program-cache-dir "$OUT/progcache_q" \
    > "$OUT/server3.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/server3.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_serve: quantized server died";
        cat "$OUT/server3.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/server3.log" || {
    echo "ci_serve: quantized server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 180 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import json, os, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]
for seed in range(2):
    body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6,
                       "top_k": 4, "seed": seed}).encode()
    r = urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", body,
        {"Content-Type": "application/json"}), timeout=120)
    assert r.status == 200, r.status
    assert len(json.loads(r.read())["tokens"]) == 6
stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=10).read())
assert stats["weights_dtype"] == "int8", stats.get("weights_dtype")
assert stats["kv_dtype"] == "int8", stats.get("kv_dtype")
assert stats["kv_blocks_capacity_effective"] == 4 * (stats["kv_pages"] - 1), \
    (stats["kv_blocks_capacity_effective"], stats["kv_pages"])
assert stats["requests_done"] == 2, stats["requests_done"]
print("ci_serve: quantized smoke — weights", stats["weights_dtype"],
      "kv", stats["kv_dtype"],
      "capacity_eff", stats["kv_blocks_capacity_effective"],
      "weights_bytes", stats["weights_bytes"])
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: quantized smoke failed";
    cat "$OUT/server3.log"; kill -9 "$SRV"; exit "$rc"; }

# wait for the quantized warmup so every quantized program persists
timeout -k 10 120 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import json, os, time, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]
deadline = time.monotonic() + 110
while time.monotonic() < deadline:
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/stats", timeout=10).read())
    w = stats.get("warmup")
    if w is None or w.get("done"):
        print("ci_serve: quantized warmup done:", w)
        break
    time.sleep(1)
else:
    raise SystemExit(f"quantized warmup never finished: {stats.get('warmup')}")
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: quantized warmup wait failed";
    cat "$OUT/server3.log"; kill -9 "$SRV"; exit "$rc"; }

kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: quantized server exit rc=$rc";
    cat "$OUT/server3.log"; exit 1; }

# quantized restart drill: a warmed restart must serve quantized with
# ZERO XLA compiles (the ISSUE 11 acceptance bar)
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT" --num_slots 2 --device cpu \
    --quant int8 --kv-quant int8 \
    --program-cache-dir "$OUT/progcache_q" \
    > "$OUT/server4.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/server4.log" && break
    kill -0 "$SRV" 2>/dev/null || {
        echo "ci_serve: quantized restart died";
        cat "$OUT/server4.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/server4.log" || {
    echo "ci_serve: quantized restart never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 180 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import json, os, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]
body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6,
                   "top_k": 4, "seed": 0}).encode()
r = urllib.request.urlopen(urllib.request.Request(
    f"http://127.0.0.1:{port}/generate", body,
    {"Content-Type": "application/json"}), timeout=120)
assert r.status == 200, r.status
assert len(json.loads(r.read())["tokens"]) == 6
stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=10).read())
assert stats["weights_dtype"] == "int8" and stats["kv_dtype"] == "int8"
assert stats["programs_compiled"] == 0, (
    f"quantized restart recompiled {stats['programs_compiled']} programs "
    f"(registry: {stats.get('programs')})")
print("ci_serve: quantized restart drill — first request 200,",
      "programs_compiled =", stats["programs_compiled"])
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: quantized restart drill failed";
    cat "$OUT/server4.log"; kill -9 "$SRV"; exit "$rc"; }
kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: quantized restart exit rc=$rc";
    cat "$OUT/server4.log"; exit 1; }

# Out-of-process fleet drill (ISSUE 13): re-serve the same checkpoint
# with --out-of-process --replicas 2 against the f32 program cache the
# first server seeded. Gate: spawned replica WORKER PROCESSES each
# report programs_compiled=0 in /stats (zero XLA compiles off the warm
# persistent tier — what makes autoscaler spawns cheap), a streamed
# request delivers chunked SSE, and SIGTERM reaps both workers (exit 0).
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT" --num_slots 2 --device cpu \
    --out-of-process --replicas 2 \
    --program-cache-dir "$OUT/progcache" \
    > "$OUT/server5.log" 2>&1 &
SRV=$!
for _ in $(seq 1 180); do
    grep -q "listening" "$OUT/server5.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_serve: process-fleet server died";
        cat "$OUT/server5.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/server5.log" || {
    echo "ci_serve: process-fleet server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 180 env GYM_TPU_CI_SERVE_PORT="$PORT" python - <<'EOF'
import json, os, urllib.request

port = os.environ["GYM_TPU_CI_SERVE_PORT"]
# streamed request: chunked SSE, done event carries ttft
body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6,
                   "top_k": 4, "seed": 0, "stream": True}).encode()
r = urllib.request.urlopen(urllib.request.Request(
    f"http://127.0.0.1:{port}/generate", body,
    {"Content-Type": "application/json"}), timeout=120)
assert r.headers["Content-Type"] == "text/event-stream", dict(r.headers)
events = [json.loads(line[6:]) for line in r
          if line.strip().startswith(b"data: ")]
toks = [t for e in events if not e.get("done")
        for t in e.get("tokens", [])]
fin = events[-1]
assert fin.get("done") is True and len(toks) == 6, events
print("ci_serve: process-fleet streamed request ok, ttft", fin["ttft_s"])

stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=10).read())
assert stats.get("fleet") == "process", stats.get("fleet")
live = [r for r in stats["replicas"] if not r["retired"]]
assert len(live) == 2 and stats["healthy_replicas"] == 2, stats["replicas"]
pids = {r["pid"] for r in live}
assert len(pids) == 2 and os.getpid() not in pids, pids
for rep in live:
    assert rep["programs_compiled"] == 0, (
        f"worker {rep['id']} (pid {rep['pid']}) compiled "
        f"{rep['programs_compiled']} programs — persistent tier miss")
assert stats["replicas_spawned"] == 2, stats["replicas_spawned"]
print("ci_serve: spawned workers report programs_compiled=0, pids", pids)
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: process-fleet drill failed";
    cat "$OUT/server5.log"; kill -9 "$SRV"; exit "$rc"; }

kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_serve: process-fleet exit rc=$rc";
    cat "$OUT/server5.log"; exit 1; }
grep -q "shut down cleanly" "$OUT/server5.log" || {
    echo "ci_serve: no clean-shutdown line (process fleet)";
    cat "$OUT/server5.log"; exit 1; }
pgrep -f "gym_tpu.serve.worker" > /dev/null && {
    echo "ci_serve: leaked worker processes:"; pgrep -af "gym_tpu.serve.worker";
    exit 1; }
echo "ci_serve: process-fleet drill OK"

echo "ci_serve: OK (log at $OUT/server.log)"
exit 0
