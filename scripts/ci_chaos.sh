#!/usr/bin/env bash
# Chaos gate (ISSUE 5) — serving under fire, run NEXT TO
# scripts/ci_tier1.sh, ci_faults.sh, ci_sim.sh and ci_serve.sh.
# Three layers:
#
#   1. the chaos unit suite (tests/test_serve_chaos.py): deadline
#      shedding, EWMA admission control, NaN quarantine, supervisor
#      crash/wedge recovery, typed HTTP mappings;
#   2. the serve parity suite RE-RUN under injected latency faults
#      (GYM_TPU_FAULTS delay on every prefill+decode dispatch): token
#      streams must stay EXACT under host-side latency chaos;
#   3. the HTTP chaos smoke through the real `python -m gym_tpu.serve`
#      entry point with an injected decode HANG: the supervisor must
#      abandon the wedged driver, fail the in-flight request TYPED
#      (503, inside its deadline — never a 500), rebuild the engine and
#      answer the next request; an infeasible deadline must draw
#      429 + Retry-After; SIGTERM must still exit 0 with a clean
#      shutdown line;
#   4. the REPLICA-KILL drill (ISSUE 8) against `--replicas 2
#      --max-restarts 0`: a decode hang lands mid-stream on replica 0
#      and the exhausted restart budget makes it a hard engine death —
#      the client must STILL get its 200 (transparent failover to the
#      sibling, full token stream), /stats must record failovers>=1
#      with the dead replica excluded from dispatch, and SIGTERM must
#      exit 0 while replica 0's driver is still wedged (per-replica
#      stack dump, typed queued failures, no engine stepping);
#   5. the PROCESS-KILL drill (ISSUE 13) against `--out-of-process
#      --replicas 2 --autoscale`: kill -9 the worker SUBPROCESS serving
#      a stream, mid-stream, under concurrent load — zero dropped
#      streams (the router splices the re-derived suffix onto a
#      sibling: the concatenated client stream is byte-identical to an
#      uncontended run), the autoscaler respawns the dead worker
#      (/stats shows replicas_spawned/healthy_replicas recovering), and
#      the SIGTERM drill exits 0 reaping every child (no zombies).
#   6. the TENANT-ISOLATION drill (ISSUE 17) against `--preempt
#      --quotas '{"batch": ...}'`: tenant B floods the live server with
#      batch streams while tenant A's interactive requests arrive —
#      A's TTFT stays inside its SLO (preemptible decode parks a flood
#      slot), B's overflow sheds TYPED (429 + Retry-After from the
#      class quota, never a hang), a parked-then-resumed flood stream
#      finishes byte-identical to its uncontended run, and /stats
#      reports the preempt/shed counters per class.
#
# CPU-only; sized for the 2-core container.
#
# Usage: scripts/ci_chaos.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
REPO="$(pwd)"

rm -f /tmp/_chaos.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_serve_chaos.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_chaos.log
rc=${PIPESTATUS[0]}
echo CHAOS_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_chaos.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# Layer 2: the PR-4 parity oracles must hold UNDER latency faults — a
# delayed dispatch may be slow, never wrong.
rm -f /tmp/_chaos2.log
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    GYM_TPU_FAULTS="serve.decode:delay=0.002,serve.prefill:delay=0.002" \
    python -m pytest tests/test_serve.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_chaos2.log
rc=${PIPESTATUS[0]}
echo CHAOS_PARITY_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_chaos2.log | tr -cd . | wc -c)
[ "$rc" -ne 0 ] && exit "$rc"

# Layer 3: HTTP chaos smoke. Fresh tiny checkpoint, then the real server
# under an injected decode hang.
OUT=${GYM_TPU_CI_CHAOS_OUT:-/tmp/gym_tpu_ci_chaos}
PORT=${GYM_TPU_CI_CHAOS_PORT:-8742}
rm -rf "$OUT"; mkdir -p "$OUT"

timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" python - "$OUT" <<'EOF'
import sys, numpy as np
from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.strategy.optim import OptimSpec
from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy

out = sys.argv[1]
cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                n_embd=32, dropout=0.0)
rng = np.random.default_rng(0)
toks = rng.integers(0, 48, (64, 33))
ds = ArrayDataset(toks[:, :-1].astype(np.int64),
                  toks[:, 1:].astype(np.int64))
Trainer(GPT(cfg), ds).fit(
    strategy=SimpleReduceStrategy(optim_spec=OptimSpec("adamw", lr=1e-3)),
    num_nodes=1, max_steps=4, batch_size=4, val_size=0, val_interval=0,
    show_progress=False, seed=1, checkpoint_interval=4,
    save_dir=out + "/ckpts", run_name="ci", log_dir=out + "/logs")
print("ci_chaos: checkpoint trained")
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: training the smoke ckpt failed"; exit "$rc"; }

# Injected hang at decode dispatch 4 (request A consumes dispatches 1-3,
# so the hang lands in request B); the 15 s watchdog reaps it. Bare
# `python ... &` so $! is the server pid, not a subshell's.
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    GYM_TPU_FAULTS="serve.decode:hang=600@4" \
    python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT" --num_slots 2 --device cpu \
    --dispatch-timeout 15 \
    > "$OUT/server.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/server.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_chaos: server died at startup";
        cat "$OUT/server.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/server.log" || {
    echo "ci_chaos: server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 240 env GYM_TPU_CI_CHAOS_PORT="$PORT" python - <<'EOF'
import json, os, time, urllib.error, urllib.request

port = os.environ["GYM_TPU_CI_CHAOS_PORT"]

def post(payload, timeout=120):
    body = json.dumps(payload).encode()
    t0 = time.perf_counter()
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", body,
            {"Content-Type": "application/json"}), timeout=timeout)
        return r.status, json.loads(r.read()), r.headers, \
            time.perf_counter() - t0
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers, \
            time.perf_counter() - t0

# A: dispatches 1-3 — completes, primes compiles + the tokens/s EWMA
code, body, _, dt = post({"prompt": [1, 2, 3], "max_new_tokens": 4,
                          "top_k": 4, "seed": 0, "deadline_s": 90})
assert code == 200 and len(body["tokens"]) == 4, (code, body)
print("ci_chaos: pre-chaos request ok", body["tokens"])

# B: hits the hung dispatch 4 — must fail TYPED (503, not 500, not a
# connection drop) INSIDE its deadline, via supervisor failover
code, body, _, dt = post({"prompt": [1, 2, 3], "max_new_tokens": 8,
                          "top_k": 4, "seed": 1, "deadline_s": 60})
assert code == 503, (code, body)
assert "EngineFailedError" in body["error"], body
assert dt < 60, f"typed failure took {dt:.1f}s — past its deadline"
print(f"ci_chaos: wedged request failed typed in {dt:.1f}s (503)")

# C: post-chaos — the rebuilt engine serves cleanly
code, body, _, dt = post({"prompt": [1, 2, 3], "max_new_tokens": 6,
                          "top_k": 4, "seed": 2, "deadline_s": 90})
assert code == 200 and len(body["tokens"]) == 6, (code, body)
assert dt < 90, f"post-chaos request took {dt:.1f}s"
print("ci_chaos: post-chaos request ok", body["tokens"])

# D: infeasible deadline — shed at admission: 429 + Retry-After, never
# enqueued
code, body, headers, _ = post({"prompt": [1, 2, 3],
                               "max_new_tokens": 28,
                               "deadline_s": 1e-4})
assert code == 429, (code, body)
assert headers.get("Retry-After") is not None, dict(headers)
print("ci_chaos: infeasible deadline shed at admission "
      f"(429, Retry-After={headers['Retry-After']})")

stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=30).read())
assert stats["engine_restarts"] == 1, stats
assert stats["requests_rejected"] == 1, stats
assert stats["status"] == "ok", stats
print("ci_chaos: stats ok —",
      json.dumps({k: stats[k] for k in
                  ("engine_restarts", "requests_done", "requests_failed",
                   "requests_rejected")}))
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: HTTP chaos drill failed";
    cat "$OUT/server.log"; kill -9 "$SRV"; exit "$rc"; }

grep -q "supervisor — engine rebuilt" "$OUT/server.log" || {
    echo "ci_chaos: no supervisor-rebuild line in server log";
    cat "$OUT/server.log"; exit 1; }

# SIGTERM drill: the server must still exit 0 cleanly AFTER an engine
# failover (the abandoned wedged thread is a daemon, still asleep)
kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: server exit rc=$rc after SIGTERM";
    cat "$OUT/server.log"; exit 1; }
grep -q "shut down cleanly" "$OUT/server.log" || {
    echo "ci_chaos: no clean-shutdown line"; cat "$OUT/server.log"; exit 1; }
grep -q "engine restart" "$OUT/server.log" || {
    echo "ci_chaos: no restart count in shutdown line";
    cat "$OUT/server.log"; exit 1; }

# Layer 4: replica-kill drill — same tiny checkpoint, 2 replicas, zero
# restart budget. Request A (max_new 4: prefill + decode dispatches 1-3)
# primes replica 0; request B (max_new 8) lands on replica 0 too (idle
# tie-break) and wedges at decode dispatch 6 — MID-stream, ~3 tokens in.
# The 15 s watchdog reaps the wedged driver, the exhausted budget
# declares replica 0 dead, and the router must retry B on replica 1
# under B's remaining deadline: the client sees 200 and the full 8
# tokens, never the death. --drain-deadline is short because replica 0's
# driver is STILL wedged at SIGTERM: close must dump its stacks and fail
# its requests typed instead of waiting out the hang.
PORT2=$((PORT + 1))
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    GYM_TPU_FAULTS="serve.decode:hang=600@6" \
    python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT2" --num_slots 2 --device cpu \
    --replicas 2 --max-restarts 0 --dispatch-timeout 15 \
    --drain-deadline 5 \
    > "$OUT/fleet.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/fleet.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_chaos: fleet server died at startup";
        cat "$OUT/fleet.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/fleet.log" || {
    echo "ci_chaos: fleet server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 240 env GYM_TPU_CI_CHAOS_PORT="$PORT2" python - <<'EOF'
import json, os, time, urllib.error, urllib.request

port = os.environ["GYM_TPU_CI_CHAOS_PORT"]

def post(payload, timeout=120):
    body = json.dumps(payload).encode()
    t0 = time.perf_counter()
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", body,
            {"Content-Type": "application/json"}), timeout=timeout)
        return r.status, json.loads(r.read()), time.perf_counter() - t0
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), time.perf_counter() - t0

# A: decode dispatches 1-3 on replica 0 — completes, primes programs
code, body, _ = post({"prompt": [1, 2, 3], "max_new_tokens": 4,
                      "top_k": 4, "seed": 0, "deadline_s": 90})
assert code == 200 and len(body["tokens"]) == 4, (code, body)
assert body["replica"] == 0 and body["failovers"] == 0, body
print("ci_chaos: fleet pre-kill request ok on replica", body["replica"])

# B: wedges replica 0 at dispatch 6, mid-stream; restart budget 0 makes
# it a hard death — the router must answer via replica 1: 200, full
# stream, inside B's deadline
code, body, dt = post({"prompt": [1, 2, 3], "max_new_tokens": 8,
                       "top_k": 4, "seed": 1, "deadline_s": 60})
assert code == 200, (code, body)
assert len(body["tokens"]) == 8, body
assert body["replica"] == 1, body
assert body["failovers"] >= 1, body
assert dt < 60, f"failover took {dt:.1f}s — past B's deadline"
print(f"ci_chaos: replica-kill survived — 200 via replica 1 in "
      f"{dt:.1f}s ({body['failovers']} failover)")

# C: the dead replica is OUT of dispatch — every subsequent request
# lands on the sibling
for seed in (2, 3):
    code, body, _ = post({"prompt": [1, 2, 3], "max_new_tokens": 4,
                          "top_k": 4, "seed": seed, "deadline_s": 90})
    assert code == 200 and body["replica"] == 1, (code, body)
print("ci_chaos: dead replica excluded from dispatch")

stats = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/stats", timeout=30).read())
assert stats["failovers"] >= 1, stats
assert stats["healthy_replicas"] == 1, stats
reps = {r["id"]: r for r in stats["replicas"]}
assert reps[0]["dead"] is True and reps[0]["healthy"] is False, stats
assert reps[1]["healthy"] is True, stats
assert stats["status"] == "degraded", stats
print("ci_chaos: fleet stats ok —", json.dumps({
    "failovers": stats["failovers"],
    "healthy_replicas": stats["healthy_replicas"],
    "retries_exhausted": stats["retries_exhausted"]}))
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: replica-kill drill failed";
    cat "$OUT/fleet.log"; kill -9 "$SRV"; exit "$rc"; }

grep -q "failover: request retried on replica 1" "$OUT/fleet.log" || {
    echo "ci_chaos: no failover line in fleet log";
    cat "$OUT/fleet.log"; exit 1; }
grep -q "replica 0 declared dead" "$OUT/fleet.log" || {
    echo "ci_chaos: no replica-death line in fleet log";
    cat "$OUT/fleet.log"; exit 1; }

# SIGTERM with replica 0's driver still wedged in the 600 s hang: the
# close must dump that replica's stacks, fail its requests typed and
# STILL exit 0 with the clean-shutdown headline (failovers included)
kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: fleet exit rc=$rc after SIGTERM";
    cat "$OUT/fleet.log"; exit 1; }
grep -q "replica 0 driver wedged" "$OUT/fleet.log" || {
    echo "ci_chaos: no per-replica wedge stack dump";
    cat "$OUT/fleet.log"; exit 1; }
grep -q "shut down cleanly" "$OUT/fleet.log" || {
    echo "ci_chaos: no clean-shutdown line in fleet log";
    cat "$OUT/fleet.log"; exit 1; }
grep -q "failover(s)" "$OUT/fleet.log" || {
    echo "ci_chaos: no failover count in shutdown line";
    cat "$OUT/fleet.log"; exit 1; }
echo "ci_chaos: replica-kill drill OK (log at $OUT/fleet.log)"

# Layer 5: PROCESS-kill drill (ISSUE 13) — the out-of-process fleet
# with the autoscaler. kill -9 the worker pid serving a stream,
# mid-stream, with concurrent streams in flight.
PORT3=$((PORT + 2))
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT3" --num_slots 2 --device cpu \
    --out-of-process --replicas 2 --autoscale --min-replicas 2 \
    --max-replicas 3 --autoscale-interval 0.5 \
    --program-cache-dir "$OUT/progcache5" --drain-deadline 15 \
    > "$OUT/procfleet.log" 2>&1 &
SRV=$!
for _ in $(seq 1 180); do
    grep -q "listening" "$OUT/procfleet.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_chaos: proc-fleet server died at startup";
        cat "$OUT/procfleet.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/procfleet.log" || {
    echo "ci_chaos: proc-fleet server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 300 env GYM_TPU_CI_CHAOS_PORT="$PORT3" python - <<'EOF'
import concurrent.futures, json, os, signal, time, urllib.request

port = os.environ["GYM_TPU_CI_CHAOS_PORT"]
base = f"http://127.0.0.1:{port}"

def stats():
    return json.loads(urllib.request.urlopen(base + "/stats",
                                             timeout=30).read())

def stream(payload, kill_after_chunks=None, pid_by_rid=None):
    """Consume one SSE stream; optionally kill -9 the serving worker
    PROCESS after N chunk events (pids pre-resolved — a /stats round
    trip inside the loop would let a fast stream finish before the
    kill lands). Returns (tokens, final_event)."""
    body = json.dumps(dict(payload, stream=True)).encode()
    r = urllib.request.urlopen(urllib.request.Request(
        base + "/generate", body,
        {"Content-Type": "application/json"}), timeout=180)
    toks, chunks, fin = [], 0, None
    for line in r:
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        ev = json.loads(line[6:])
        if ev.get("done") or ev.get("error"):
            fin = ev
            break
        toks.extend(ev["tokens"])
        chunks += 1
        if kill_after_chunks is not None and chunks == kill_after_chunks:
            rid = ev["replica"]
            pid = pid_by_rid[rid]
            os.kill(pid, signal.SIGKILL)
            print(f"ci_chaos: SIGKILLed worker pid {pid} (replica "
                  f"{rid}) after {chunks} chunks "
                  f"({len(toks)} tokens)", flush=True)
            kill_after_chunks = None
    return toks, fin

req = {"prompt": [1, 2, 3], "max_new_tokens": 24, "top_k": 4,
       "seed": 7, "deadline_s": 120}
# uncontended reference stream (deterministic engine)
ref, fin = stream(req)
assert fin.get("done") and len(ref) == 24, (ref, fin)
before = stats()
assert before["healthy_replicas"] == 2, before["replicas"]
spawned0 = before["replicas_spawned"]

# under load: two sibling streams in flight while the victim stream's
# worker process is kill -9'd mid-stream — ZERO dropped streams
pid_by_rid = {rep["id"]: rep["pid"] for rep in before["replicas"]
              if not rep["retired"]}
with concurrent.futures.ThreadPoolExecutor(3) as ex:
    bg = [ex.submit(stream, {"prompt": [1, 2, 3], "max_new_tokens": 10,
                             "top_k": 4, "seed": 20 + i,
                             "deadline_s": 120}) for i in range(2)]
    toks, fin = stream(req, kill_after_chunks=1, pid_by_rid=pid_by_rid)
    bg_results = [f.result() for f in bg]
assert fin.get("done") is True, fin
assert toks == ref, f"spliced stream diverged:\n  got {toks}\n  ref {ref}"
assert fin["failovers"] >= 1, fin
for btoks, bfin in bg_results:
    assert bfin.get("done") is True and len(btoks) == 10, (btoks, bfin)
print("ci_chaos: kill -9 mid-stream — spliced stream byte-identical, "
      f"{fin['failovers']} failover(s), sibling streams intact")

# the autoscaler must respawn the dead worker: healthy_replicas back
# to 2, replicas_spawned grew
deadline = time.monotonic() + 120
st = stats()
while time.monotonic() < deadline:
    st = stats()
    if (st["healthy_replicas"] >= 2
            and st["replicas_spawned"] > spawned0):
        break
    time.sleep(1)
assert st["healthy_replicas"] >= 2, st["replicas"]
assert st["replicas_spawned"] > spawned0, (
    st["replicas_spawned"], spawned0)
assert st["streams_active"] == 0, st["streams_active"]
print("ci_chaos: autoscaler respawned —",
      json.dumps({"replicas_spawned": st["replicas_spawned"],
                  "healthy_replicas": st["healthy_replicas"],
                  "failovers": st["failovers"]}))

# and the recovered fleet still serves exact streams
toks, fin = stream(req)
assert fin.get("done") and toks == ref, (toks, fin)
print("ci_chaos: post-respawn stream exact")
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: process-kill drill failed";
    cat "$OUT/procfleet.log"; kill -9 "$SRV"; exit "$rc"; }

grep -q "declared dead" "$OUT/procfleet.log" || {
    echo "ci_chaos: no worker-death line in proc-fleet log";
    cat "$OUT/procfleet.log"; exit 1; }
grep -q "failover: request retried" "$OUT/procfleet.log" || {
    echo "ci_chaos: no splice-failover line in proc-fleet log";
    cat "$OUT/procfleet.log"; exit 1; }
grep -q "autoscaler — scale UP" "$OUT/procfleet.log" || {
    echo "ci_chaos: no autoscaler respawn line in proc-fleet log";
    cat "$OUT/procfleet.log"; exit 1; }

# SIGTERM drill: exit 0, clean shutdown, EVERY worker child reaped
kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: proc-fleet exit rc=$rc after SIGTERM";
    cat "$OUT/procfleet.log"; exit 1; }
grep -q "shut down cleanly" "$OUT/procfleet.log" || {
    echo "ci_chaos: no clean-shutdown line in proc-fleet log";
    cat "$OUT/procfleet.log"; exit 1; }
pgrep -f "gym_tpu.serve.worker" > /dev/null && {
    echo "ci_chaos: leaked worker processes after SIGTERM:";
    pgrep -af "gym_tpu.serve.worker"; exit 1; }
echo "ci_chaos: process-kill drill OK (log at $OUT/procfleet.log)"

# Layer 6: tenant-isolation drill (ISSUE 17) — quotas + preemptible
# decode on the live server. Tenant B floods; tenant A must not feel
# it. The injected 50 ms decode delay makes every flood stream a real
# slot-holder (warm tiny-model decode is otherwise too fast for the
# victim to ever contend) — the same latency-chaos idiom as layer 2.
PORT4=$((PORT + 3))
env JAX_PLATFORMS=cpu PYTHONPATH="$REPO" \
    GYM_TPU_FAULTS="serve.decode:delay=0.05" \
    python -m gym_tpu.serve \
    --ckpt "$OUT/ckpts/ci" --port "$PORT4" --num_slots 2 --device cpu \
    --preempt --quotas '{"batch": {"tokens_per_s": 30, "burst_s": 2}}' \
    > "$OUT/tenant.log" 2>&1 &
SRV=$!
for _ in $(seq 1 90); do
    grep -q "listening" "$OUT/tenant.log" && break
    kill -0 "$SRV" 2>/dev/null || { echo "ci_chaos: tenant server died at startup";
        cat "$OUT/tenant.log"; exit 1; }
    sleep 1
done
grep -q "listening" "$OUT/tenant.log" || {
    echo "ci_chaos: tenant server never started"; kill -9 "$SRV"; exit 1; }

timeout -k 10 240 env GYM_TPU_CI_CHAOS_PORT="$PORT4" python - <<'EOF'
import concurrent.futures, json, os, time, urllib.error, urllib.request

port = os.environ["GYM_TPU_CI_CHAOS_PORT"]
base = f"http://127.0.0.1:{port}"

def post(payload, timeout=120):
    body = json.dumps(payload).encode()
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            base + "/generate", body,
            {"Content-Type": "application/json"}), timeout=timeout)
        return r.status, json.loads(r.read()), r.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers

def stream_ttft(payload):
    """Consume one SSE stream; return (ttft_s, tokens)."""
    body = json.dumps(dict(payload, stream=True)).encode()
    t0 = time.perf_counter()
    r = urllib.request.urlopen(urllib.request.Request(
        base + "/generate", body,
        {"Content-Type": "application/json"}), timeout=120)
    ttft, toks = None, []
    for line in r:
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        ev = json.loads(line[6:])
        if ev.get("done") or ev.get("error"):
            assert ev.get("done"), ev
            break
        if ev["tokens"] and ttft is None:
            ttft = time.perf_counter() - t0
        toks.extend(ev["tokens"])
    return ttft, toks

FLOOD = {"prompt": [1, 2, 3], "max_new_tokens": 24, "top_k": 4,
         "seed": 7, "deadline_s": 120, "tenant": "tenant_b",
         "slo_class": "batch"}

# warm request + the UNCONTENDED reference for the flood signature
# (same engine, empty server): the resume-exactness oracle
code, body, _ = post(dict(FLOOD, seed=0))
assert code == 200 and len(body["tokens"]) == 24, (code, body)
code, ref_body, _ = post(FLOOD)
assert code == 200 and len(ref_body["tokens"]) == 24, (code, ref_body)
ref = ref_body["tokens"]
print("ci_chaos: tenant warm + reference ok")
time.sleep(2.5)      # refill the batch bucket to its 60-token cap

# tenant B floods: 6 concurrent batch streams of 24 tokens against a
# 60-token bucket — ~2 admit and hold both slots, the tail sheds 429
with concurrent.futures.ThreadPoolExecutor(6) as ex:
    flood = [ex.submit(post, FLOOD) for _ in range(6)]
    time.sleep(0.4)  # flood decoding; both slots busy
    # tenant A: interactive requests DURING the flood — preemptible
    # decode must park a flood slot for each
    ttfts = []
    for i in range(3):
        ttft, toks = stream_ttft({"prompt": [1, 2, 3],
                                  "max_new_tokens": 4, "top_k": 4,
                                  "seed": 100 + i, "deadline_s": 60,
                                  "tenant": "tenant_a",
                                  "slo_class": "interactive"})
        assert ttft is not None and len(toks) == 4, (ttft, toks)
        ttfts.append(ttft)
    flood = [f.result() for f in flood]

ok = [b for c, b, _ in flood if c == 200]
shed = [(c, b, h) for c, b, h in flood if c == 429]
assert ok and shed, [c for c, _, _ in flood]
for c, b, h in shed:
    assert h.get("Retry-After") is not None, dict(h)
    assert "quota" in b["error"].lower(), b
# every admitted flood stream — parked and resumed under tenant A's
# arrivals — equals the uncontended reference token-for-token
for b in ok:
    assert b["tokens"] == ref, (b["tokens"], ref)
worst = max(ttfts)
assert worst < 5.0, f"victim TTFT {worst:.2f}s blew the 5s SLO"
print(f"ci_chaos: tenant drill — victim TTFTs "
      f"{[round(t, 3) for t in ttfts]}s (SLO 5s), "
      f"{len(ok)} flood admitted (streams exact), {len(shed)} shed "
      f"typed 429+Retry-After")

stats = json.loads(urllib.request.urlopen(base + "/stats",
                                          timeout=30).read())
ten = stats["tenants"]
assert ten["preemptions"] >= 1 and ten["resumes"] >= 1, ten
assert ten["quota_rejections"].get("batch", 0) >= len(shed), ten
print("ci_chaos: tenant stats ok —", json.dumps({
    "preemptions": ten["preemptions"], "resumes": ten["resumes"],
    "quota_rejections": ten["quota_rejections"]}))
EOF
rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: tenant-isolation drill failed";
    cat "$OUT/tenant.log"; kill -9 "$SRV"; exit "$rc"; }

kill -TERM "$SRV"
wait "$SRV"; rc=$?
[ "$rc" -ne 0 ] && { echo "ci_chaos: tenant server exit rc=$rc after SIGTERM";
    cat "$OUT/tenant.log"; exit 1; }
grep -q "shut down cleanly" "$OUT/tenant.log" || {
    echo "ci_chaos: no clean-shutdown line in tenant log";
    cat "$OUT/tenant.log"; exit 1; }
echo "ci_chaos: tenant-isolation drill OK (log at $OUT/tenant.log)"

# bench rider: one-line shed/recovered/percentile headline
timeout -k 10 600 python "$REPO/bench.py" --chaos-only \
    > "$OUT/chaos_bench.json" 2> "$OUT/chaos_bench.err" || {
    echo "ci_chaos: bench.py --chaos-only failed";
    cat "$OUT/chaos_bench.err"; exit 1; }
python - "$OUT/chaos_bench.json" <<'EOF'
import json, sys
with open(sys.argv[1]) as f:
    head = json.loads(f.read().strip().splitlines()[-1])["chaos"]
assert head["recovered"] is True, head
assert head["faulted"]["engine_restarts"] >= 1, head
assert head["faulted"]["post_chaos_request_ok"] is True, head
assert head["clean"]["ttft_p99_s"] is not None, head
print("ci_chaos: bench headline ok —", json.dumps({
    "clean_p99_ttft_s": head["clean"]["ttft_p99_s"],
    "faulted_p99_ttft_s": head["faulted"]["ttft_p99_s"],
    "shed_at_admission": head["faulted"]["shed_at_admission"],
    "engine_restarts": head["faulted"]["engine_restarts"]}))
EOF
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# fleet bench rider (ISSUE 8): replica-kill + rolling hot-swap drills as
# one JSON line — the BENCHMARKS "Fleet failover & hot-swap" numbers
timeout -k 10 600 python "$REPO/bench.py" --fleet-only \
    > "$OUT/fleet_bench.json" 2> "$OUT/fleet_bench.err" || {
    echo "ci_chaos: bench.py --fleet-only failed";
    cat "$OUT/fleet_bench.err"; exit 1; }
python - "$OUT/fleet_bench.json" <<'EOF'
import json, sys
with open(sys.argv[1]) as f:
    head = json.loads(f.read().strip().splitlines()[0])["fleet"]
kill, swap = head["replica_kill"], head["hot_swap"]
assert kill["requests_failed"] == 0, head
assert kill["failovers"] >= 1 and kill["dead_replicas"] == 1, head
assert swap["requests_failed"] == 0, head
assert swap["recompiles_during_swap"] == 0, head
assert swap["post_swap_params_verified"] is True, head
# ISSUE 13: the process-fleet A/B — both arms measured, the
# 2-subprocess fleet at or above the in-process-thread fleet, and
# streamed p99 TTFB tracking p99 TTFT (not completion time)
ab = head["process_ab"]
assert ab["status"] == "measured" and ab["measured"] is True, ab
# small noise margin on a >=2-core box (the measured headline runs
# 1.2-1.6x; a CI pass within noise of parity is not a regression —
# the structural asserts inside bench.py still gate the protocol).
# On a SINGLE core the premise of the A/B is gone: router + 2 worker
# subprocesses time-slice one CPU, so process >= thread is
# unsatisfiable by construction (unmodified HEAD measures ~0.90x
# there) — keep only an IPC-overhead sanity floor.
import os
floor = 0.95 if (os.cpu_count() or 1) >= 2 else 0.70
assert ab["process_fleet_tok_s"] >= floor * ab["thread_fleet_tok_s"], (
    f"2-subprocess fleet {ab['process_fleet_tok_s']} tok/s well under "
    f"the thread fleet {ab['thread_fleet_tok_s']} tok/s "
    f"(floor {floor}, cores {os.cpu_count()})")
assert ab["p99_ttfb_s"] <= ab["p99_ttft_s"] * 1.5 + 0.2, ab
assert ab["p99_ttfb_s"] < ab["p99_completion_s"], ab
assert all(c == 0 for c in ab["worker_programs_compiled"]), (
    f"spawned workers recompiled: {ab['worker_programs_compiled']}")
print("ci_chaos: fleet bench ok —", json.dumps({
    "kill_failovers": kill["failovers"],
    "kill_requests_ok": kill["requests_ok"],
    "swap_requests_ok": swap["requests_ok"],
    "swap_reload_wall_s": swap["reload_wall_s"],
    "thread_fleet_tok_s": ab["thread_fleet_tok_s"],
    "process_fleet_tok_s": ab["process_fleet_tok_s"],
    "p99_ttfb_s": ab["p99_ttfb_s"],
    "p99_ttft_s": ab["p99_ttft_s"]}))
EOF
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"

# tenant bench rider (ISSUE 17): the noisy-neighbor A/B as one JSON
# line — the BENCHMARKS "Multi-tenant isolation" numbers; its in-bench
# asserts (victim p99 bounded, preempted resume exact) already gate it
timeout -k 10 600 python "$REPO/bench.py" --tenant-only \
    > "$OUT/tenant_bench.json" 2> "$OUT/tenant_bench.err" || {
    echo "ci_chaos: bench.py --tenant-only failed";
    cat "$OUT/tenant_bench.err"; exit 1; }
python - "$OUT/tenant_bench.json" <<'EOF'
import json, sys
with open(sys.argv[1]) as f:
    head = json.loads(f.read().strip().splitlines()[-1])["tenant"]
assert head["status"] == "measured" and head["measured"] is True, head
assert head["preempted_resume_exact"] is True, head
assert head["isolated"]["preemptions"] >= 1, head
assert head["isolated"]["flood_shed_typed"] >= 1, head
assert head["victim_p99_improvement"] >= 1.0, head
print("ci_chaos: tenant bench ok —", json.dumps({
    "victim_p99_baseline_s": head["baseline"]["victim_ttft_p99_s"],
    "victim_p99_isolated_s": head["isolated"]["victim_ttft_p99_s"],
    "improvement": head["victim_p99_improvement"],
    "preemptions": head["isolated"]["preemptions"]}))
EOF
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"
echo "ci_chaos: OK (logs at $OUT/server.log, $OUT/fleet.log, $OUT/tenant.log)"
exit 0
