#!/usr/bin/env bash
# Fault-tolerance gate (ISSUE 2) — the kill harness + in-process
# resilience suite, run NEXT TO scripts/ci_tier1.sh (which excludes the
# slow-marked kill sites). Subprocess `kill -9` at every registered
# fault-injection site, resume, assert the stitched loss trajectory is
# bit-identical to an uninterrupted run; plus the SIGTERM preemption
# drill and the corrupt-checkpoint fallback. CPU-only, sized for the
# 2-core container (the kill harness itself runs in ~45 s; the timeout
# leaves headroom for the in-process suite and a loaded machine).
#
# Usage: scripts/ci_faults.sh   (from the repo root or anywhere)
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_faults.log
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_kill_harness.py tests/test_resilience.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_faults.log
rc=${PIPESTATUS[0]}
echo FAULT_DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
    /tmp/_faults.log | tr -cd . | wc -c)
exit $rc
