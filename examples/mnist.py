"""MNIST example (reference ``example/mnist.py`` parity).

Trains the 2-block CNN with SPARTA on 2 simulated nodes, batch size 256 —
the exact configuration behind the reference's published benchmark table
(``README.md:104-112``, BASELINE.md). Data: torchvision MNIST when a local
copy exists (this environment has no network egress), otherwise a
deterministic synthetic stand-in with the same shapes.

Run: ``python examples/mnist.py [--strategy sparta] [--num_nodes 2]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import argparse

import numpy as np

from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.models import MnistLossModel
from gym_tpu.strategy import (DeMoStrategy, DiLoCoStrategy, DynamiQStrategy,
                              FedAvgStrategy, NoLoCoStrategy, OptimSpec,
                              SimpleReduceStrategy, SPARTAStrategy)


def load_mnist(train: bool):
    """Real digit images with translate augmentation (the reference trains
    torchvision MNIST + RandomAffine, ``example/mnist.py:14-27``). Priority:
    torchvision MNIST if a local copy exists → sklearn's bundled
    handwritten-digits scans (REAL data, no download — see
    ``gym_tpu/data/offline.py``) → synthetic blobs as a last resort."""
    try:
        from torchvision import datasets, transforms  # noqa

        from gym_tpu.data.offline import CropAugmentedDataset

        ds = datasets.MNIST("data", train=train, download=False)
        imgs = (ds.data.numpy().astype(np.float32) / 255.0 - 0.1307) / 0.3081
        labels = ds.targets.numpy().astype(np.int32)
        if train:
            # same crop-translate augmentation as the digits path, so the
            # baseline semantics do not depend on which corpus is present
            pad = 3
            padded = np.pad(imgs, ((0, 0), (pad, pad), (pad, pad)),
                            constant_values=-0.1307 / 0.3081)
            return CropAugmentedDataset(padded[..., None], labels, 28)
        return ArrayDataset(imgs[..., None], labels)
    except Exception:
        pass
    try:
        from gym_tpu.data.offline import load_digits_mnist

        return load_digits_mnist(train)
    except Exception as e:
        print(f"[examples/mnist] digits unavailable ({e}) -> synthetic")
        n = 8192 if train else 1024
        rng = np.random.default_rng(0 if train else 1)
        labels = rng.integers(0, 10, size=n).astype(np.int32)
        imgs = rng.normal(0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
        for i, y in enumerate(labels):
            imgs[i, (y * 2): (y * 2 + 6), 8:20, 0] += 1.2
        return ArrayDataset(imgs, labels)


def make_strategy(name: str, lr: float):
    optim = OptimSpec("adam", lr=lr)
    sched = dict(lr_scheduler="lambda_cosine",
                 lr_scheduler_kwargs={"warmup_steps": 100})
    return {
        "simple_reduce": lambda: SimpleReduceStrategy(optim, **sched),
        "sparta": lambda: SPARTAStrategy(optim, p_sparta=0.005, **sched),
        "diloco": lambda: DiLoCoStrategy(optim, H=100, **sched),
        "fedavg": lambda: FedAvgStrategy(optim, H=100, **sched),
        "demo": lambda: DeMoStrategy(
            optim_spec=OptimSpec("sgd", lr=lr),
            compression_decay=0.999, compression_topk=32,
            compression_chunk=64, **sched),
        "noloco": lambda: NoLoCoStrategy(optim, H=100, **sched),
        "dynamiq": lambda: DynamiQStrategy(optim, codec="int8", **sched),
    }[name]()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--strategy", default="sparta",
                   choices=["simple_reduce", "sparta", "diloco", "fedavg",
                            "demo", "noloco", "dynamiq"])
    p.add_argument("--num_nodes", type=int, default=2)
    p.add_argument("--num_epochs", type=int, default=1)
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--device", default=None)
    p.add_argument("--wandb_project", default=None)
    args = p.parse_args()

    if args.device == "cpu":
        # pin the platform LIST (see examples/nanogpt.py): initializing
        # a dead accelerator plugin first would hang forever
        import jax
        jax.config.update("jax_platforms", "cpu")

    trainer = Trainer(MnistLossModel(), load_mnist(True), load_mnist(False))
    res = trainer.fit(
        num_epochs=args.num_epochs,
        max_steps=args.max_steps,
        strategy=make_strategy(args.strategy, args.lr),
        num_nodes=args.num_nodes,
        device=args.device,
        batch_size=args.batch_size,
        val_size=256,
        val_interval=100,
        wandb_project=args.wandb_project,
        run_name=f"mnist_{args.strategy}_{args.num_nodes}n",
    )
    print(f"final train loss {res.final_train_loss:.4f} "
          f"({res.steps_per_second:.2f} it/s)")


if __name__ == "__main__":
    main()
