"""Minimal DiLoCo playground (reference ``example/playground.py`` parity):
4 simulated nodes, 8L/8H/512 GPT on OWT (synthetic fallback offline)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


from gym_tpu import Trainer
from gym_tpu.data import get_dataset
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.strategy import DiLoCoStrategy, OptimSpec

NUM_NODES = 4
BLOCK_SIZE = 1024


def dataset_factory(rank, num_nodes, is_val):
    if is_val:
        ds, _ = get_dataset("owt", BLOCK_SIZE, start_pc=0.99, end_pc=1.0)
        return ds
    width = 0.99 / num_nodes
    ds, _ = get_dataset("owt", BLOCK_SIZE, start_pc=rank * width,
                        end_pc=(rank + 1) * width)
    return ds


def main():
    _, vocab_size = get_dataset("owt", BLOCK_SIZE, start_pc=0.0, end_pc=0.001)
    # flash + bf16: 4 nodes × 8L/512 at T=1024 with dense f32 attention
    # wants ~17 GB of probs in the backward — doesn't fit a 16 GB chip
    cfg = GPTConfig(block_size=BLOCK_SIZE, vocab_size=int(vocab_size),
                    n_layer=8, n_head=8, n_embd=512, dropout=0.0,
                    attn_impl="flash")
    res = Trainer(GPT(cfg), dataset_factory, dataset_factory).fit(
        max_steps=int(os.environ.get("PLAYGROUND_STEPS", 1000)),
        strategy=DiLoCoStrategy(
            optim_spec=OptimSpec("adamw", lr=3e-4), H=100,
            lr_scheduler="lambda_cosine",
            lr_scheduler_kwargs={"warmup_steps": 100}),
        num_nodes=NUM_NODES,
        batch_size=16,
        minibatch_size=4,  # 50k-vocab f32 logits are 0.8 GB per 4-seq
        # microbatch per node — the eval computes local AND consensus
        # losses, so keep the in-flight logits small
        val_size=64,
        val_interval=100,
        autocast=True,
        run_name="playground_diloco",
    )
    print(f"final loss {res.final_train_loss:.4f}")


if __name__ == "__main__":
    main()
