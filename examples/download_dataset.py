"""Pre-fetch and cache datasets (reference
``example/nanogpt/download_dataset.py``): populate the ``data/`` token
caches up front so training runs never touch the network.

Online datasets (shakespeare / wikitext / code) use HuggingFace when
reachable; everything falls back to the deterministic offline sources
(``docs`` is always offline-real, ``owt`` materializes synthetic chunks).

Usage:
    python examples/download_dataset.py                # default set
    python examples/download_dataset.py --datasets docs owt --block_size 256
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--datasets", nargs="+",
                   default=["shakespeare", "docs"],
                   choices=["shakespeare", "wikitext", "code", "docs", "owt"])
    p.add_argument("--block_size", type=int, default=1024)
    p.add_argument("--data_root", default="data")
    args = p.parse_args()

    from gym_tpu.data import get_dataset

    for name in args.datasets:
        ds, vocab = get_dataset(name, args.block_size,
                                data_root=args.data_root)
        print(f"{name}: {len(ds)} windows cached under "
              f"{args.data_root}/ (vocab {vocab})")


if __name__ == "__main__":
    main()
