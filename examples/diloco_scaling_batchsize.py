"""DiLoCo batch-size scaling sweep (reference
``example/diloco_scaling_batchsize.py`` parity): global batch × {1,2,4,8},
DDP-vs-DiLoCo at K ∈ {1,2,4}, H=30, fixed token budget, lr scaled linearly
with the batch multiplier (reference ``:74-129``)."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import argparse
import json

from gym_tpu import Trainer
from gym_tpu.data import get_dataset
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.strategy import DiLoCoStrategy, OptimSpec, SimpleReduceStrategy

BASE_BATCH = 16
BASE_LR = 3e-4
TOKEN_BUDGET = 2 ** 24  # scaled down from the reference's 2^28
H = 30
BLOCK_SIZE = 256


def run(mult: int, num_nodes: int, use_diloco: bool,
        budget: int = TOKEN_BUDGET):
    ds, vocab = get_dataset("shakespeare", BLOCK_SIZE, end_pc=0.9)
    val, _ = get_dataset("shakespeare", BLOCK_SIZE, start_pc=0.9)
    cfg = GPTConfig.gpt2_size_map("small")
    cfg.vocab_size = int(vocab)
    cfg.block_size = BLOCK_SIZE

    batch_size = BASE_BATCH * mult
    lr = BASE_LR * mult  # linear lr scaling (reference :79, :104)
    max_steps = max(1, budget // (batch_size * BLOCK_SIZE * num_nodes))
    if use_diloco:
        strategy = DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=lr), H=H)
    else:
        strategy = SimpleReduceStrategy(OptimSpec("adamw", lr=lr))
    res = Trainer(GPT(cfg), ds, val).fit(
        max_steps=max_steps, strategy=strategy, num_nodes=num_nodes,
        batch_size=batch_size, val_size=64, val_interval=200,
        run_name=f"scaling_m{mult}_k{num_nodes}_"
                 f"{'diloco' if use_diloco else 'ddp'}",
    )
    return {"mult": mult, "num_nodes": num_nodes,
            "strategy": "diloco" if use_diloco else "ddp",
            "steps": res.steps, "final_loss": res.final_train_loss,
            "it_s": res.steps_per_second}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mults", type=int, nargs="+", default=[1, 2, 4, 8])
    p.add_argument("--nodes", type=int, nargs="+", default=[1, 2, 4])
    p.add_argument("--budget", type=int, default=TOKEN_BUDGET,
                   help="token budget per config (smoke runs: e.g. 65536)")
    args = p.parse_args()
    results = []
    for mult in args.mults:
        results.append(run(mult, 1, use_diloco=False,
                           budget=args.budget))  # DDP baseline
        print(json.dumps(results[-1]))
        for k in args.nodes:
            results.append(run(mult, k, use_diloco=True,
                               budget=args.budget))
            print(json.dumps(results[-1]))
    with open("logs/scaling_results.json", "w") as f:
        json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
