"""nanoGPT CLI (reference ``example/nanogpt.py`` parity).

Full flag surface of the reference (SURVEY §5.6): dataset/pc-range/
block_size (``:36-47``), training/model-size (``:49-58``), optimization
(``:61-67``), seed/wandb/val (``:69-74``), ``--strategy`` choice (``:77-83``)
and per-strategy knobs — FedAvg ``--H --island_size`` (``:85-92``), SPARTA
``--p_sparta --sparta_interval`` (``:93-102``; unlike the reference these
flags are actually consumed), DiLoCo ``--diloco_interval --outer_lr
--nesterov --outer_momentum`` (``:104-116``), DeMo compression flags
(``:118-133``). The ``diloco_sparta`` combo works here (the reference ships
it broken — SURVEY §2.1).

TPU-native additions: ``--cp`` (context-parallel devices per node, ring
attention) and ``--attn_impl`` (dense/flash/ring).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import argparse

import numpy as np

from gym_tpu import Trainer
from gym_tpu.data import ContiguousGPTTrainDataset, get_dataset
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.strategy import (DecoupledMomentumStrategy, DeMoStrategy,
                              DiLoCoStrategy, DynamiQStrategy,
                              FedAvgStrategy, NoLoCoStrategy, OptimSpec,
                              SimpleReduceStrategy, SPARTADiLoCoStrategy,
                              SPARTAStrategy, ZeroReduceStrategy)


def gen_run_name(args) -> str:
    """Run-name generator (reference ``example/nanogpt.py:9-28``)."""
    parts = [args.dataset, args.model_size, args.strategy,
             f"{args.num_nodes}n", f"bs{args.batch_size}"]
    if args.strategy in ("diloco", "diloco_sparta", "noloco",
                         "demo_outer"):
        parts.append(f"H{args.diloco_interval}")
    if args.strategy in ("sparta", "diloco_sparta"):
        parts.append(f"p{args.p_sparta}")
    if args.strategy == "dynamiq":
        parts.append(args.codec or "int8")
    elif args.strategy == "demo_outer":
        # the default link is top-k (create_strategy) — name it so the
        # default run and an explicit --codec topk run share a run dir
        codec = args.codec or "topk"
        if codec != "dense":
            parts.append(codec)
    elif (args.strategy in ("diloco", "noloco")
            and args.codec not in (None, "dense")):
        parts.append(args.codec)
    if getattr(args, "participation", 1.0) < 1.0:
        parts.append(f"part{args.participation}")
    if getattr(args, "n_experts", 0):
        parts.append(f"moe{args.n_experts}e{args.expert_topk}")
    return "_".join(str(p) for p in parts)


def create_strategy(args):
    """Strategy factory (reference ``example/nanogpt.py:138-245``)."""
    if (getattr(args, "participation", 1.0) < 1.0
            and args.strategy not in ("fedavg", "diloco", "sparta",
                                      "diloco_sparta")):
        # refuse rather than silently ignore — the parsed-but-unused flag
        # bug class this framework exists to kill (SURVEY §5.6)
        raise SystemExit(
            f"--participation is not supported by --strategy "
            f"{args.strategy} (fedavg/diloco/sparta/diloco_sparta only)"
        )
    optim = OptimSpec("adamw", lr=args.lr)
    sched = dict(
        lr_scheduler="lambda_cosine",
        lr_scheduler_kwargs={
            "warmup_steps": args.warmup_steps,
            "cosine_anneal": args.cosine_anneal,
        },
        max_norm=args.max_norm,
    )
    if args.strategy == "base":
        return SimpleReduceStrategy(optim_spec=optim, **sched)
    if args.strategy == "zero":
        # ZeRO-1 DDP (beyond the reference's strategy set): optimizer
        # state sharded 1/K per node — see strategy/zero_reduce.py
        return ZeroReduceStrategy(optim_spec=optim, **sched)
    if args.strategy == "fedavg":
        return FedAvgStrategy(inner_optim=optim, H=args.H,
                              island_size=args.island_size,
                              participation=args.participation, **sched)
    # the CompressedLink codec axis (ISSUE 12): shared by diloco /
    # noloco / demo_outer; "dense" (or unset) is the identity link
    link_codec = None if args.codec in (None, "dense") else args.codec
    link_kw = ({"frac": args.topk_frac} if link_codec == "topk" else {})
    if args.strategy == "diloco":
        return DiLoCoStrategy(
            optim_spec=optim,
            outer_optim_spec=OptimSpec(
                "sgd", lr=args.outer_lr, nesterov=args.nesterov,
                momentum=args.outer_momentum),
            H=args.diloco_interval,
            participation=args.participation,
            codec=link_codec, **link_kw, **sched)
    if args.strategy == "sparta":
        return SPARTAStrategy(inner_optim=optim, p_sparta=args.p_sparta,
                              interval=args.sparta_interval,
                              participation=args.participation, **sched)
    if args.strategy == "diloco_sparta":
        return SPARTADiLoCoStrategy(
            optim_spec=optim,
            outer_optim_spec=OptimSpec(
                "sgd", lr=args.outer_lr, nesterov=args.nesterov,
                momentum=args.outer_momentum),
            p_sparta=args.p_sparta, H=args.diloco_interval,
            sparta_interval=args.sparta_interval,
            participation=args.participation, **sched)
    if args.strategy == "demo":
        return DeMoStrategy(
            optim_spec=OptimSpec("sgd", lr=args.lr),
            compression_decay=args.compression_decay,
            compression_topk=args.compression_topk,
            compression_chunk=args.compression_chunk,
            weight_decay=args.weight_decay, **sched)
    if args.strategy == "noloco":
        # all-reduce-free: shared-PRNG partner gossip every
        # --diloco_interval steps (see strategy/noloco.py)
        return NoLoCoStrategy(
            optim_spec=optim,
            outer_optim_spec=OptimSpec(
                "sgd", lr=args.outer_lr, nesterov=args.nesterov,
                momentum=args.outer_momentum),
            H=args.diloco_interval,
            codec=link_codec, **link_kw, **sched)
    if args.strategy == "demo_outer":
        # decoupled outer momentum (arXiv 2510.03371; strategy/demo.py):
        # --codec defaults to the DeMo-style top-k extraction
        codec = link_codec or ("topk" if args.codec is None else None)
        ckw = {"frac": args.topk_frac} if codec == "topk" else {}
        return DecoupledMomentumStrategy(
            optim_spec=optim, H=args.diloco_interval,
            outer_lr=args.outer_lr, outer_momentum=args.outer_momentum,
            codec=codec, **ckw, **sched)
    if args.strategy == "dynamiq":
        # compressed all-reduce: DDP sync pattern, codec'd payloads
        # (see strategy/dynamiq.py)
        if args.codec == "dense":
            raise SystemExit(
                "dynamiq is compressed by definition — --codec dense "
                "is plain DDP; use --strategy base instead")
        codec = args.codec or "int8"
        kw = {"frac": args.topk_frac} if codec == "topk" else {}
        return DynamiQStrategy(optim_spec=optim, codec=codec,
                               **kw, **sched)
    raise ValueError(f"unknown strategy {args.strategy}")


def main():
    p = argparse.ArgumentParser()
    # dataset (reference :36-47)
    p.add_argument("--dataset", default="shakespeare",
                   choices=["shakespeare", "wikitext", "code", "docs", "owt"])
    p.add_argument("--start_pc", type=float, default=0.0)
    p.add_argument("--end_pc", type=float, default=1.0)
    p.add_argument("--block_size", type=int, default=1024)
    # training / model size (:49-58)
    p.add_argument("--num_nodes", type=int, default=1)
    p.add_argument("--device", default=None)
    p.add_argument("--model_size", default="small",
                   choices=["small", "base", "medium", "large", "xl"])
    p.add_argument("--num_epochs", type=int, default=1)
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--minibatch_size", type=int, default=None)
    # optimization (:61-67)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--dropout", type=float, default=0.0,
                   help="model dropout rate (reference nanogpt.py:141)")
    p.add_argument("--max_norm", type=float, default=1.0)
    p.add_argument("--warmup_steps", type=int, default=100)
    p.add_argument("--cosine_anneal", action="store_true")
    p.add_argument("--weight_decay", type=float, default=0.1)
    # bookkeeping (:69-74)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--wandb_project", default=None)
    p.add_argument("--val_size", type=int, default=256)
    p.add_argument("--val_interval", type=int, default=100)
    # strategy (:77-133)
    p.add_argument("--strategy", default="base",
                   choices=["base", "zero", "fedavg", "diloco", "sparta",
                            "diloco_sparta", "demo", "noloco", "dynamiq",
                            "demo_outer"])
    p.add_argument("--H", type=int, default=1)
    p.add_argument("--island_size", type=int, default=None)
    p.add_argument("--p_sparta", type=float, default=0.005)
    p.add_argument("--sparta_interval", type=int, default=1)
    p.add_argument("--diloco_interval", type=int, default=100)
    p.add_argument("--outer_lr", type=float, default=0.7)
    p.add_argument("--nesterov",
                   type=lambda s: s.lower() in ("1", "true", "yes"),
                   default=True)
    p.add_argument("--outer_momentum", type=float, default=0.9)
    p.add_argument("--compression_decay", type=float, default=0.999)
    p.add_argument("--compression_topk", type=int, default=32)
    p.add_argument("--compression_chunk", type=int, default=64)
    p.add_argument("--codec", default=None,
                   choices=["dense", "int8", "int4", "topk"],
                   help="outer-loop payload codec (strategy/compress.py "
                        "CompressedLink): diloco/noloco/demo_outer "
                        "default dense (demo_outer: topk), dynamiq "
                        "defaults int8")
    p.add_argument("--topk_frac", type=float, default=0.01,
                   help="kept fraction for --codec topk")
    # TPU-native additions
    p.add_argument("--cp", type=int, default=1,
                   help="context-parallel devices per node (ring attention)")
    p.add_argument("--attn_impl", default=None,
                   choices=[None, "dense", "flash", "ring"])
    p.add_argument("--seq_layout", default="zigzag",
                   choices=["zigzag", "contiguous"],
                   help="cp chunk assignment (zigzag = load-balanced "
                        "halves, ~2x ring step; contiguous for A/B)")
    p.add_argument("--autocast", action="store_true",
                   help="bf16 forward pass")
    p.add_argument("--n_experts", type=int, default=0,
                   help="MoE: experts per MoE block (0 = dense)")
    p.add_argument("--expert_topk", type=int, default=2)
    p.add_argument("--moe_every", type=int, default=2,
                   help="every Nth block is MoE (2 = alternate)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel devices per node (Megatron "
                        "sharding over a GSPMD-auto 'model' axis)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel devices (shards experts)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel devices per node (GPipe stages;"
                        " grad-accum microbatches are the pipeline's M)")
    p.add_argument("--participation", type=float, default=1.0,
                   help="fraction of nodes alive per comm round "
                        "(simulated failures; fedavg/diloco/sparta)")
    p.add_argument("--skip_nonfinite", action="store_true",
                   help="quarantine non-finite per-node gradients")
    p.add_argument("--sample", type=int, default=0, metavar="N",
                   help="after training, sample N tokens from the "
                        "node-averaged model (KV-cache decoder); with "
                        "--ckpt, sample from that run dir instead of "
                        "training")
    p.add_argument("--ckpt", default=None, metavar="RUN_DIR",
                   help="skip training: params-only restore from this "
                        "checkpoint run dir (fit save_dir/<run_name>) "
                        "and --sample from it")
    # host-overlap pipeline knobs (ISSUE 1) — overlap is the default;
    # the flags select the serial paths for A/Bs and debugging
    p.add_argument("--no_prefetch", action="store_true",
                   help="assemble + device_put every batch on the "
                        "dispatch critical path (overlap off)")
    p.add_argument("--sync_checkpoint", action="store_true",
                   help="blocking checkpoint saves instead of the "
                        "writer-thread overlap")
    p.add_argument("--compilation_cache_dir", default=None, metavar="DIR",
                   help="persistent XLA compile cache (repeat runs skip "
                        "warmup compiles); also honors "
                        "JAX_COMPILATION_CACHE_DIR")
    # network simulation (ISSUE 3): price the strategy's collective trace
    # on a declarative topology and log sim_step_s/sim_total_s
    p.add_argument("--network", default=None, metavar="PRESET",
                   help="simulate this network topology (datacenter, wan, "
                        "federated) — logs simulated per-step and total "
                        "wall-clock alongside comm_bytes")
    p.add_argument("--network_overlap", action="store_true",
                   help="model perfect compute/comm overlap in the "
                        "network simulation (default: comm serializes)")
    args = p.parse_args()

    if args.ckpt:
        # sampling-only mode: params-only restore (gym_tpu.serve.load) —
        # no optimizer-state template, no dataset, no training. A missing
        # or fully corrupt run dir is a one-line message, not a traceback.
        from gym_tpu.serve.load import load_for_serving
        from gym_tpu.utils.checkpoint import CheckpointNotFoundError
        try:
            params, cfg, info = load_for_serving(args.ckpt)
        except (CheckpointNotFoundError, FileNotFoundError,
                ValueError) as e:
            # ValueError covers a non-GPT config.json or a num_nodes /
            # node-axis mismatch — same one-line contract, no traceback
            raise SystemExit(f"nanogpt: cannot sample from {args.ckpt}: "
                             f"{e}")
        print(f"restored step {info['step']} "
              f"({info['num_nodes']}-node average) from {args.ckpt}")
        _print_sample(params, cfg, cfg.vocab_size,
                      args.sample or 200, args.seed)
        return

    if args.device == "cpu":
        # pin the platform LIST, not just the device choice: initializing
        # the full list (this host forces an accelerator plugin first)
        # hangs forever when the accelerator transport is down
        import jax
        jax.config.update("jax_platforms", "cpu")

    attn = args.attn_impl or ("ring" if args.cp > 1 else "dense")

    # dataset factory: per-node OWT shard convention
    # (reference example/nanogpt.py:253-281)
    if args.dataset == "owt":
        def factory(rank, num_nodes, is_val):
            if is_val:
                ds, _ = get_dataset("owt", args.block_size,
                                    start_pc=0.99, end_pc=1.0)
                return ds
            width = 0.99 / num_nodes
            ds, _ = get_dataset(
                "owt", args.block_size,
                start_pc=args.start_pc + rank * width,
                end_pc=args.start_pc + (rank + 1) * width)
            return ds
        train_data, val_data = factory, factory
        _, vocab_size = get_dataset("owt", args.block_size,
                                    start_pc=0.0, end_pc=0.001)
    else:
        ds, vocab_size = get_dataset(args.dataset, args.block_size,
                                     start_pc=args.start_pc,
                                     end_pc=args.end_pc * 0.9)
        val, _ = get_dataset(args.dataset, args.block_size,
                             start_pc=args.end_pc * 0.9, end_pc=args.end_pc)
        train_data, val_data = ds, val

    cfg = GPTConfig.gpt2_size_map(args.model_size)
    cfg.vocab_size = int(vocab_size)
    cfg.block_size = args.block_size
    cfg.attn_impl = attn
    cfg.seq_axis = "seq" if attn == "ring" else None
    cfg.seq_layout = args.seq_layout
    cfg.dropout = args.dropout
    if args.n_experts:
        cfg.n_experts = args.n_experts
        cfg.expert_topk = args.expert_topk
        cfg.moe_every = args.moe_every
        cfg.expert_axis = "expert" if args.ep > 1 else None

    res = Trainer(GPT(cfg), train_data, val_data).fit(
        num_epochs=args.num_epochs,
        max_steps=args.max_steps,
        strategy=create_strategy(args),
        num_nodes=args.num_nodes,
        device=args.device,
        batch_size=args.batch_size,
        minibatch_size=args.minibatch_size,
        cp=args.cp,
        tp=args.tp,
        ep=args.ep,
        pp=args.pp,
        skip_nonfinite=args.skip_nonfinite,
        autocast=args.autocast,
        prefetch=not args.no_prefetch,
        async_checkpoint=not args.sync_checkpoint,
        compilation_cache_dir=args.compilation_cache_dir,
        network=args.network,
        network_overlap=args.network_overlap,
        seed=args.seed,
        val_size=args.val_size,
        val_interval=args.val_interval,
        wandb_project=args.wandb_project,
        run_name=gen_run_name(args),
    )
    print(f"final train loss {res.final_train_loss:.4f} "
          f"({res.steps_per_second:.2f} it/s)")
    if res.sim is not None:
        print(f"simulated on {res.sim['topology']}: "
              f"{res.sim['sim_total_s']:.1f}s total "
              f"({res.sim['sim_comm_s']:.1f}s comm, "
              f"{res.sim['sim_compute_s']:.1f}s compute)")

    if args.sample:
        _print_sample(res.params, cfg, int(vocab_size), args.sample,
                      args.seed)


def _print_sample(params, cfg, vocab_size: int, n: int, seed: int) -> None:
    """Sample ``n`` tokens from token 0 via the KV-cache decoder and print
    them — as text for char-level corpora, token ids otherwise. Shared by
    the post-training path and ``--ckpt`` sampling-only mode."""
    from gym_tpu.data.build_dataset import CHAR_VOCAB
    from gym_tpu.models.nanogpt import generate_fast

    prompt = np.zeros((1, 1), np.int64)  # start from token 0
    n_new = min(n, cfg.block_size - 1)  # KV-cache capacity
    if n_new < n:
        print(f"(clamping sample to {n_new} tokens — the KV cache "
              f"holds block_size={cfg.block_size})")
    out = generate_fast(params, cfg, prompt, n_new,
                        temperature=0.8, top_k=40, seed=seed)
    toks = out[0, 1:].tolist()
    if int(vocab_size) <= len(CHAR_VOCAB) + 1:  # char-level corpus
        text = "".join(CHAR_VOCAB[t] if t < len(CHAR_VOCAB) else ""
                       for t in toks)
        print("--- sample ---")
        print(text)
    else:
        print("--- sample (token ids) ---")
        print(toks)


if __name__ == "__main__":
    main()
