"""gym_tpu — TPU-native framework for simulated distributed training.

Capability-parity rebuild of EXO Gym (see SURVEY.md): K simulated
data-parallel nodes with pluggable synchronization strategies (AllReduce,
FedAvg, DiLoCo, SPARTA, DeMo), implemented SPMD-first on a JAX device mesh
instead of process-per-node message passing.
"""

from .trainer import FitResult, LocalTrainer, Trainer
from .train_node import TrainState

__version__ = "0.1.0"

__all__ = ["Trainer", "LocalTrainer", "FitResult", "TrainState"]
