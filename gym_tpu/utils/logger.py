"""Rank-0 observability: tqdm progress, CSV logs, optional wandb.

Reference (``exogym/logger.py``): base Logger drives a tqdm bar with live
loss/lr postfix; ``CSVLogger`` writes ``logs/<run>/train.csv``,
``validation.csv``, ``config.json``; ``WandbLogger`` mirrors the same
streams plus perplexity ``exp(loss)``. This port adds the metric the
reference forgot to log: cumulative communicated bytes per node (SURVEY
§5.5 — the whole point of these algorithms).
"""

from __future__ import annotations

import csv
import json
import math
import os
import time
from typing import Any, Dict, Optional

try:
    from tqdm import tqdm
except ImportError:  # pragma: no cover
    tqdm = None


class Logger:
    """Progress + train/val loss streams (reference ``logger.py:13-44``)."""

    def __init__(self, max_steps: int, show_progress: bool = True):
        self.max_steps = max_steps
        self.step = 0
        self.cum_comm_bytes = 0.0
        # perf_counter, not time.time: steps_per_second is a DURATION
        # metric and the wall clock steps under NTP
        self._t0 = time.perf_counter()
        self.pbar = (
            tqdm(total=max_steps, dynamic_ncols=True)
            if (show_progress and tqdm is not None)
            else None
        )

    def log_train(self, loss: float, lr: float = 0.0,
                  comm_bytes: float = 0.0,
                  step: Optional[int] = None,
                  sim_step_s: Optional[float] = None) -> None:
        """``step`` pins the record to the step the loss was COMPUTED at
        (the fit loop drains metrics one dispatch late for host overlap,
        so ``self.step`` has already moved on). Required for crash+resume
        CSV stitching: rows are pruned/re-logged by true step.
        ``sim_step_s`` is the network-simulated wall-clock for this step
        (fit(network=...)); None when no network is simulated."""
        self.cum_comm_bytes += comm_bytes
        if self.pbar is not None:
            self.pbar.set_postfix(
                loss=f"{loss:.4f}", lr=f"{lr:.1e}",
                comm=_fmt_bytes(self.cum_comm_bytes),
            )

    def log_loss(self, loss: float, name: str,
                 step: Optional[int] = None) -> None:
        """``step`` pins the record to the step the value was COMPUTED at —
        the fit loop defers eval/correlation host fetches past the next
        dispatch (host-overlap), by which time ``self.step`` has moved on."""
        at = self.step if step is None else step
        if self.pbar is not None:
            self.pbar.write(
                f"step {at}: {name} loss {loss:.4f} "
                f"(ppl {math.exp(min(loss, 20.0)):.2f})"
            )

    def log_event(self, msg: str) -> None:
        """One-off notable event (e.g. non-finite quarantine). Must stay
        visible in headless runs — falls back to stdout when the progress
        bar is off."""
        if self.pbar is not None:
            self.pbar.write(f"step {self.step}: {msg}")
        else:
            print(f"step {self.step}: {msg}")

    def increment_step(self) -> None:
        self.step += 1
        if self.pbar is not None:
            self.pbar.update(1)

    def log_summary(self, summary: Dict[str, Any]) -> None:
        """End-of-run aggregates (it/s, MFU, comm totals)."""
        if self.pbar is not None:
            mfu = summary.get("mfu")
            if mfu is not None:
                self.pbar.write(f"MFU {mfu:.1%}")

    def sync(self) -> None:
        """Make everything logged so far durable (fsync where backed by
        files). The Trainer calls this at every checkpoint boundary so a
        crash after a checkpoint loses no rows the checkpoint covers."""

    def close(self) -> None:
        if self.pbar is not None:
            self.pbar.close()

    @property
    def steps_per_second(self) -> float:
        dt = time.perf_counter() - self._t0
        return self.step / dt if dt > 0 else 0.0


class NullLogger(Logger):
    """Non-primary hosts in a multi-process world (the analog of the
    reference's rank-0-only logger gate, ``train_node.py:585-602``):
    keeps the step/comm counters the fit loop reads, writes nothing."""

    def __init__(self, max_steps: int):
        super().__init__(max_steps, show_progress=False)

    def log_loss(self, loss: float, name: str,
                 step: Optional[int] = None) -> None:
        pass

    def log_event(self, msg: str) -> None:
        pass


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0:
            return f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}PB"


class CSVLogger(Logger):
    """``logs/<run>/{train.csv,validation.csv,config.json}``
    (reference ``logger.py:134-201``).

    Resume semantics (ISSUE 2 — these files used to be opened ``"w"``,
    so a resumed run erased all prior history): with ``resume_step > 0``
    every row logged BEFORE the restored step is preserved and rows at
    or past it are dropped — the resumed run re-logs them, so after a
    crash+resume the files read exactly as an uninterrupted run's. Rows
    are filtered, not blindly appended, because a ``kill -9`` can leave
    a torn final line and rows past the restore point would duplicate.
    ``sync()`` fsyncs both streams; the Trainer calls it at every
    checkpoint boundary, making every row a checkpoint covers durable.
    """

    _TRAIN_HEADER = ["step", "loss", "lr", "comm_bytes", "cum_comm_bytes"]
    _VAL_HEADER = ["step", "name", "loss", "perplexity"]

    def __init__(self, max_steps: int, run_name: Optional[str] = None,
                 log_dir: str = "logs", config: Optional[Dict] = None,
                 show_progress: bool = True, resume_step: int = 0,
                 resume_cum_comm: Optional[float] = None,
                 sim: bool = False):
        super().__init__(max_steps, show_progress)
        run_name = run_name or f"run_{int(time.time())}"
        self.run_dir = os.path.join(log_dir, run_name)
        os.makedirs(self.run_dir, exist_ok=True)
        if config is not None:
            with open(os.path.join(self.run_dir, "config.json"), "w") as f:
                json.dump(_jsonable(config), f, indent=2, default=str)
        # network-simulated runs carry an extra per-row column; the
        # header is fixed per run (resume keeps it consistent because
        # fit(network=...) is pinned by the resumed call's arguments)
        self._sim = bool(sim)
        train_header = (self._TRAIN_HEADER + ["sim_step_s"] if self._sim
                        else self._TRAIN_HEADER)
        # both train formats (with/without the sim column) are valid
        # pre-resume rows: a resumed fit that flips network= must not
        # discard the run's whole history over one column
        train_lens = {len(self._TRAIN_HEADER), len(self._TRAIN_HEADER) + 1}
        self._train_f, self._train_w, train_kept = self._open_csv(
            "train.csv", train_header, resume_step, ok_lens=train_lens)
        self._val_f, self._val_w, _ = self._open_csv(
            "validation.csv", self._VAL_HEADER, resume_step)
        # Comm accumulation continues across the resume so the cum column
        # stays continuous (and bit-identical to an uninterrupted run).
        # ``resume_cum_comm`` is the EXACT accumulator saved in the
        # checkpoint's extra metadata (the Trainer passes it through);
        # the last kept CSV row is the fallback, %.0f-rounded, so with
        # fractional per-step comm it can drift where the extra cannot.
        if resume_cum_comm is not None:
            self.cum_comm_bytes = float(resume_cum_comm)
        elif train_kept:
            try:
                self.cum_comm_bytes = float(train_kept[-1][4])
            except (ValueError, IndexError):
                pass

    def _open_csv(self, name: str, header, resume_step: int,
                  ok_lens=None):
        """(Re)open a CSV stream, keeping pre-restore rows on resume.

        A kept row must have a known column count (``ok_lens``; default
        exactly the header's — a torn line from a mid-write crash is a
        strict prefix, so it has fewer fields or an intact step field
        that the ``< resume_step`` filter drops) and a step strictly
        before the restored step. Rows from an alternate known format
        are padded/truncated to the current header, so e.g. a resume
        that toggles the network-sim column cannot discard the run's
        whole history; torn rows stay excluded because every row a
        checkpoint covers was fsynced complete, and anything after the
        last fsync has a step the ``< resume_step`` filter drops.

        The filtered file is rewritten ATOMICALLY (temp + fsync +
        ``os.replace``) and then opened for append: truncating the
        original in place would leave a window where a kill -9 during
        resume initialization destroys the entire prior history — the
        exact event this layer defends against."""
        path = os.path.join(self.run_dir, name)
        ok_lens = ok_lens or {len(header)}
        kept = []
        if resume_step > 0 and os.path.exists(path):
            with open(path, newline="") as f:
                rows = list(csv.reader(f))
            for r in rows[1:]:
                try:
                    if len(r) in ok_lens and int(r[0]) < resume_step:
                        kept.append((r + [""] * len(header))[:len(header)])
                except ValueError:
                    continue  # unparseable (torn) row
        tmp = path + ".tmp"
        with open(tmp, "w", newline="") as tf:
            tw = csv.writer(tf)
            tw.writerow(header)
            tw.writerows(kept)
            tf.flush()
            os.fsync(tf.fileno())
        os.replace(tmp, path)
        f = open(path, "a", newline="")
        w = csv.writer(f)
        return f, w, kept

    def log_train(self, loss, lr=0.0, comm_bytes=0.0, step=None,
                  sim_step_s=None):
        super().log_train(loss, lr, comm_bytes, step, sim_step_s)
        row = [self.step if step is None else step, f"{loss:.6f}",
               f"{lr:.8f}", f"{comm_bytes:.0f}",
               f"{self.cum_comm_bytes:.0f}"]
        if self._sim:
            row.append("" if sim_step_s is None else f"{sim_step_s:.6f}")
        self._train_w.writerow(row)

    def log_loss(self, loss, name, step=None):
        super().log_loss(loss, name, step)
        self._val_w.writerow(
            [self.step if step is None else step, name, f"{loss:.6f}",
             f"{math.exp(min(loss, 20.0)):.4f}"]
        )
        self._val_f.flush()

    def log_summary(self, summary):
        super().log_summary(summary)
        with open(os.path.join(self.run_dir, "summary.json"), "w") as f:
            json.dump(_jsonable(summary), f, indent=2, default=str)

    def sync(self):
        for f in (self._train_f, self._val_f):
            f.flush()
            os.fsync(f.fileno())

    def close(self):
        super().close()
        self._train_f.close()
        self._val_f.close()


class WandbLogger(Logger):
    """wandb mirror of the CSV streams (reference ``logger.py:47-131``).
    Degrades to base Logger when wandb is unavailable/offline."""

    def __init__(self, max_steps: int, project: str,
                 run_name: Optional[str] = None,
                 config: Optional[Dict] = None, show_progress: bool = True):
        super().__init__(max_steps, show_progress)
        try:
            import wandb
            self._wandb = wandb
            self._run = wandb.init(project=project, name=run_name,
                                   config=_jsonable(config or {}))
        except Exception as e:
            # degrade (offline environments have no wandb) but LOUDLY
            # (VERDICT r3 missing #3: a misconfigured project must not
            # die silently while the run appears to train normally)
            import warnings
            warnings.warn(
                f"wandb logging disabled ({type(e).__name__}: {e}); "
                "falling back to progress-bar-only logging",
                stacklevel=2)
            self._wandb = None
            self._run = None

    def log_train(self, loss, lr=0.0, comm_bytes=0.0, step=None,
                  sim_step_s=None):
        super().log_train(loss, lr, comm_bytes, step, sim_step_s)
        if self._run is not None:
            payload = {"train/loss": loss,
                       "train/perplexity": math.exp(min(loss, 20.0)),
                       "lr": lr, "comm/bytes_step": comm_bytes,
                       "comm/bytes_cum": self.cum_comm_bytes}
            if sim_step_s is not None:
                payload["sim/step_s"] = sim_step_s
            self._run.log(payload,
                          step=self.step if step is None else step)

    def log_loss(self, loss, name, step=None):
        super().log_loss(loss, name, step)
        if self._run is not None:
            self._run.log(
                {f"{name}/loss": loss,
                 f"{name}/perplexity": math.exp(min(loss, 20.0))},
                step=self.step if step is None else step,
            )

    def log_summary(self, summary):
        super().log_summary(summary)
        if self._run is not None:
            self._run.summary.update(
                {k: v for k, v in summary.items() if v is not None}
            )

    def close(self):
        super().close()
        if self._run is not None:
            self._run.finish()


def _jsonable(obj: Any, depth: int = 0) -> Any:
    """Best-effort config serializer (reference ``utils.py:17-99``
    extract_config: depth-guarded, non-serializable values stringified)."""
    if depth > 10:
        return str(obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1) for k, v in
                list(obj.items())[:50]}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v, depth + 1) for v in obj[:10]]
    return str(obj)
