"""Persistent XLA compilation cache wiring (registry-owned).

The fit loop's warmup cost is dominated by XLA compiles of the node
program (~40 s for the bench workload); JAX's persistent compilation
cache makes repeated invocations of the same program — re-running
``bench.py``, iterating on a training script, resuming from a checkpoint
— skip straight to execution.

Since ISSUE 9 the knob is OWNED by the unified device-program registry
(``gym_tpu.programs.registry.enable_disk_tier``): the registry's
persistent executable tier and this helper are the same JAX compilation
cache, configured in one place, with hit/miss monitoring installed so
``programs.xla_compile_counter()`` can attribute deserializations vs
real compiles.  This module stays as the stable ``Trainer.fit`` /
``bench.py`` entry point and simply delegates.
"""

from __future__ import annotations

from typing import Optional

from ..programs.registry import DEFAULT_CACHE_DIR  # noqa: F401 (re-export)


def enable_compilation_cache(
    cache_dir: Optional[str] = None,
    *,
    min_compile_time_secs: Optional[float] = None,
) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Idempotent; safe to call before or after backend initialization (the
    cache is consulted lazily at the first compile). Returns the resolved
    directory. ``min_compile_time_secs=0`` caches even sub-second
    compiles — useful for CPU test/bench programs; by default JAX only
    persists compiles above ~1 s (``None`` leaves JAX's threshold
    untouched). Delegates to the device-program registry's
    ``enable_disk_tier`` — one owner for the disk tier.
    """
    from ..programs.registry import enable_disk_tier

    return enable_disk_tier(cache_dir,
                            min_compile_time_secs=min_compile_time_secs)
