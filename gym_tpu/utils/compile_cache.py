"""Persistent XLA compilation cache wiring.

The fit loop's warmup cost is dominated by XLA compiles of the node
program (~40 s for the bench workload); JAX's persistent compilation
cache makes repeated invocations of the same program — re-running
``bench.py``, iterating on a training script, resuming from a checkpoint
— skip straight to execution. This module is the single place the knob
is wired so ``Trainer.fit``, ``bench.py`` and user scripts all agree on
resolution order: explicit argument > ``JAX_COMPILATION_CACHE_DIR`` env
var > the gym-tpu default under ``~/.cache``.
"""

from __future__ import annotations

import os
from typing import Optional

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "gym_tpu", "xla_cache")


def enable_compilation_cache(
    cache_dir: Optional[str] = None,
    *,
    min_compile_time_secs: Optional[float] = None,
) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Idempotent; safe to call before or after backend initialization (the
    cache is consulted lazily at the first compile). Returns the resolved
    directory. ``min_compile_time_secs=0`` caches even sub-second
    compiles — useful for CPU test/bench programs; by default JAX only
    persists compiles above ~1 s.
    """
    import jax

    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or DEFAULT_CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    if min_compile_time_secs is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_time_secs)
    return cache_dir
