from .compile_cache import enable_compilation_cache
from .logger import CSVLogger, Logger, WandbLogger

__all__ = ["CSVLogger", "Logger", "WandbLogger",
           "enable_compilation_cache"]
