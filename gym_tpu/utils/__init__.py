from .checkpoint import CheckpointNotFoundError
from .compile_cache import enable_compilation_cache
from .integrity import (ChecksumMismatchError, Guard, GuardRuntime,
                        GuardTrippedError, crc32c, tree_fingerprint,
                        verify_sidecar, write_sidecar)
from .logger import CSVLogger, Logger, WandbLogger
from .resilience import (FAULT_SITES, RetryPolicy, Watchdog, corrupt_point,
                         fault_point, faults, with_retries)

__all__ = ["CSVLogger", "Logger", "WandbLogger",
           "CheckpointNotFoundError", "FAULT_SITES", "RetryPolicy",
           "Watchdog", "fault_point", "faults", "with_retries",
           "enable_compilation_cache",
           "ChecksumMismatchError", "Guard", "GuardRuntime",
           "GuardTrippedError", "crc32c", "tree_fingerprint",
           "verify_sidecar", "write_sidecar", "corrupt_point"]
