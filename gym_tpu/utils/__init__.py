from .logger import CSVLogger, Logger, WandbLogger

__all__ = ["CSVLogger", "Logger", "WandbLogger"]
