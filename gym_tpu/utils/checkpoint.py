"""Checkpoint/resume, done for real.

The reference ships a complete checkpoint system that is deliberately
disabled — every path short-circuits with ``return  ## TODO``
(``exogym/train_node.py:248-496``; SURVEY §5.4). Its intended surface:
step-numbered checkpoints per run containing model, optimizer, scheduler,
local_step, epoch and RNG states, newest-first loading with corrupt-file
skip, and keep-latest-only pruning.

This module implements that surface TPU-native with Orbax: ONE checkpoint
per step for the whole K-node mesh (the per-node axis is just the leading
dimension of every array), ``max_to_keep`` pruning, atomic finalization
(replaces the reference's corrupt-zipfile handling), and the data-iterator
position + logger step saved alongside the device state — the two pieces
the reference's fast-forward hack (``train_node.py:444-474``) approximated.

Saves come in two flavors:

- ``save``: synchronous — device→host fetch and the Orbax write both run
  on the caller's thread. Required in a multi-process world (every process
  must participate in the write in lockstep).
- ``save_async``: the overlapped path the Trainer uses single-process. The
  caller hands over a device-side SNAPSHOT (fresh buffers — the Trainer
  jits a ``jnp.copy`` of the state, so the live state can be donated to
  the very next dispatch) and returns immediately; a writer thread does
  the blocking ``jax.device_get`` and the Orbax write off the dispatch
  critical path. If a newer save arrives while one is still being
  written, the older PENDING save is coalesced away (the in-flight write
  completes) — checkpoints are recovery points, the newest wins.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np

PyTree = Any


class CheckpointManager:
    """Orbax-backed manager for a training run.

    Layout: ``<save_dir>/<run_name>/<step>/...`` — the reference's
    ``<save_dir>/<project>/<run>/<rank>/<step>.pt`` without the rank level
    (all simulated nodes live in one sharded state).
    """

    def __init__(self, save_dir: str, run_name: str, max_to_keep: int = 1,
                 async_save: bool = True):
        """``async_save=True`` enables the ``save_async`` writer thread;
        ``False`` forces every save synchronous — required in a
        multi-process world, where a background write on one process
        would race the collective write protocol; the Trainer passes it
        automatically."""
        import orbax.checkpoint as ocp

        self._ocp = ocp
        path = os.path.abspath(os.path.join(save_dir, run_name))
        os.makedirs(path, exist_ok=True)
        self.directory = path
        self.manager = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                # Orbax's own async path still blocks the caller on the
                # device→host copy; our writer thread moves that off the
                # critical path too, so the underlying writes stay sync.
                enable_async_checkpointing=False,
                create=True,
            ),
        )
        self._async = async_save
        self._writer: Optional[threading.Thread] = None
        self._work = threading.Condition()
        self._pending: Optional[tuple] = None
        self._inflight = False
        self._closing = False
        self._writer_error: Optional[BaseException] = None

    # -- writes -----------------------------------------------------------

    def _write(self, step: int, state: PyTree, data_state: dict,
               extra: Optional[dict]) -> None:
        ocp = self._ocp
        meta = {"data_state": data_state, "extra": extra or {}}
        self.manager.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
        )

    def save(self, step: int, state: PyTree, data_state: dict,
             extra: Optional[dict] = None) -> None:
        """Synchronous save of device state + host-side progress metadata.

        State goes to Orbax as-is: in a multi-process world the arrays are
        non-addressable global shards that only Orbax's collective write
        protocol may fetch (a ``device_get`` here would raise)."""
        self.wait()  # serialize with any in-flight async write
        self._write(step, state, data_state, extra)

    def save_async(self, step: int, state_snapshot: PyTree, data_state: dict,
                   extra: Optional[dict] = None) -> None:
        """Enqueue a save and return immediately (writer-thread mode).

        ``state_snapshot`` must be device arrays the caller will NOT
        mutate or donate afterwards — hand over a fresh device-side copy,
        not the live training state. The writer thread performs the
        ``device_get`` and the Orbax write; a still-PENDING older save is
        replaced (newest-wins coalescing) so the queue depth — and the
        HBM pinned by staged snapshots — is bounded at one pending plus
        one in flight.
        """
        if not self._async:
            self.save(step, state_snapshot, data_state, extra)
            return
        with self._work:
            self._raise_writer_error()
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._writer_loop, name="gym-tpu-ckpt-writer",
                    daemon=True)
                self._writer.start()
            self._pending = (step, state_snapshot, data_state, extra)
            self._work.notify_all()

    def _writer_loop(self) -> None:
        while True:
            with self._work:
                while self._pending is None and not self._closing:
                    self._work.wait()
                if self._pending is None:
                    return
                item, self._pending = self._pending, None
                self._inflight = True
            try:
                step, snapshot, data_state, extra = item
                host_state = jax.device_get(snapshot)
                del snapshot  # release the device-side copy promptly
                self._write(step, host_state, data_state, extra)
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                self._writer_error = e
            finally:
                with self._work:
                    self._inflight = False
                    self._work.notify_all()

    def _raise_writer_error(self) -> None:
        if self._writer_error is not None:
            e, self._writer_error = self._writer_error, None
            raise RuntimeError("async checkpoint write failed") from e

    # -- reads / lifecycle ------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, template_state: PyTree,
                step: Optional[int] = None) -> Tuple[int, PyTree, dict, dict]:
        """Restore ``(step, state, data_state, extra)``.

        ``template_state`` supplies shapes/dtypes/shardings (the freshly
        initialized state) so arrays are restored directly onto the mesh.
        """
        ocp = self._ocp
        if step is None:
            step = self.manager.latest_step()
        assert step is not None, "no checkpoint to restore"
        restored = self.manager.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template_state),
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = restored["meta"]
        return int(step), restored["state"], dict(meta["data_state"]), dict(
            meta.get("extra", {})
        )

    def wait(self) -> None:
        """Block until every enqueued save is durable."""
        with self._work:
            while self._pending is not None or self._inflight:
                self._work.wait()
            self._raise_writer_error()
        self.manager.wait_until_finished()

    def close(self) -> None:
        with self._work:
            self._closing = True
            self._work.notify_all()
        if self._writer is not None:
            self._writer.join(timeout=600.0)
        with self._work:
            self._raise_writer_error()
        self.manager.wait_until_finished()
        self.manager.close()
