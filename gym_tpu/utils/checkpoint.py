"""Checkpoint/resume, done for real.

The reference ships a complete checkpoint system that is deliberately
disabled — every path short-circuits with ``return  ## TODO``
(``exogym/train_node.py:248-496``; SURVEY §5.4). Its intended surface:
step-numbered checkpoints per run containing model, optimizer, scheduler,
local_step, epoch and RNG states, newest-first loading with corrupt-file
skip, and keep-latest-only pruning.

This module implements that surface TPU-native with Orbax: ONE checkpoint
per step for the whole K-node mesh (the per-node axis is just the leading
dimension of every array), async save so the TPU never waits on disk,
atomic finalization (replaces the reference's corrupt-zipfile handling),
``max_to_keep`` pruning, and the data-iterator position + logger step saved
alongside the device state — the two pieces the reference's fast-forward
hack (``train_node.py:444-474``) approximated.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

PyTree = Any


class CheckpointManager:
    """Orbax-backed manager for a training run.

    Layout: ``<save_dir>/<run_name>/<step>/...`` — the reference's
    ``<save_dir>/<project>/<run>/<rank>/<step>.pt`` without the rank level
    (all simulated nodes live in one sharded state).
    """

    def __init__(self, save_dir: str, run_name: str, max_to_keep: int = 1,
                 async_save: bool = True):
        """``async_save=False`` forces synchronous saves — required in a
        multi-process world, where Orbax's async finalize (process-0
        metadata commit after every process's write) races max_to_keep
        pruning of the tmp dir; the Trainer passes it automatically."""
        import orbax.checkpoint as ocp

        self._ocp = ocp
        path = os.path.abspath(os.path.join(save_dir, run_name))
        os.makedirs(path, exist_ok=True)
        self.directory = path
        self.manager = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    def save(self, step: int, state: PyTree, data_state: dict,
             extra: Optional[dict] = None) -> None:
        """Async save of device state + host-side progress metadata."""
        ocp = self._ocp
        meta = {"data_state": data_state, "extra": extra or {}}
        self.manager.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
        )

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, template_state: PyTree,
                step: Optional[int] = None) -> Tuple[int, PyTree, dict, dict]:
        """Restore ``(step, state, data_state, extra)``.

        ``template_state`` supplies shapes/dtypes/shardings (the freshly
        initialized state) so arrays are restored directly onto the mesh.
        """
        ocp = self._ocp
        if step is None:
            step = self.manager.latest_step()
        assert step is not None, "no checkpoint to restore"
        restored = self.manager.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template_state),
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = restored["meta"]
        return int(step), restored["state"], dict(meta["data_state"]), dict(
            meta.get("extra", {})
        )

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()
