"""Checkpoint/resume, done for real.

The reference ships a complete checkpoint system that is deliberately
disabled — every path short-circuits with ``return  ## TODO``
(``exogym/train_node.py:248-496``; SURVEY §5.4). Its intended surface:
step-numbered checkpoints per run containing model, optimizer, scheduler,
local_step, epoch and RNG states, newest-first loading with corrupt-file
skip, and keep-latest-only pruning.

This module implements that surface TPU-native with Orbax: ONE checkpoint
per step for the whole K-node mesh (the per-node axis is just the leading
dimension of every array), ``max_to_keep`` pruning, atomic finalization
(replaces the reference's corrupt-zipfile handling), and the data-iterator
position + logger step saved alongside the device state — the two pieces
the reference's fast-forward hack (``train_node.py:444-474``) approximated.

Saves come in two flavors:

- ``save``: synchronous — device→host fetch and the Orbax write both run
  on the caller's thread. Required in a multi-process world (every process
  must participate in the write in lockstep).
- ``save_async``: the overlapped path the Trainer uses single-process. The
  caller hands over a device-side SNAPSHOT (fresh buffers — the Trainer
  jits a ``jnp.copy`` of the state, so the live state can be donated to
  the very next dispatch) and returns immediately; a writer thread does
  the blocking ``jax.device_get`` and the Orbax write off the dispatch
  critical path. If a newer save arrives while one is still being
  written, the older PENDING save is coalesced away (the in-flight write
  completes) — checkpoints are recovery points, the newest wins.

Resilience (ISSUE 2): every write — sync, async, and the writer thread's
``device_get`` — runs under a ``RetryPolicy`` (exponential backoff +
jitter), so one transient ``OSError`` no longer poisons the run through
the writer-thread error latch; ``restore`` walks checkpoints NEWEST-FIRST
and skips past corrupt/torn step dirs (the reference's corrupt-zipfile
skip, ``exogym/train_node.py``), quarantining each aside as
``<step>.corrupt-k`` — never deleting, since a skip may also be a
template mismatch or an IO error that outlived its retries — so a later
save of the same step doesn't collide with Orbax's cached step list; and
a missing checkpoint raises the typed ``CheckpointNotFoundError``
instead of an ``assert``.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np

from . import integrity
from .resilience import (RetryPolicy, Watchdog, dump_thread_stacks,
                         fault_point, watch_or_null, with_retries)

PyTree = Any


class CheckpointNotFoundError(RuntimeError):
    """No (valid) checkpoint exists to restore — either the run directory
    has no committed steps, or every committed step is corrupt."""


class CheckpointWriteError(RuntimeError):
    """The async writer thread's save failed after retries; raised on the
    caller's thread at the next ``save_async``/``wait``. Subclasses
    ``RuntimeError`` so pre-existing ``except RuntimeError`` callers keep
    working."""


class CheckpointWriterStuckError(RuntimeError):
    """``close()`` could not join the writer thread — a write is hung
    (filesystem stall, injected hang); the message carries every
    thread's stack as evidence."""


def restore_params(run_dir: str, step: Optional[int] = None,
                   retry_policy: Optional[RetryPolicy] = None
                   ) -> Tuple[int, PyTree, dict]:
    """Params-only restore from a checkpoint run dir
    (``<save_dir>/<run_name>``) — no train-state template required.

    ``CheckpointManager.restore`` needs the full ``TrainState`` template
    (including optimizer state) to describe shapes/shardings to Orbax; a
    serving process (``gym_tpu/serve``) has no strategy to build one
    from. This walks the committed steps NEWEST-FIRST (or takes the
    pinned ``step``), reads each with Orbax's template-free restore (the
    tree comes back exactly as saved), and returns
    ``(step, state['params'], extra_meta)`` — the per-node-stacked param
    tree with its leading [K] node axis intact (callers average it;
    ``serve.load`` does).

    Read-only by design: unreadable steps are SKIPPED, never quarantined
    or deleted — a serving process must not mutate a run dir a trainer
    may still own. Transient IO errors are retried (``retry_policy``,
    default ``RetryPolicy.from_env()``) before a step is skipped.
    Raises ``CheckpointNotFoundError`` when no (valid) step exists, or
    when a pinned ``step`` is absent.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(run_dir)
    if not os.path.isdir(path):
        raise CheckpointNotFoundError(
            f"checkpoint run dir {path} does not exist")
    retry = retry_policy or RetryPolicy.from_env()
    mgr = ocp.CheckpointManager(
        path, options=ocp.CheckpointManagerOptions(
            create=False, read_only=True))
    try:
        steps = sorted(mgr.all_steps(), reverse=True)
        if step is not None:
            if step not in steps:
                raise CheckpointNotFoundError(
                    f"checkpoint step {step} not found under {path} "
                    f"(have {sorted(steps)})")
            steps = [step]
        if not steps:
            raise CheckpointNotFoundError(
                f"no checkpoint to restore under {path}")

        def read(s):
            # checksum gate first — a bit-flipped shard must surface as
            # the typed mismatch (and a skip to an older step), never as
            # silently wrong serving params; missing sidecar = old
            # checkpoint, accepted
            integrity.verify_sidecar(os.path.join(path, str(s)))
            restored = mgr.restore(
                s, args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(),
                    meta=ocp.args.JsonRestore()))
            state = restored["state"]
            if "params" not in state:
                raise ValueError(
                    f"checkpoint step {s} has no 'params' subtree "
                    f"(keys: {sorted(state)})")
            meta = restored["meta"] or {}
            return int(s), state["params"], dict(meta.get("extra", {}))

        errors = []
        for s in steps:
            try:
                return with_retries(
                    lambda s=s: read(s), retry,
                    describe=f"params-only restore (step {s})")
            except Exception as e:  # noqa: BLE001 — corrupt-step skip
                errors.append((s, e))
                import sys
                sys.stderr.write(
                    f"gym_tpu: skipping unreadable checkpoint step {s} "
                    f"under {path} ({type(e).__name__}: {e})\n")
        raise CheckpointNotFoundError(
            f"no valid checkpoint under {path}: every step in {steps} "
            f"failed to restore "
            f"(newest: {type(errors[0][1]).__name__}: {errors[0][1]})"
        ) from errors[0][1]
    finally:
        mgr.close()


class CheckpointManager:
    """Orbax-backed manager for a training run.

    Layout: ``<save_dir>/<run_name>/<step>/...`` — the reference's
    ``<save_dir>/<project>/<run>/<rank>/<step>.pt`` without the rank level
    (all simulated nodes live in one sharded state).
    """

    def __init__(self, save_dir: str, run_name: str, max_to_keep: int = 2,
                 async_save: bool = True,
                 retry_policy: Optional[RetryPolicy] = None,
                 watchdog: Optional[Watchdog] = None,
                 close_timeout: float = 600.0):
        """``async_save=True`` enables the ``save_async`` writer thread;
        ``False`` forces every save synchronous — required in a
        multi-process world, where a background write on one process
        would race the collective write protocol; the Trainer passes it
        automatically.

        ``max_to_keep`` defaults to 2, not 1: restore falls back past a
        corrupt newest checkpoint, which only helps if an older valid
        one survives pruning. ``retry_policy`` governs transient-IO
        retries (default: ``RetryPolicy.from_env()``); ``watchdog``, when
        set, deadline-protects the blocking write/wait regions."""
        import orbax.checkpoint as ocp

        self._ocp = ocp
        path = os.path.abspath(os.path.join(save_dir, run_name))
        os.makedirs(path, exist_ok=True)
        self.directory = path
        self._max_to_keep = max_to_keep
        self.manager = self._make_manager()
        self._async = async_save
        self._retry = retry_policy or RetryPolicy.from_env()
        self._watchdog = watchdog
        self._close_timeout = close_timeout
        self._writer: Optional[threading.Thread] = None
        self._work = threading.Condition()
        self._pending: Optional[tuple] = None
        self._inflight = False
        self._closing = False
        self._writer_error: Optional[BaseException] = None

    def _make_manager(self):
        ocp = self._ocp
        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep,
                # Orbax's own async path still blocks the caller on the
                # device→host copy; our writer thread moves that off the
                # critical path too, so the underlying writes stay sync.
                enable_async_checkpointing=False,
                create=True,
            ),
        )

    # -- writes -----------------------------------------------------------

    def _write(self, step: int, state: PyTree, data_state: dict,
               extra: Optional[dict]) -> None:
        ocp = self._ocp
        meta = {"data_state": data_state, "extra": extra or {}}

        def attempt():
            fault_point("checkpoint.write")
            self.manager.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    meta=ocp.args.JsonSave(meta),
                ),
            )

        with watch_or_null(self._watchdog, f"checkpoint.write step {step}"):
            with_retries(attempt, self._retry,
                         describe=f"checkpoint write (step {step})")
        # SDC defense (ISSUE 20): hash the finalized step dir into its
        # integrity sidecar, THEN pass it through the checkpoint.bytes
        # corruption site — so the sidecar records the GOOD bytes and an
        # injected bitflip is caught at restore, exactly like real bit
        # rot between write and read. Primary process only: the Orbax
        # write is collective, the sidecar is one file on shared storage.
        if jax.process_index() == 0:
            step_dir = os.path.join(self.directory, str(step))
            with_retries(
                lambda: integrity.write_sidecar(
                    step_dir,
                    fingerprint=integrity.tree_fingerprint_host(state)),
                self._retry,
                describe=f"integrity sidecar write (step {step})")
            integrity.corrupt_checkpoint_files(step_dir)

    def save(self, step: int, state: PyTree, data_state: dict,
             extra: Optional[dict] = None) -> None:
        """Synchronous save of device state + host-side progress metadata.

        State goes to Orbax as-is: in a multi-process world the arrays are
        non-addressable global shards that only Orbax's collective write
        protocol may fetch (a ``device_get`` here would raise)."""
        self.wait()  # serialize with any in-flight async write
        self._write(step, state, data_state, extra)

    def save_async(self, step: int, state_snapshot: PyTree, data_state: dict,
                   extra: Optional[dict] = None) -> None:
        """Enqueue a save and return immediately (writer-thread mode).

        ``state_snapshot`` must be device arrays the caller will NOT
        mutate or donate afterwards — hand over a fresh device-side copy,
        not the live training state. The writer thread performs the
        ``device_get`` and the Orbax write; a still-PENDING older save is
        replaced (newest-wins coalescing) so the queue depth — and the
        HBM pinned by staged snapshots — is bounded at one pending plus
        one in flight.
        """
        if not self._async:
            self.save(step, state_snapshot, data_state, extra)
            return
        with self._work:
            self._raise_writer_error()
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._writer_loop, name="gym-tpu-ckpt-writer",
                    daemon=True)
                self._writer.start()
            self._pending = (step, state_snapshot, data_state, extra)
            self._work.notify_all()

    def _writer_loop(self) -> None:
        while True:
            with self._work:
                while self._pending is None and not self._closing:
                    self._work.wait()
                if self._pending is None:
                    return
                item, self._pending = self._pending, None
                self._inflight = True
            try:
                step, snapshot, data_state, extra = item

                def fetch(snapshot=snapshot):
                    fault_point("checkpoint.device_get")
                    return jax.device_get(snapshot)

                with watch_or_null(self._watchdog,
                                   f"checkpoint.device_get step {step}"):
                    host_state = with_retries(
                        fetch, self._retry,
                        describe=f"checkpoint device_get (step {step})")
                del snapshot  # release the device-side copy promptly
                self._write(step, host_state, data_state, extra)
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                self._writer_error = e
            finally:
                with self._work:
                    self._inflight = False
                    self._work.notify_all()

    def _raise_writer_error(self) -> None:
        if self._writer_error is not None:
            e, self._writer_error = self._writer_error, None
            raise CheckpointWriteError(
                "async checkpoint write failed") from e

    # -- reads / lifecycle ------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def _restore_step(self, step: int, template_state: PyTree
                      ) -> Tuple[int, PyTree, dict, dict]:
        ocp = self._ocp
        # Content verification BEFORE Orbax parses anything: a bit flip
        # Orbax would happily deserialize raises the typed
        # ChecksumMismatchError here, which the newest-first fallback
        # quarantines like any other corrupt step. Sidecar-less steps
        # (pre-integrity checkpoints) pass unverified — soft-degrade.
        integrity.verify_sidecar(os.path.join(self.directory, str(step)))
        # template=None → Orbax's template-free read: the tree comes back
        # exactly as saved (host arrays). The elastic resume path uses
        # this — the saved (K, layout) need not match the live state.
        state_arg = (ocp.args.StandardRestore(template_state)
                     if template_state is not None
                     else ocp.args.StandardRestore())
        restored = self.manager.restore(
            step,
            args=ocp.args.Composite(
                state=state_arg,
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = restored["meta"]
        return int(step), restored["state"], dict(meta["data_state"]), dict(
            meta.get("extra", {})
        )

    def peek_meta(self, step: Optional[int] = None) -> Optional[dict]:
        """Read ONLY the JSON meta of the newest (or pinned) committed
        step: ``{"data_state": ..., "extra": ...}``, or None when no step
        has readable meta. The trainer peeks this BEFORE choosing a
        restore template — the saved membership/layout
        (``extra["elastic"]``) decides whether the plain template restore
        applies or the elastic reshard path must run; attempting a
        template restore against a mismatched layout would quarantine
        perfectly valid checkpoints as 'corrupt'."""
        ocp = self._ocp
        steps = sorted(self.manager.all_steps(), reverse=True)
        if step is not None:
            steps = [step] if step in steps else []
        for s in steps:
            try:
                restored = self.manager.restore(
                    s, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
                return dict(restored["meta"])
            except Exception:  # noqa: BLE001 — peek is best-effort
                continue
        return None

    def restore_raw(self, step: Optional[int] = None
                    ) -> Tuple[int, PyTree, dict, dict]:
        """Template-free ``restore``: the state tree exactly as saved
        (host arrays, whatever K/layout it was written at), with the same
        newest-first walk, corrupt-step quarantine and manager reload as
        the template path. The elastic resume path reads through this and
        reshards the result onto the live membership."""
        return self.restore(None, step=step)

    def restore(self, template_state: PyTree,
                step: Optional[int] = None) -> Tuple[int, PyTree, dict, dict]:
        """Restore ``(step, state, data_state, extra)``.

        ``template_state`` supplies shapes/dtypes/shardings (the freshly
        initialized state) so arrays are restored directly onto the mesh.

        With ``step=None``, walks committed steps NEWEST-FIRST and falls
        back past corrupt/torn step dirs (a ``kill -9`` mid-write, a
        zeroed array file): each skipped dir is logged, QUARANTINED
        (renamed aside, never deleted — a skip may also be a template
        mismatch or an IO error that outlived its retries), and the
        Orbax manager reloaded — its cached step list would otherwise
        silently skip a later re-save of the same step number. Raises
        ``CheckpointNotFoundError`` when no step, or no VALID step,
        exists. With an explicit ``step``, a missing step raises
        ``CheckpointNotFoundError``; a corrupt one propagates the
        underlying error (the caller asked for that exact state).
        """
        if step is not None:
            if step not in self.manager.all_steps():
                raise CheckpointNotFoundError(
                    f"checkpoint step {step} not found under "
                    f"{self.directory} (have {self.manager.all_steps()})")
            return with_retries(
                lambda: self._restore_step(step, template_state),
                self._retry, describe=f"checkpoint restore (step {step})")
        steps = sorted(self.manager.all_steps(), reverse=True)
        if not steps:
            raise CheckpointNotFoundError(
                f"no checkpoint to restore under {self.directory}")
        skipped = []
        out = None
        for s in steps:
            try:
                # transient IO errors are retried BEFORE a step is
                # classified corrupt — the fallback below deletes what it
                # skips, and a one-shot flaky read must not destroy a
                # valid newest checkpoint
                out = with_retries(
                    lambda s=s: self._restore_step(s, template_state),
                    self._retry, describe=f"checkpoint restore (step {s})")
                break
            except Exception as e:  # noqa: BLE001 — corrupt-dir fallback
                skipped.append((s, e))
        if skipped:
            import sys
            for s, e in skipped:
                sys.stderr.write(
                    f"gym_tpu: skipping unreadable checkpoint step {s} "
                    f"under {self.directory} ({type(e).__name__}: {e}); "
                    f"quarantining it and falling back to an older step\n")
                self._quarantine_step(s)
            # Orbax caches the step list at manager construction and
            # SILENTLY skips saves of steps it believes exist — reload so
            # the run (resumed OR restarted fresh after an all-corrupt
            # fallthrough) can re-save the deleted step numbers.
            self.manager.close()
            self.manager = self._make_manager()
        if out is None:
            raise CheckpointNotFoundError(
                f"no valid checkpoint under {self.directory}: every step "
                f"in {steps} failed to restore "
                f"(newest: {type(skipped[0][1]).__name__}: {skipped[0][1]})"
            ) from skipped[0][1]
        return out

    def _quarantine_step(self, step: int) -> None:
        """Move an unreadable step dir aside (``<step>.corrupt-k``) rather
        than deleting it: the restore fallback cannot reliably tell true
        corruption from, say, a template shape mismatch, so what it skips
        must stay recoverable by hand. Orbax ignores non-numeric dirs, so
        the quarantined copy no longer blocks a re-save of the step."""
        src = os.path.join(self.directory, str(step))
        for k in range(100):
            dst = f"{src}.corrupt-{k}"
            if os.path.exists(dst):
                continue
            try:
                os.rename(src, dst)
                return
            except OSError as e:
                # A racing quarantine (or a leftover file at dst) can
                # land between the exists() probe and the rename — that
                # is a COLLISION, so try the next suffix; anything else
                # (src vanished, permissions) won't be fixed by a
                # different k, fall through to the rmtree.
                import errno
                if e.errno in (errno.EEXIST, errno.ENOTEMPTY,
                               errno.ENOTDIR, errno.EISDIR):
                    continue
                break
        shutil.rmtree(src, ignore_errors=True)  # last resort: unblock

    def purge(self) -> None:
        """Delete every committed step and reload the Orbax manager —
        ``fit(resume="never")``'s start-over semantics. The reload
        matters: Orbax caches the step list at construction and silently
        skips saves of step numbers it believes already exist."""
        self.wait()
        for s in list(self.manager.all_steps()):
            shutil.rmtree(os.path.join(self.directory, str(s)),
                          ignore_errors=True)
        self.manager.close()
        self.manager = self._make_manager()

    def wait(self) -> None:
        """Block until every enqueued save is durable."""
        with watch_or_null(self._watchdog, "checkpoint.wait"):
            with self._work:
                while self._pending is not None or self._inflight:
                    self._work.wait()
                self._raise_writer_error()
            self.manager.wait_until_finished()

    def close(self) -> None:
        with self._work:
            self._closing = True
            self._work.notify_all()
        if self._writer is not None:
            self._writer.join(timeout=self._close_timeout)
            if self._writer.is_alive():
                # A silently leaked writer thread means a write is hung
                # (filesystem stall, injected hang) — fail loudly with
                # the evidence rather than pretend the close succeeded.
                raise CheckpointWriterStuckError(
                    f"checkpoint writer thread still alive after "
                    f"{self._close_timeout:.0f}s close timeout — a write "
                    f"is hung\n" + dump_thread_stacks("thread stacks:"))
        with self._work:
            self._raise_writer_error()
        self.manager.wait_until_finished()
        self.manager.close()
