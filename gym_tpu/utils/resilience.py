"""Host-resilience layer: fault injection, retry policy, watchdog.

The device side already simulates node failures (``strategy/faults.py``
partial participation); this module is the HOST side of the fault story —
the failures real TPU fleets see between the accelerator and the
filesystem: preemption, torn checkpoint writes, transient IO errors,
hung threads. Three independent pieces:

- **Fault injection** (``fault_point`` / ``faults``): named sites in the
  host pipeline where tests and the kill harness deterministically
  inject crashes, errors, delays, or hangs. Sites are hit-counted per
  process, so "die at the 3rd dispatch boundary" reproduces exactly.
  Configured programmatically (``faults.install``) or via the
  ``GYM_TPU_FAULTS`` env var, which is how the subprocess kill harness
  arms a child run.
- **Retry policy** (``RetryPolicy`` / ``with_retries``): exponential
  backoff + jitter for transient IO, wrapped around checkpoint writes so
  one flaky ``OSError`` no longer poisons the run via the writer
  thread's error latch.
- **Watchdog** (``Watchdog``): monitors named blocking regions (a
  dispatch drain, a checkpoint write) and, if one exceeds its deadline,
  dumps EVERY thread's stack and fails the process loudly — a hung run
  becomes a diagnosable crash instead of an eternal silent stall.

Registered fault sites (each lists who fires it):

====================== ====================================================
``checkpoint.write``    ``CheckpointManager._write`` — per write attempt
``checkpoint.device_get`` checkpoint writer thread, before the snapshot fetch
``prefetch.fill``       ``HostPrefetcher`` worker, before each batch assembly
``dispatch.boundary``   Trainer fit loop, top of every dispatch iteration
``serve.prefill``       ``InferenceEngine.admit``, before the prefill dispatch
``serve.decode``        ``InferenceEngine.step``, before the decode dispatch
``serve.admit``         ``Scheduler.submit``, before admission control
``serve.http``          ``gym_tpu.serve`` HTTP handler, top of ``POST``
``checkpoint.bytes``    ``integrity.corrupt_checkpoint_files``, after every
                        finalized checkpoint save (corruption-only site)
``wire.frame``          ``serve/wire.py:encode_frame``, every outgoing
                        frame's encoded bytes (corruption-only site)
``dispatch.state``      ``integrity.corrupt_state_tree``, top of every
                        dispatch iteration (corruption-only site)
====================== ====================================================

``GYM_TPU_FAULTS`` spec: comma-separated ``site:action[=arg][@window]``
where action is one of ``kill`` (SIGKILL self — simulated preemption
without grace), ``sigterm`` (SIGTERM self — preemption WITH grace, the
Trainer's handler takes an emergency checkpoint), ``oserror`` (raise
``OSError``), ``delay`` (sleep ``arg`` seconds), ``hang`` (sleep
``arg or 3600`` seconds — watchdog bait), ``bitflip=<n>`` (flip ``n``
deterministically-random bits of the site's payload — silent data
corruption), or ``truncate[=n]`` (drop the payload's last ``n`` bytes,
default half — a torn write); and window is ``@N`` (Nth hit only,
1-based), ``@N-M`` (hits N..M), or ``@N+`` (every hit from N). Default
window: every hit. ``bitflip``/``truncate`` only take effect at the
corruption-capable sites, which pass their payload through
``faults.corrupt``; at plain ``fault_point`` sites they count the hit
and do nothing. Example::

    GYM_TPU_FAULTS="checkpoint.write:oserror@1-2,dispatch.boundary:kill@5"
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import sys
import threading
import time
import traceback
import zlib
from contextlib import contextmanager, nullcontext
from typing import Callable, Dict, List, Optional, Tuple

FAULT_SITES = (
    "checkpoint.write",
    "checkpoint.device_get",
    "prefetch.fill",
    "dispatch.boundary",
    "serve.prefill",
    "serve.decode",
    "serve.admit",
    "serve.http",
    "checkpoint.bytes",
    "wire.frame",
    "dispatch.state",
)

_ACTIONS = ("kill", "sigterm", "oserror", "delay", "hang",
            "bitflip", "truncate")

#: Actions that transform a payload instead of performing a side effect.
#: They fire only through ``FaultRegistry.corrupt`` / ``fire_matched`` —
#: a plain ``fault_point`` has no bytes to corrupt.
_CORRUPT_ACTIONS = ("bitflip", "truncate")


class InjectedFault(OSError):
    """The error raised by an ``oserror`` fault — an ``OSError`` subclass
    so retry policies treat it exactly like a real transient IO error,
    but distinguishable in test assertions."""


class WatchdogTimeoutError(RuntimeError):
    """A watchdog-protected region exceeded its deadline and the run is
    being failed loudly (thread stacks already dumped to stderr). Typed
    so callers distinguish a diagnosed hang from an ordinary error."""


@dataclasses.dataclass
class _Rule:
    site: str
    action: str
    arg: float = 0.0
    first: int = 1               # 1-based hit window [first, last]
    last: Optional[int] = None   # None = open-ended


class FaultRegistry:
    """Deterministic per-process fault injection over named sites."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[_Rule] = []
        self._hits: Dict[str, int] = {}

    def install(self, site: str, action: str, arg: float = 0.0,
                first: int = 1, last: Optional[int] = None) -> None:
        """Arm ``action`` at ``site`` for hit numbers in [first, last]
        (1-based; ``last=None`` means every hit from ``first``,
        ``last=first`` a single hit)."""
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        if site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {site!r}; registered: {FAULT_SITES}")
        with self._lock:
            self._rules.append(_Rule(site, action, arg, first, last))

    def configure(self, spec: str) -> None:
        """Parse a ``GYM_TPU_FAULTS``-format spec (see module docstring)."""
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            window = None
            if "@" in part:
                part, window = part.rsplit("@", 1)
            site, _, action = part.partition(":")
            arg = 0.0
            if "=" in action:
                action, argstr = action.split("=", 1)
                arg = float(argstr)
            first, last = 1, None
            if window:
                if window.endswith("+"):
                    first, last = int(window[:-1]), None
                elif "-" in window:
                    a, b = window.split("-", 1)
                    first, last = int(a), int(b)
                else:
                    first = last = int(window)
            self.install(site.strip(), action.strip(), arg, first, last)

    def reset(self) -> None:
        with self._lock:
            self._rules.clear()
            self._hits.clear()

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    @property
    def active(self) -> bool:
        return bool(self._rules)

    def fire(self, site: str) -> None:
        """Count a hit at ``site`` and perform any matching rule's action.
        Called via ``fault_point`` — a no-op (one attribute read) when no
        rules are armed."""
        self.fire_matched(site)

    def fire_matched(self, site: str) -> Tuple[int, List[_Rule]]:
        """Count a hit, PERFORM matching side-effect rules (kill, delay,
        ...) and return ``(hit, corruption_rules)`` — the hook for sites
        whose payload isn't plain bytes (``dispatch.state`` applies the
        returned ``bitflip`` rules to a live device tree itself)."""
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            matched = [r for r in self._rules
                       if r.site == site and r.first <= n
                       and (r.last is None or n <= r.last)]
        corrupt_rules = []
        for r in matched:
            if r.action in _CORRUPT_ACTIONS:
                corrupt_rules.append(r)
            else:
                self._perform(r, site, n)
        return n, corrupt_rules

    def corrupt(self, site: str, data: bytes) -> bytes:
        """Count a hit at ``site`` and pass ``data`` through any matching
        ``bitflip``/``truncate`` rules (side-effect rules still perform).
        Deterministic: corrupted positions are seeded from site + hit
        number, so a campaign seed reproduces the exact same wrong
        bytes. Returns ``data`` unchanged when nothing matches."""
        n, rules = self.fire_matched(site)
        out = data
        for r in rules:
            if out:
                out = self._corrupt_payload(out, r, site, n)
        return out

    @staticmethod
    def _corrupt_payload(data: bytes, rule: _Rule, site: str,
                         hit: int) -> bytes:
        tag = f"injected fault at {site} (hit {hit})"
        rng = random.Random(zlib.crc32(f"{site}:{hit}".encode()))
        if rule.action == "bitflip":
            buf = bytearray(data)
            nbits = max(1, int(rule.arg))
            for _ in range(nbits):
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            sys.stderr.write(
                f"{tag}: bitflip {nbits} bit(s) in {len(buf)} bytes\n")
            sys.stderr.flush()
            return bytes(buf)
        if rule.action == "truncate":
            drop = int(rule.arg) or max(1, len(data) // 2)
            drop = min(drop, len(data))
            sys.stderr.write(
                f"{tag}: truncate last {drop} of {len(data)} bytes\n")
            sys.stderr.flush()
            return data[:len(data) - drop]
        return data

    @staticmethod
    def _perform(rule: _Rule, site: str, hit: int) -> None:
        tag = f"injected fault at {site} (hit {hit})"
        if rule.action == "kill":
            sys.stderr.write(f"{tag}: SIGKILL\n")
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        elif rule.action == "sigterm":
            sys.stderr.write(f"{tag}: SIGTERM\n")
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGTERM)
        elif rule.action == "oserror":
            raise InjectedFault(f"{tag}: OSError")
        elif rule.action == "delay":
            time.sleep(rule.arg)
        elif rule.action == "hang":
            time.sleep(rule.arg or 3600.0)


#: Process-global registry. Armed from ``GYM_TPU_FAULTS`` at import time
#: (how the subprocess kill harness reaches a child run) and
#: programmatically by in-process tests (``faults.install`` /
#: ``faults.reset``).
faults = FaultRegistry()
faults.configure(os.environ.get("GYM_TPU_FAULTS", ""))


def fault_point(site: str) -> None:
    """Mark a named fault-injection site. Near-zero cost when no faults
    are armed; otherwise counts the hit and performs matching actions."""
    if faults.active:
        faults.fire(site)


def corrupt_point(site: str, data: bytes) -> bytes:
    """Payload-carrying twin of ``fault_point``: pass ``data`` through
    any armed corruption rules at ``site``. Returns ``data`` unchanged
    (no hit counted) when no faults are armed at all — the hot-path
    cost stays one attribute read, same contract as ``fault_point``."""
    if faults.active:
        return faults.corrupt(site, data)
    return data


# -- retry policy ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter for transient host IO.

    Delay before retry k (0-based) is
    ``min(max_delay, base_delay * factor**k) * (1 + U(-jitter, +jitter))``.
    ``attempts`` is the TOTAL number of tries, so ``attempts=1`` disables
    retrying.
    """

    attempts: int = 4
    base_delay: float = 0.1
    factor: float = 2.0
    max_delay: float = 5.0
    jitter: float = 0.25
    retry_on: Tuple[type, ...] = (OSError,)

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Defaults, overridable via ``GYM_TPU_IO_RETRIES`` /
        ``GYM_TPU_IO_RETRY_BASE_S`` / ``GYM_TPU_IO_RETRY_MAX_S`` — the
        kill harness shrinks the delays so crash tests stay fast."""
        return cls(
            attempts=int(os.environ.get("GYM_TPU_IO_RETRIES", 4)),
            base_delay=float(os.environ.get("GYM_TPU_IO_RETRY_BASE_S", 0.1)),
            max_delay=float(os.environ.get("GYM_TPU_IO_RETRY_MAX_S", 5.0)),
        )

    def delay(self, attempt: int, rng: Optional[random.Random] = None
              ) -> float:
        d = min(self.max_delay, self.base_delay * self.factor ** attempt)
        j = (rng or random).uniform(-self.jitter, self.jitter)
        return max(0.0, d * (1.0 + j))


def with_retries(fn: Callable, policy: RetryPolicy, *,
                 describe: str = "operation",
                 on_retry: Optional[Callable] = None,
                 rng: Optional[random.Random] = None):
    """Run ``fn()`` under ``policy``. Retries only ``policy.retry_on``
    exceptions; the final failure propagates unwrapped. ``on_retry(k, exc,
    delay)`` (1-based retry index) observes each retry; the default logs
    to stderr so silent-retry loops don't mask a dying filesystem.

    ``attempts`` is clamped to >= 1: ``GYM_TPU_IO_RETRIES=0`` (a natural
    spelling of "disable retries") must disable RETRYING, not silently
    skip the wrapped operation itself."""
    attempts = max(1, policy.attempts)
    for attempt in range(attempts):
        try:
            return fn()
        except policy.retry_on as e:
            if attempt == attempts - 1:
                raise
            d = policy.delay(attempt, rng)
            if on_retry is not None:
                on_retry(attempt + 1, e, d)
            else:
                sys.stderr.write(
                    f"gym_tpu: transient failure in {describe} "
                    f"(attempt {attempt + 1}/{policy.attempts}): "
                    f"{type(e).__name__}: {e}; retrying in {d:.2f}s\n")
            time.sleep(d)


# -- watchdog -------------------------------------------------------------


def dump_thread_stacks(header: str) -> str:
    """Every live thread's current stack, formatted — the payload a hung
    run leaves behind instead of an eternal silent stall. When the
    program registry reports compiled programs in flight, their keys
    lead the dump, so a wedged dispatch is attributable to a SPECIFIC
    compiled program, not just 'the main thread is inside jax'."""
    lines = [header]
    try:
        # Deferred + guarded: the registry pulls jax; the watchdog must
        # dump stacks even in a process where jax never imported.
        from ..programs.registry import inflight_programs
        inflight = inflight_programs()
    except Exception:
        inflight = {}
    if inflight:
        lines.append("in-flight registry programs (thread id -> key):")
        for tid, key in sorted(inflight.items()):
            lines.append(f"  thread {tid}: program {key}")
    frames = sys._current_frames()
    for t in threading.enumerate():
        lines.append(f"\n--- thread {t.name} (daemon={t.daemon}) ---")
        frame = frames.get(t.ident)
        if frame is None:
            lines.append("  <no frame>")
        else:
            lines.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(lines) + "\n"


class Watchdog:
    """Deadline monitor for named blocking regions.

    Wrap each potentially-hanging operation in ``with wd.watch(label):``.
    A monitor thread polls; if any active region outlives ``timeout``
    seconds the watchdog (once) dumps every thread's stack to stderr and
    fails the run: by default it interrupts the main thread and, if the
    process is still alive after a grace period (the main thread may be
    stuck inside a C call that never returns), hard-exits with status
    86 — loud death over silent hang. Tests pass ``on_timeout`` to
    observe the firing without killing the process.
    """

    EXIT_CODE = 86
    _GRACE_S = 10.0

    def __init__(self, timeout: float,
                 on_timeout: Optional[Callable[[str, str], None]] = None,
                 poll: Optional[float] = None):
        self.timeout = float(timeout)
        self._on_timeout = on_timeout
        self._poll = poll if poll is not None else min(
            1.0, max(0.05, self.timeout / 4.0))
        self._lock = threading.Lock()
        self._active: Dict[int, Tuple[str, float]] = {}
        self._next_token = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired: Optional[str] = None  # label of the region that fired

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._monitor, name="gym-tpu-watchdog", daemon=True)
            self._thread.start()
        return self

    @contextmanager
    def watch(self, label: str, timeout: Optional[float] = None):
        """Deadline-protect a blocking region."""
        deadline = time.monotonic() + (timeout or self.timeout)
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._active[token] = (label, deadline)
        try:
            yield
        finally:
            with self._lock:
                self._active.pop(token, None)

    def _monitor(self) -> None:
        while not self._stop.wait(self._poll):
            now = time.monotonic()
            with self._lock:
                expired = [label for label, dl in self._active.values()
                           if now > dl]
            if expired and self.fired is None:
                self._fire(expired[0])
                return

    def _fire(self, label: str) -> None:
        self.fired = label
        msg = dump_thread_stacks(
            f"gym_tpu watchdog: '{label}' exceeded {self.timeout:.0f}s — "
            f"dumping all thread stacks and failing the run")
        sys.stderr.write(msg)
        sys.stderr.flush()
        if self._on_timeout is not None:
            self._on_timeout(label, msg)
            return
        import _thread
        _thread.interrupt_main()
        # The main thread may be hung inside a C call KeyboardInterrupt
        # can't reach; a watchdog that can itself hang is no watchdog.
        if not self._stop.wait(self._GRACE_S):
            os._exit(self.EXIT_CODE)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def watch_or_null(wd: Optional[Watchdog], label: str):
    """``wd.watch(label)`` or a no-op context — callers wire the watchdog
    optionally without branching at every site."""
    return wd.watch(label) if wd is not None else nullcontext()
