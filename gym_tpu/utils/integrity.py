"""Silent-data-corruption defense (ISSUE 20): integrity tags + guard.

The resilience layer (``resilience.py``) defends against crashes, hangs
and transient IO errors — failures that are LOUD. This module defends
against *wrong bytes*: a bit-flipped checkpoint Orbax still parses, a
corrupted wire frame that is valid JSON, a flipped exponent bit in a
live training state that trains on forever. Production TPU fleets treat
silent data corruption (SDC) as a first-class failure mode; here it is
injectable (``resilience.py`` ``bitflip``/``truncate`` actions),
detectable, and provably recoverable byte-exactly. Four pieces:

- **crc32c** (Castagnoli): pure-stdlib, slicing-by-8 table-driven — the
  checksum production storage/wire stacks use for content integrity.
  No new dependency; fast enough for checkpoint shards at gym scale.
- **Checkpoint sidecars**: ``write_sidecar`` records every file's crc32c
  (+ a host tree fingerprint) in ``<step_dir>/integrity.json`` after an
  Orbax save; ``verify_sidecar`` re-hashes on restore and raises the
  typed ``ChecksumMismatchError`` on any mismatch — which the restore
  fallback routes through the existing ``.corrupt-k`` quarantine, so a
  bit-flipped step is never restored. A MISSING sidecar is accepted
  (old-format checkpoint: mixed-version soft-degrade, the same rule the
  wire protocol applies to crc-less frames).
- **Tree fingerprints**: cheap folded f32 sums over a pytree —
  ``tree_fingerprint`` is jit-able (the guard's on-device hot-path
  probe), ``tree_fingerprint_host`` is the float64 host twin written
  into sidecars.
- **Training guard** (``Guard``/``GuardRuntime``): per-dispatch
  invariants — loss finiteness, an EWMA spike threshold, optional
  state-fingerprint drift — that raise the typed ``GuardTrippedError``.
  ``Trainer.fit(guard=...)`` catches it, rolls back to the last
  checksum-verified checkpoint and REPLAYS; the loop is
  bit-deterministic, so the replayed ``train.csv`` must be
  byte-identical to an uninterrupted run (the oracle the kill harness
  already uses for crashes).

``corrupt_state_tree`` is the ``dispatch.state`` fault hook: it flips
exponent bits in the largest float leaf of the live state — the
worst-case SDC (a mantissa flip may be benign; an exponent flip is the
failure the guard exists to catch).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import sys
import zlib
from typing import Any, Dict, List, Optional, Tuple

PyTree = Any

SIDECAR_NAME = "integrity.json"

# -- crc32c (Castagnoli, reflected 0x82F63B78) -----------------------------

_CRC32C_POLY = 0x82F63B78


def _build_tables() -> List[List[int]]:
    t0 = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for k in range(1, 8):
        prev = tables[k - 1]
        tables.append([t0[prev[n] & 0xFF] ^ (prev[n] >> 8)
                       for n in range(256)])
    return tables


_T = _build_tables()


def crc32c(data: bytes, crc: int = 0) -> int:
    """crc32c of ``data`` (chainable via ``crc``). Slicing-by-8: 8 bytes
    per loop iteration keeps pure-Python hashing usable on multi-MB
    checkpoint shards."""
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    crc = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    mv = memoryview(data)
    n8 = len(mv) - (len(mv) % 8)
    i = 0
    while i < n8:
        b0, b1, b2, b3, b4, b5, b6, b7 = mv[i:i + 8]
        crc ^= b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
               ^ t5[(crc >> 16) & 0xFF] ^ t4[(crc >> 24) & 0xFF]
               ^ t3[b4] ^ t2[b5] ^ t1[b6] ^ t0[b7])
        i += 8
    for b in mv[n8:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def checksum_file(path: str, chunk_bytes: int = 1 << 20
                  ) -> Tuple[int, int]:
    """``(crc32c, size)`` of a file, streamed (shards never fully
    buffered)."""
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            crc = crc32c(block, crc)
            size += len(block)
    return crc, size


# -- typed errors ----------------------------------------------------------


class IntegrityError(RuntimeError):
    """Base class for every integrity violation this module detects."""


class ChecksumMismatchError(IntegrityError):
    """Stored checksum disagrees with the bytes on disk — the content
    changed after it was written (bit rot, torn write, injected
    corruption). The checkpoint restore fallback quarantines on this."""


class GuardTrippedError(RuntimeError):
    """The training guard detected a per-dispatch invariant violation
    (non-finite or spiking loss, fingerprint jump). ``fit(guard=...)``
    catches this to roll back and replay; with rollback exhausted or
    unconfigured it propagates to the caller. Not an ``IntegrityError``
    subclass: a loss spike is an ANOMALY, not proof of bad bytes."""

    def __init__(self, message: str, step: Optional[int] = None,
                 reason: str = ""):
        super().__init__(message)
        self.step = step
        self.reason = reason


# -- checkpoint sidecars ---------------------------------------------------


def _walk_files(step_dir: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            if name == SIDECAR_NAME:
                continue
            full = os.path.join(root, name)
            out.append(os.path.relpath(full, step_dir))
    return sorted(out)


def write_sidecar(step_dir: str,
                  fingerprint: Optional[Dict[str, Any]] = None) -> str:
    """Hash every file under ``step_dir`` into
    ``<step_dir>/integrity.json`` (atomic: tmp + fsync + rename). Called
    right after the Orbax save finalizes; the sidecar travels with the
    step dir through pruning and quarantine for free."""
    record: Dict[str, Any] = {"algo": "crc32c", "files": {}}
    for rel in _walk_files(step_dir):
        crc, size = checksum_file(os.path.join(step_dir, rel))
        record["files"][rel] = {"crc32c": f"{crc:08x}", "size": size}
    if fingerprint is not None:
        record["fingerprint"] = fingerprint
    path = os.path.join(step_dir, SIDECAR_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def verify_sidecar(step_dir: str) -> bool:
    """Re-hash ``step_dir`` against its sidecar. Returns True when
    verified, False when no sidecar exists (pre-ISSUE-20 checkpoint:
    accepted, soft-degrade). Raises ``ChecksumMismatchError`` on any
    missing file or crc/size mismatch — the typed signal the restore
    fallback quarantines on."""
    path = os.path.join(step_dir, SIDECAR_NAME)
    if not os.path.exists(path):
        return False
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ChecksumMismatchError(
            f"unreadable integrity sidecar {path}: "
            f"{type(e).__name__}: {e}") from e
    bad = []
    for rel, want in sorted(record.get("files", {}).items()):
        full = os.path.join(step_dir, rel)
        if not os.path.exists(full):
            bad.append(f"{rel}: file missing")
            continue
        crc, size = checksum_file(full)
        if size != int(want.get("size", -1)):
            bad.append(f"{rel}: size {size} != recorded {want['size']}")
        elif f"{crc:08x}" != want.get("crc32c"):
            bad.append(
                f"{rel}: crc32c {crc:08x} != recorded {want['crc32c']}")
    if bad:
        raise ChecksumMismatchError(
            f"checkpoint content mismatch under {step_dir} "
            f"({len(bad)} file(s)): " + "; ".join(bad))
    return True


def corrupt_checkpoint_files(step_dir: str) -> None:
    """The ``checkpoint.bytes`` fault site: pass the LARGEST file in a
    just-written step dir (deterministically the array shard) through
    the corruption registry. A no-op (beyond the hit count) unless a
    ``bitflip``/``truncate`` rule is armed there."""
    from .resilience import faults
    if not faults.active:
        return
    candidates = [(os.path.getsize(os.path.join(step_dir, rel)), rel)
                  for rel in _walk_files(step_dir)]
    if not candidates:
        faults.fire("checkpoint.bytes")  # keep the hit count honest
        return
    _size, rel = max(candidates)
    path = os.path.join(step_dir, rel)
    with open(path, "rb") as f:
        data = f.read()
    out = faults.corrupt("checkpoint.bytes", data)
    if out != data:
        with open(path, "wb") as f:
            f.write(out)
            f.flush()
            os.fsync(f.fileno())


# -- tree fingerprints -----------------------------------------------------


def tree_fingerprint(tree: PyTree):
    """Folded f32 sum over every numeric leaf — ONE scalar that moves
    when any value moves. Cheap enough for the dispatch hot path and
    jit-able (``jax.jit(tree_fingerprint)``); under a mesh the caller
    replicates the output like any other metric scalar. Used by the
    training guard (finiteness + jump detection), NOT for byte
    integrity — that is crc32c's job."""
    import jax
    import jax.numpy as jnp
    total = jnp.float32(0.0)
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "dtype"):
            continue
        if jnp.issubdtype(leaf.dtype, jnp.floating) or jnp.issubdtype(
                leaf.dtype, jnp.integer):
            total = total + jnp.sum(leaf.astype(jnp.float32))
    return total


def tree_fingerprint_host(tree: PyTree) -> Optional[Dict[str, Any]]:
    """Float64 host-side twin of ``tree_fingerprint``, recorded in the
    checkpoint sidecar (per-leaf sums folded; leaf count pins the tree
    shape). Returns None when any leaf is not fully addressable (the
    multi-process save path may not fetch global shards here)."""
    import jax
    import numpy as np
    total = 0.0
    n = 0
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "dtype"):
            continue
        if not getattr(leaf, "is_fully_addressable", True):
            return None
        arr = np.asarray(leaf)
        if arr.dtype.kind in ("f", "i", "u", "b"):
            total += float(np.sum(arr.astype(np.float64)))
            n += 1
    return {"sum": total, "num_leaves": n}


def corrupt_state_tree(tree: PyTree) -> PyTree:
    """The ``dispatch.state`` fault hook: when a ``bitflip`` rule
    matches this hit, flip exponent bits in the LARGEST float leaf of
    the live tree (deterministic positions, seeded by site+hit).
    Exponent bits are the worst-case SDC — a huge, silent value change
    the guard must catch. Returns the (possibly corrupted) tree; hit
    counting matches every other site."""
    from .resilience import faults
    if not faults.active:
        return tree
    hit, rules = faults.fire_matched("dispatch.state")
    rules = [r for r in rules if r.action == "bitflip"]
    if not rules:
        return tree
    import jax
    import numpy as np
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    float_idx = [i for i, l in enumerate(leaves)
                 if hasattr(l, "dtype")
                 and np.issubdtype(np.dtype(l.dtype), np.floating)]
    if not float_idx:
        return tree
    target = max(float_idx, key=lambda i: leaves[i].size)
    arr = np.array(jax.device_get(leaves[target]))
    view = arr.view(np.uint8).reshape(arr.size, arr.itemsize)
    rng = random.Random(zlib.crc32(f"dispatch.state:{hit}".encode()))
    nbits = sum(max(1, int(r.arg)) for r in rules)
    for _ in range(nbits):
        el = rng.randrange(arr.size)
        # little-endian: the top byte of a float holds sign + exponent
        # MSBs; 0x40 lands on an exponent bit for f32/f16/bf16/f64
        view[el, arr.itemsize - 1] ^= 0x40
    sys.stderr.write(
        f"injected fault at dispatch.state (hit {hit}): flipped {nbits} "
        f"exponent bit(s) in a {arr.shape} {arr.dtype} state leaf\n")
    sys.stderr.flush()
    sharding = getattr(leaves[target], "sharding", None)
    leaves[target] = (jax.device_put(arr, sharding)
                      if sharding is not None else jax.device_put(arr))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- training guard --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Guard:
    """Anomaly-detection policy for ``Trainer.fit(guard=...)``.

    Per-drained-step checks: loss must be finite, and past ``warmup``
    observations it must stay under
    ``max(spike_factor * ewma, ewma + spike_slack)`` — the factor term
    scales with the loss, the absolute slack keeps near-zero converged
    losses from tripping on noise. ``fingerprint_interval`` > 0 adds an
    on-device state-fingerprint probe every N steps (finiteness + a
    relative-jump bound of ``fingerprint_factor``) — the channel that
    sees strategy-state corruption a healthy-looking loss can hide
    until the next outer sync. ``max_rollbacks`` bounds the
    rollback-and-replay loop; a trip past the budget propagates."""

    ewma_alpha: float = 0.2
    spike_factor: float = 3.0
    spike_slack: float = 2.0
    warmup: int = 3
    fingerprint_interval: int = 0
    fingerprint_factor: float = 1e3
    max_rollbacks: int = 2


class GuardRuntime:
    """Mutable guard state, carried ACROSS rollback-and-replay attempts
    (the config dataclass stays frozen). ``observe_loss`` /
    ``observe_fingerprint`` raise ``GuardTrippedError``;
    ``note_rollback`` resets the statistics (the EWMA saw corrupt
    losses) and counts the attempt."""

    def __init__(self, cfg: Optional[Guard] = None):
        self.cfg = cfg or Guard()
        self.rollbacks = 0
        self.trips: List[Tuple[int, str]] = []
        self._reset_stats()

    def _reset_stats(self) -> None:
        self._ewma: Optional[float] = None
        self._seen = 0
        self._last_fp: Optional[float] = None

    def note_rollback(self) -> None:
        self.rollbacks += 1
        self._reset_stats()

    def _trip(self, step: int, reason: str) -> None:
        self.trips.append((step, reason))
        raise GuardTrippedError(
            f"training guard tripped at step {step}: {reason}",
            step=step, reason=reason)

    def observe_loss(self, step: int, loss: float,
                     worst: Optional[float] = None) -> None:
        """``loss`` is the canonical (node 0) value that drives the EWMA;
        ``worst`` is the max across data-parallel nodes and is what the
        trip checks run on. A bitflip in ONE node's replica shows up in
        that node's loss a full step before the all-reduce spreads it —
        checking only the logged loss lets a checkpoint boundary commit
        the corrupt state under a valid sidecar in that window."""
        if worst is None:
            worst = loss
        if not math.isfinite(worst):
            self._trip(step, f"non-finite loss {worst!r}")
        cfg = self.cfg
        if self._ewma is not None and self._seen >= cfg.warmup:
            bound = max(cfg.spike_factor * abs(self._ewma),
                        self._ewma + cfg.spike_slack)
            if worst > bound:
                self._trip(
                    step,
                    f"loss spike {worst:.6g} > bound {bound:.6g} "
                    f"(ewma {self._ewma:.6g})")
        self._ewma = (loss if self._ewma is None
                      else (1 - cfg.ewma_alpha) * self._ewma
                      + cfg.ewma_alpha * loss)
        self._seen += 1

    def observe_fingerprint(self, step: int, fp: float) -> None:
        if not math.isfinite(fp):
            self._trip(step, f"non-finite state fingerprint {fp!r}")
        if self._last_fp is not None:
            jump = abs(fp - self._last_fp)
            bound = self.cfg.fingerprint_factor * (abs(self._last_fp)
                                                   + 1.0)
            if jump > bound:
                self._trip(
                    step,
                    f"state fingerprint jump {jump:.6g} > bound "
                    f"{bound:.6g} (prev {self._last_fp:.6g}, now "
                    f"{fp:.6g})")
        self._last_fp = fp


class _InnerGuard:
    """Internal marker wrapping the runtime for the recursive fit call:
    distinguishes 'the rollback wrapper already owns this run' from a
    user-supplied Guard/GuardRuntime (which engages the wrapper)."""

    __slots__ = ("runtime",)

    def __init__(self, runtime: GuardRuntime):
        self.runtime = runtime
