"""Seeded chaos campaigns over the fault registry (ISSUE 20).

The kill harness proves ONE fault at a time (die at the 3rd dispatch
boundary, resume, byte-exact). A real fleet does not schedule its
failures one per run: a campaign samples a seeded random MIX of faults
— crashes, corruption, latency — across every compatible registered
site, runs the train→checkpoint→serve pipeline under it, and asserts
the invariant suite:

1. **No silent divergence.** Whatever happened mid-run, the completed
   run's ``train.csv`` is byte-identical to a fault-free run — crashes
   recover through checkpoints, corruption through
   quarantine/rollback-replay, and anything else is a violation.
2. **Every failure is typed.** A faulted attempt may die by the
   injected signal, exit through the watchdog, or raise one of the
   KNOWN typed errors. An unclassified traceback is a violation — it
   means a fault escaped the typed-failure discipline.
3. **Recovery completes.** Relaunching (fault-free, like a scheduler
   restarting a preempted job) converges to a completed run within the
   attempt budget; the run dir still serves (``restore_params``).

The module is stdlib-only and pipeline-agnostic: ``run_train_campaign``
drives a caller-supplied ``launch(faults_spec) -> {...}`` closure, so
the CI gate runs it over the subprocess kill-harness worker while unit
tests can drive a stub. Determinism: one integer seed fixes the whole
schedule via ``random.Random(seed)``, and the corruption actions are
themselves seeded by (site, hit) — re-running a seed reproduces the
campaign exactly.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: Sites on the TRAIN pipeline (the campaign target) with the actions
#: that make sense there. ``hang`` is excluded — it needs a watchdog
#: multiple of the run length and would dominate the campaign's wall
#: time; the watchdog has its own dedicated coverage.
TRAIN_SITE_ACTIONS: Dict[str, Tuple[str, ...]] = {
    "dispatch.boundary": ("kill", "sigterm", "delay"),
    "prefetch.fill": ("kill", "oserror", "delay"),
    "checkpoint.write": ("kill", "oserror", "delay"),
    "checkpoint.device_get": ("oserror", "delay"),
    "checkpoint.bytes": ("bitflip", "truncate"),
    "dispatch.state": ("bitflip",),
}

#: Exception type names whose appearance in a failed attempt's stderr
#: classifies the failure as TYPED (invariant 2). Everything here is a
#: deliberately raised, documented failure mode of the stack.
TYPED_ERRORS = (
    "InjectedFault",
    "CheckpointWriteError",
    "CheckpointNotFoundError",
    "CheckpointWriterStuckError",
    "ChecksumMismatchError",
    "GuardTrippedError",
    "WatchdogTimeoutError",
    "FrameCorruptError",
    "TruncatedFrameError",
    "FrameTooLargeError",
    "MalformedFrameError",
    "OSError",
)

#: Watchdog's loud-death status (resilience.Watchdog.EXIT_CODE),
#: duplicated literally so chaos stays importable without jax in the
#: classifier's process — pinned equal in tests/test_chaos_campaign.py.
WATCHDOG_EXIT_CODE = 86

#: Earliest hit the sampler will schedule ``dispatch.state`` corruption
#: at. Live-state corruption BEFORE the guard's EWMA has ``warmup``
#: (default 3) reference observations is undetectable by construction —
#: there is no baseline to spike against, and a checkpoint taken in that
#: window would commit the corrupt state under a VALID sidecar. The
#: floor keeps every sampled event detectable (integrity.Guard warmup 3
#: → first spike-checked observation is the 4th; 5 leaves slack).
GUARD_SAFE_FIRST_HIT = 5


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault: ``site:action[=arg][@window]``."""

    site: str
    action: str
    arg: float = 0.0
    first: int = 1
    last: Optional[int] = None

    def spec(self) -> str:
        part = f"{self.site}:{self.action}"
        if self.arg:
            part += f"={self.arg:g}"
        if self.last == self.first:
            part += f"@{self.first}"
        elif self.last is None and self.first > 1:
            part += f"@{self.first}+"
        elif self.last is not None:
            part += f"@{self.first}-{self.last}"
        return part


def faults_spec(events: Sequence[ChaosEvent]) -> str:
    """``GYM_TPU_FAULTS`` string for a schedule."""
    return ",".join(e.spec() for e in events)


def sample_schedule(seed: int, n_events: Optional[int] = None,
                    max_hit: int = 8,
                    site_actions: Optional[Dict[str, Tuple[str, ...]]]
                    = None) -> List[ChaosEvent]:
    """Seeded random fault schedule: ``n_events`` (default 1-3) single-hit
    events over the compatible (site, action) pairs. Single-hit windows
    (``@N``) keep every event recoverable by construction: a
    once-per-run fault either kills THAT attempt or corrupts ONE
    payload — open-ended windows would make 'relaunch until it
    completes' undecidable. Delay args are kept tiny (the campaign
    measures correctness, not patience)."""
    rng = random.Random(seed)
    sa = site_actions or TRAIN_SITE_ACTIONS
    pairs = [(s, a) for s, acts in sorted(sa.items()) for a in acts]
    n = n_events if n_events is not None else rng.randint(1, 3)
    events = []
    for _ in range(n):
        site, action = rng.choice(pairs)
        hit = rng.randint(1, max_hit)
        if site == "dispatch.state":
            hit = rng.randint(GUARD_SAFE_FIRST_HIT,
                              max(GUARD_SAFE_FIRST_HIT, max_hit))
        arg = 0.0
        if action == "delay":
            arg = round(rng.uniform(0.01, 0.1), 3)
        elif action == "bitflip":
            arg = float(rng.randint(1, 4))
        events.append(ChaosEvent(site, action, arg, first=hit, last=hit))
    return events


def classify_exit(returncode: int, stderr: str = "") -> str:
    """Classify one attempt's exit: ``clean``, a known signal death,
    the watchdog's loud exit, a TYPED error, or ``unclassified`` — the
    last being invariant violation 2 (an untyped escape)."""
    if returncode == 0:
        return "clean"
    if returncode == -9 or returncode == 137:
        return "killed"
    if returncode == -15 or returncode == 143:
        return "sigterm"
    if returncode == WATCHDOG_EXIT_CODE:
        return "watchdog"
    for name in TYPED_ERRORS:
        if name in stderr:
            return f"typed:{name}"
    return "unclassified"


@dataclasses.dataclass
class CampaignResult:
    seed: int
    events: List[ChaosEvent]
    attempts: List[str]          # classification of each launch
    completed: bool
    violations: List[str]

    @property
    def ok(self) -> bool:
        return self.completed and not self.violations


def run_train_campaign(
        seed: int,
        launch: Callable[[str], Dict[str, Any]],
        verify: Optional[Callable[[], List[str]]] = None,
        max_launches: int = 6,
        n_events: Optional[int] = None,
        site_actions: Optional[Dict[str, Tuple[str, ...]]] = None,
) -> CampaignResult:
    """Run one seeded campaign.

    ``launch(faults_spec)`` runs the pipeline once under the given
    ``GYM_TPU_FAULTS`` spec and returns at least ``{"returncode": int,
    "stderr": str, "completed": bool}``. The FIRST launch is armed with
    the sampled schedule; every subsequent launch is fault-free — the
    scheduler-restarts-the-job model, identical to the kill harness.
    ``verify()`` runs after completion and returns violation strings
    (the caller owns the oracles: train.csv byte-compare, serve
    handoff); launch/verify exceptions are violations, not crashes of
    the campaign itself.
    """
    events = sample_schedule(seed, n_events=n_events,
                             site_actions=site_actions)
    attempts: List[str] = []
    violations: List[str] = []
    completed = False
    for i in range(max_launches):
        spec = faults_spec(events) if i == 0 else ""
        try:
            out = launch(spec)
        except Exception as e:  # noqa: BLE001 — harness bug, not SDC
            violations.append(
                f"launch {i} raised {type(e).__name__}: {e}")
            break
        cls = classify_exit(int(out.get("returncode", -1)),
                            str(out.get("stderr", "")))
        attempts.append(cls)
        if cls == "unclassified":
            violations.append(
                f"launch {i} died UNTYPED (rc={out.get('returncode')}): "
                f"{str(out.get('stderr', ''))[-500:]}")
            break
        if out.get("completed"):
            completed = True
            break
    if not completed and not violations:
        violations.append(
            f"campaign did not complete within {max_launches} launches "
            f"(attempts: {attempts})")
    if completed and verify is not None:
        try:
            violations.extend(verify())
        except Exception as e:  # noqa: BLE001 — oracle failure IS a finding
            violations.append(
                f"verify() raised {type(e).__name__}: {e}")
    return CampaignResult(seed=seed, events=events, attempts=attempts,
                          completed=completed, violations=violations)
