"""Serving observability: ``serve.csv`` + aggregate headline.

CSVLogger-style (``utils/logger.py``): one append-only CSV under the
serve log dir, fsync on ``sync()``, atomic enough for a tail -f. Two row
kinds share the header:

- ``request`` — one row per completed/failed request: TTFT, new-token
  count, mean per-token latency, and the queue/slot state at completion.
- ``engine``  — a periodic engine sample (every ``engine_log_every``
  ticks of the driver loop): cumulative tokens, rolling tokens/s, queue
  depth, active-slot occupancy.

``headline()`` aggregates the run into the one-line JSON surface
``bench.py --serve-only`` and the HTTP ``/stats`` endpoint report.
"""

from __future__ import annotations

import csv
import os
import threading
import time
from typing import Any, Dict

HEADER = [
    "ts_s", "kind", "request_id", "status", "queue_depth", "active_slots",
    "prompt_tokens", "new_tokens", "ttft_s", "avg_token_latency_s",
    "cum_tokens", "tokens_per_s",
]


class ServeMetrics:
    def __init__(self, out_dir: str, engine_log_every: int = 50):
        os.makedirs(out_dir, exist_ok=True)
        self.path = os.path.join(out_dir, "serve.csv")
        # append, not "w": a server restart over the same run dir must
        # not destroy the previous run's request history — the header is
        # written only when the file is new/empty
        new_file = (not os.path.exists(self.path)
                    or os.path.getsize(self.path) == 0)
        self._f = open(self.path, "a", newline="")
        self._w = csv.writer(self._f)
        if new_file:
            self._w.writerow(HEADER)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._every = max(1, int(engine_log_every))
        self._ticks = 0
        self.requests_done = 0
        self.requests_failed = 0
        self.tokens_out = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0
        self._lat_sum = 0.0
        self._lat_n = 0

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def request_done(self, req, queue_depth: int,
                     active_slots: int) -> None:
        with self._lock:
            failed = req.error is not None
            self.requests_failed += int(failed)
            self.requests_done += int(not failed)
            self.tokens_out += len(req.tokens)
            ttft = req.ttft_s
            lat = req.avg_token_latency_s
            if ttft is not None:
                self._ttft_sum += ttft
                self._ttft_n += 1
            if lat is not None:
                self._lat_sum += lat
                self._lat_n += 1
            self._w.writerow([
                f"{self._now():.4f}", "request", req.id,
                "failed" if failed else "done", queue_depth, active_slots,
                int(req.prompt.size), len(req.tokens),
                "" if ttft is None else f"{ttft:.5f}",
                "" if lat is None else f"{lat:.5f}",
                self.tokens_out, f"{self.tokens_per_s():.2f}",
            ])
            self._f.flush()

    def engine_tick(self, stats, queue_depth: int) -> None:
        """Sampled engine row — call once per driver-loop round; writes
        every ``engine_log_every``-th call so an idle server doesn't grow
        the CSV unboundedly."""
        with self._lock:
            self._ticks += 1
            if self._ticks % self._every:
                return
            self._w.writerow([
                f"{self._now():.4f}", "engine", "", "", queue_depth,
                stats.active_slots, "", "", "", "",
                stats.tokens_generated, f"{self.tokens_per_s():.2f}",
            ])

    def tokens_per_s(self) -> float:
        dt = self._now()
        return self.tokens_out / dt if dt > 0 else 0.0

    def headline(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "requests_done": self.requests_done,
                "requests_failed": self.requests_failed,
                "tokens_out": self.tokens_out,
                "wall_s": round(self._now(), 3),
                "tokens_per_s": round(self.tokens_per_s(), 2),
                "mean_ttft_s": (round(self._ttft_sum / self._ttft_n, 5)
                                if self._ttft_n else None),
                "mean_token_latency_s": (
                    round(self._lat_sum / self._lat_n, 5)
                    if self._lat_n else None),
            }

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            self._f.close()
