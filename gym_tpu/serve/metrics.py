"""Serving observability: ``serve.csv`` + aggregate headline.

CSVLogger-style (``utils/logger.py``): one append-only CSV under the
serve log dir, fsync on ``sync()``, atomic enough for a tail -f. Two row
kinds share the header:

- ``request`` — one row per completed/failed request: TTFT, new-token
  count, mean per-token latency, and the queue/slot state at completion.
  The ``status`` column types the outcome: ``done``, ``failed``,
  ``shed`` (deadline elapsed — queued shed or running cancelled),
  ``quarantined`` (NaN/Inf logits in the slot), ``rejected`` (admission
  control turned it away before it was ever enqueued).
- ``engine``  — a periodic engine sample (every ``engine_log_every``
  ticks of the driver loop): cumulative tokens, rolling tokens/s, queue
  depth, active-slot occupancy, plus the paged-KV/speculative
  observables ``kv_blocks_in_use`` / ``prefix_hit_blocks`` /
  ``spec_accept_rate`` (blank-or-zero on unpaged engines and absent in
  pre-paging CSVs). ``status=restart`` marks a supervisor engine
  rebuild; ``status=reload`` a rolling weight hot-swap.

Fleet serving (``serve/router.py``) shares ONE collector across N
replicas: each replica's scheduler and supervisor write through a
``replica_view(replica_id)`` facade, which stamps the new
``replica_id`` column (blank on single-engine CSVs; ``read_headline``
tolerates its absence, like the PR-7 schema bump) and maintains a
PER-REPLICA tokens/s EWMA — the fleet's interleaved engine ticks would
otherwise difference two different engines' token counters and produce
garbage rates. Per-replica admission control reads its own replica's
EWMA; ``headline()`` reports the fleet aggregate plus a ``replicas``
section.

Fleet counters are per-ATTEMPT, not per-client-request: a transparently
failed-over request shows up as one ``failed`` attempt on the dead
replica plus one ``done`` attempt on the sibling (the client saw a
single 200). Alert on the router's ``retries_exhausted`` — the count of
engine-death failures that actually REACHED a client — and reconcile
``requests_failed`` against ``failovers``, both in ``/stats``.

Beyond the counters, the collector maintains a tokens/s EWMA over driver
ticks — the live service-rate estimate ``Scheduler.submit`` uses for
admission control — and p50/p95/p99 percentiles of TTFT and per-token
latency (tail latency is the serving observable; a mean hides a wedged
tail completely).

``headline()`` aggregates the run into the one-line JSON surface
``bench.py --serve-only`` / ``--chaos-only`` and the HTTP ``/stats``
endpoint report; ``read_headline(path)`` recomputes the same aggregate
from a ``serve.csv`` on disk (post-hoc analysis, tests on synthetic
files).
"""

from __future__ import annotations

import csv
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

HEADER = [
    "ts_s", "kind", "request_id", "status", "queue_depth", "active_slots",
    "prompt_tokens", "new_tokens", "ttft_s", "avg_token_latency_s",
    "cum_tokens", "tokens_per_s",
    # paged-KV / speculative observables (engine rows; blank on request
    # rows and absent in pre-paging CSVs — read_headline tolerates both)
    "kv_blocks_in_use", "prefix_hit_blocks", "spec_accept_rate",
    # fleet serving: which replica produced the row (blank on
    # single-engine collectors and absent in pre-fleet CSVs)
    "replica_id",
    # device-program registry counters (engine rows; absent in
    # pre-registry CSVs — read_headline tolerates both): cumulative
    # in-memory builds, builds that ran XLA (disk-tier hits excluded),
    # and wall seconds inside builds. A restart/reload row whose
    # programs_compiled matches the previous engine row is the
    # zero-recompile seam, on disk.
    "programs_built", "programs_compiled", "program_compile_s",
    # quantized serving (ISSUE 11; engine rows): the dtype the params
    # and KV pools are stored in — the config echo that makes a
    # serve.csv self-describing about WHAT was serving when its rates
    # were sampled. Absent in pre-quantization CSVs; read_headline
    # tolerates both (like the paging and fleet schema bumps).
    "weights_dtype", "kv_dtype",
    # out-of-process fleet (ISSUE 13): which OS process produced the
    # row's work — the server pid for in-process replicas, the worker
    # subprocess pid for process replicas. Absent in pre-fleet-process
    # CSVs; read_headline tolerates both (pinned, per repo convention).
    "pid",
    # serving simulator (ISSUE 15): request rows carry the wall-clock
    # offset (vs the collector's t0) at which the request was SUBMITTED
    # — durations alone cannot reconstruct an arrival process, and the
    # trace replayer (servesim/traces.py: replay_from_serve_csv) needs
    # exact arrivals. Absent in pre-servesim CSVs; read_headline
    # tolerates both.
    "t_submit",
    # autoscaler audit trail (ISSUE 15): ``kind=autoscale`` rows record
    # every controller tick — the snapshot it priced (healthy/starting
    # counts, backlog tokens; the rate rides the tokens_per_s column),
    # the decision (status: up/down/hold) and the REASON string — so
    # sim-vs-live validation and postmortems read decisions off disk
    # instead of reverse-engineering them from replica counts. Absent
    # in pre-servesim CSVs; read_headline tolerates both.
    "as_healthy", "as_starting", "as_backlog_tokens", "as_reason",
    # multi-tenant serving (ISSUE 17): who a request row belongs to and
    # which SLO class priced it. Request rows also gain two new status
    # values — ``preempted`` (a running low-priority request parked at a
    # chunk boundary to free its slot; an EVENT row, the request is
    # still live) and ``resumed`` (the parked request got a slot back).
    # Absent in pre-tenant CSVs; read_headline tolerates both (pinned,
    # per repo convention).
    "tenant", "slo_class",
]

#: EWMA smoothing for the live tokens/s estimate (per driver tick with
#: token progress). 0.2 ≈ a ~5-tick memory: reactive enough to track a
#: fault-induced slowdown, smooth enough not to flap admission control.
EWMA_ALPHA = 0.2

#: A fully idle engine (no active slots, empty queue, no token flow) for
#: this long resets the EWMA to None — cold again, admission turns
#: optimistic. Without this, a transient-slowdown rate measured before an
#: idle period would keep rejecting deadline'd requests forever: rejected
#: requests generate no tokens, so a stale-low EWMA could never refresh.
EWMA_IDLE_RESET_S = 10.0

#: Tail-latency sample window. Serving runs are unbounded; percentiles
#: over the last N requests keep memory flat and the numbers current.
PERCENTILE_WINDOW = 10_000

_PCTS = (50, 95, 99)


def _percentiles(samples, prefix: str) -> Dict[str, Optional[float]]:
    out: Dict[str, Optional[float]] = {}
    arr = np.asarray([s for s in samples if s is not None], np.float64)
    for p in _PCTS:
        out[f"{prefix}_p{p}_s"] = (
            round(float(np.percentile(arr, p)), 5) if arr.size else None)
    return out


#: request-failure exception class → serve.csv status value. Typed by
#: NAME so metrics stays import-decoupled from the scheduler.
_STATUS_BY_EXC = {
    "DeadlineExceededError": "shed",
    "SlotQuarantinedError": "quarantined",
    # client went away mid-stream (EPIPE on the chunked write): the
    # request was cancelled at the next decode-chunk boundary — a
    # client decision, recorded distinctly and NOT counted as a server
    # failure
    "RequestCancelledError": "disconnected",
}


def _program_counters() -> Optional[Dict[str, Any]]:
    """Live device-program-registry counters (plus the persistent-cache
    event totals), or None if the registry is unimportable — metrics
    must keep writing rows even if the programs package is broken."""
    try:
        from ..programs import default_registry, disk_event_counters
        return {**default_registry().counters(), **disk_event_counters()}
    except Exception:  # noqa: BLE001 — observability must not crash
        return None


class _RateState:
    """One engine's tokens/s EWMA state — per replica in a fleet (the
    interleaved ticks of two engines must never be differenced against
    each other) plus the legacy single-engine slot. Caller holds the
    collector's lock."""

    __slots__ = ("ewma", "last_tok", "last_t", "idle_since")

    def __init__(self):
        self.ewma: Optional[float] = None
        self.last_tok = 0
        self.last_t: Optional[float] = None
        self.idle_since: Optional[float] = None

    def update(self, tok: int, now: float, active_slots: int,
               queue_depth: int, idle_reset_s: float) -> None:
        if self.last_t is not None:
            d_tok = tok - self.last_tok
            d_t = now - self.last_t
            # d_tok < 0 = the engine was rebuilt/hot-swapped (counter
            # reset): re-anchor, keep the old EWMA — the rate estimate
            # survives a supervisor failover or a weight reload
            if d_tok > 0 and d_t > 0:
                inst = d_tok / d_t
                self.ewma = (inst if self.ewma is None else
                             EWMA_ALPHA * inst
                             + (1.0 - EWMA_ALPHA) * self.ewma)
                self.idle_since = None
            elif int(active_slots) == 0 and queue_depth == 0:
                # fully idle: after a while the old rate says nothing
                # about the next request — go cold (optimistic admit)
                # rather than reject on a stale-low estimate. A
                # BUSY-but-stalled engine keeps its honest low rate.
                if self.idle_since is None:
                    self.idle_since = now
                elif (now - self.idle_since >= idle_reset_s
                      and self.ewma is not None):
                    self.ewma = None
            else:
                self.idle_since = None
        self.last_tok, self.last_t = tok, now


class _ReplicaAgg:
    """Per-replica slice of the fleet counters (the ``replicas`` section
    of ``headline()``). Caller holds the collector's lock."""

    __slots__ = ("rate", "done", "failed", "shed", "quarantined",
                 "rejected", "disconnected", "restarts", "reloads",
                 "tokens_out", "kv_blocks_in_use", "prefix_hit_blocks",
                 "spec_accept_rate", "pid")

    def __init__(self):
        self.rate = _RateState()
        self.done = self.failed = self.shed = 0
        self.quarantined = self.rejected = 0
        self.disconnected = 0
        self.restarts = self.reloads = 0
        self.tokens_out = 0
        self.kv_blocks_in_use = 0
        self.prefix_hit_blocks = 0
        self.spec_accept_rate: Optional[float] = None
        self.pid: Optional[int] = None

    def headline(self) -> Dict[str, Any]:
        return {
            "requests_done": self.done,
            "requests_failed": self.failed,
            "requests_shed": self.shed,
            "requests_quarantined": self.quarantined,
            "requests_rejected": self.rejected,
            "requests_disconnected": self.disconnected,
            "engine_restarts": self.restarts,
            "engine_reloads": self.reloads,
            "tokens_out": self.tokens_out,
            "tokens_per_s_ewma": (round(self.rate.ewma, 2)
                                  if self.rate.ewma is not None else None),
            "kv_blocks_in_use": self.kv_blocks_in_use,
            "prefix_hit_blocks": self.prefix_hit_blocks,
            "pid": self.pid,
        }


class _ClassAgg:
    """Per-SLO-class slice of the request counters + TTFT tail (the
    ``classes`` section of ``headline()``; ISSUE 17). Caller holds the
    collector's lock."""

    __slots__ = ("done", "shed", "rejected", "preempted", "resumed",
                 "ttfts")

    def __init__(self):
        self.done = self.shed = self.rejected = 0
        self.preempted = self.resumed = 0
        self.ttfts: deque = deque(maxlen=PERCENTILE_WINDOW)

    def headline(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "requests_done": self.done,
            "requests_shed": self.shed,
            "requests_rejected": self.rejected,
            "preemptions": self.preempted,
            "resumes": self.resumed,
        }
        out.update(_percentiles(self.ttfts, "ttft"))
        return out


class ReplicaMetrics:
    """Replica-scoped facade over a shared ``ServeMetrics``: the exact
    collector interface a ``Scheduler``/``Supervisor`` consumes, with
    the replica id stamped on every write and the EWMA read scoped to
    this replica (admission control must price a replica's OWN backlog
    against its OWN service rate)."""

    def __init__(self, base: "ServeMetrics", replica_id: int,
                 pid: Optional[int] = None):
        self.base = base
        self.replica_id = int(replica_id)
        # in-process replicas all live in the server process; the
        # process fleet stamps each worker's own pid
        self.pid = os.getpid() if pid is None else int(pid)

    def request_done(self, req, queue_depth: int,
                     active_slots: int) -> None:
        self.base.request_done(req, queue_depth, active_slots,
                               replica_id=self.replica_id, pid=self.pid)

    def request_rejected(self, queue_depth: int, active_slots: int,
                         tenant: Optional[str] = None,
                         slo_class: Optional[str] = None) -> None:
        self.base.request_rejected(queue_depth, active_slots,
                                   replica_id=self.replica_id,
                                   pid=self.pid, tenant=tenant,
                                   slo_class=slo_class)

    def request_preempted(self, req, queue_depth: int,
                          active_slots: int) -> None:
        self.base.request_preempted(req, queue_depth, active_slots,
                                    replica_id=self.replica_id,
                                    pid=self.pid)

    def request_resumed(self, req, queue_depth: int,
                        active_slots: int) -> None:
        self.base.request_resumed(req, queue_depth, active_slots,
                                  replica_id=self.replica_id,
                                  pid=self.pid)

    def engine_tick(self, stats, queue_depth: int) -> None:
        self.base.engine_tick(stats, queue_depth,
                              replica_id=self.replica_id, pid=self.pid)

    def engine_restarted(self) -> None:
        self.base.engine_restarted(replica_id=self.replica_id,
                                   pid=self.pid)

    def engine_reloaded(self) -> None:
        self.base.engine_reloaded(replica_id=self.replica_id,
                                  pid=self.pid)

    def tokens_per_s_ewma(self) -> Optional[float]:
        return self.base.tokens_per_s_ewma(replica_id=self.replica_id)

    def headline(self) -> Dict[str, Any]:
        return self.base.headline()

    def sync(self) -> None:
        self.base.sync()


class ServeMetrics:
    def __init__(self, out_dir: str, engine_log_every: int = 50,
                 ewma_idle_reset_s: float = EWMA_IDLE_RESET_S):
        os.makedirs(out_dir, exist_ok=True)
        self.path = os.path.join(out_dir, "serve.csv")
        # append, not "w": a server restart over the same run dir must
        # not destroy the previous run's request history — the header is
        # written only when the file is new/empty
        new_file = (not os.path.exists(self.path)
                    or os.path.getsize(self.path) == 0)
        self._f = open(self.path, "a", newline="")
        self._w = csv.writer(self._f)
        if new_file:
            self._w.writerow(HEADER)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._every = max(1, int(engine_log_every))
        self._ticks = 0
        self.requests_done = 0
        self.requests_failed = 0
        self.requests_shed = 0
        self.requests_quarantined = 0
        self.requests_rejected = 0
        self.requests_disconnected = 0
        # multi-tenant serving (ISSUE 17): preempt/resume are EVENTS on
        # live requests, not completions — their own counters, never
        # inflating requests_done/failed
        self.requests_preempted = 0
        self.requests_resumed = 0
        self._classes: Dict[str, _ClassAgg] = {}
        self.engine_restarts = 0
        self.engine_reloads = 0
        # out-of-process fleet counters (ISSUE 13): process-replica
        # lifecycle (autoscaler spawns/retires + kill-respawns) and the
        # live count of token streams currently being written to
        # clients (the HTTP layer gates it around each SSE response)
        self.replicas_spawned = 0
        self.replicas_retired = 0
        self.streams_active = 0
        # autoscaler audit trail (ISSUE 15): controller-tick counters
        # next to the per-tick CSV rows
        self.autoscale_ticks = 0
        self.autoscale_ups = 0
        self.autoscale_downs = 0
        self.tokens_out = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0
        self._lat_sum = 0.0
        self._lat_n = 0
        self._ttfts: deque = deque(maxlen=PERCENTILE_WINDOW)
        self._lats: deque = deque(maxlen=PERCENTILE_WINDOW)
        self._rate = _RateState()       # legacy single-engine EWMA slot
        self._replicas: Dict[int, _ReplicaAgg] = {}
        self._ewma_idle_reset_s = float(ewma_idle_reset_s)
        # last engine sample of the paged/speculative observables (an
        # unpaged engine reports 0 blocks and a None accept rate)
        self._kv_blocks_in_use = 0
        self._prefix_hit_blocks = 0
        self._spec_accept_rate: Optional[float] = None
        # last engine sample of the quantized-serving config echo (None
        # until the first tick; fleet replicas share one config, so a
        # collector-level last-wins sample is exact)
        self._weights_dtype: Optional[str] = None
        self._kv_dtype: Optional[str] = None

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def replica_view(self, replica_id: int,
                     pid: Optional[int] = None) -> ReplicaMetrics:
        """Replica-scoped facade for one fleet member's scheduler and
        supervisor (see ``ReplicaMetrics``)."""
        with self._lock:
            agg = self._replicas.setdefault(int(replica_id),
                                            _ReplicaAgg())
            agg.pid = os.getpid() if pid is None else int(pid)
        return ReplicaMetrics(self, replica_id, pid=pid)

    # -- process-fleet lifecycle (ISSUE 13) -------------------------------

    def replica_spawned(self, replica_id: Optional[int] = None,
                        pid: Optional[int] = None) -> None:
        """A replica worker process was spawned (fleet startup,
        autoscaler scale-up, or a respawn after a kill)."""
        with self._lock:
            self.replicas_spawned += 1
            rep = self._rep(replica_id)
            if rep is not None and pid is not None:
                rep.pid = int(pid)

    def replica_retired(self, replica_id: Optional[int] = None,
                        pid: Optional[int] = None) -> None:
        """A replica worker process was drained and stopped
        (autoscaler scale-down)."""
        with self._lock:
            self.replicas_retired += 1

    def stream_started(self) -> None:
        with self._lock:
            self.streams_active += 1

    def stream_ended(self) -> None:
        with self._lock:
            self.streams_active = max(0, self.streams_active - 1)

    def _rep(self, replica_id: Optional[int]) -> Optional[_ReplicaAgg]:
        if replica_id is None:
            return None
        return self._replicas.setdefault(int(replica_id), _ReplicaAgg())

    def _cls(self, slo_class: Optional[str]) -> Optional[_ClassAgg]:
        if not slo_class:
            return None
        return self._classes.setdefault(str(slo_class), _ClassAgg())

    @staticmethod
    def _tenant_cells(req) -> List[Any]:
        """The two ISSUE-17 columns for a request-row write — blank on
        pre-tenant Request objects (duck-typed: metrics stays
        import-decoupled from the scheduler)."""
        return [str(getattr(req, "tenant", "") or ""),
                str(getattr(req, "slo_class", "") or "")]

    @staticmethod
    def _rid_cell(replica_id: Optional[int]):
        return "" if replica_id is None else int(replica_id)

    @staticmethod
    def _pid_cell(pid: Optional[int]):
        return "" if pid is None else int(pid)

    @staticmethod
    def _program_cells() -> List[Any]:
        """The device-program registry's cumulative build/compile
        counters, as engine-row CSV cells (the serve.csv face of
        ``programs.compile_counter()``)."""
        c = _program_counters()
        if c is None:
            return ["", "", ""]
        return [c["builds"], c["xla_compiles"],
                f"{c['compile_seconds']:.3f}"]

    def request_done(self, req, queue_depth: int, active_slots: int,
                     replica_id: Optional[int] = None,
                     pid: Optional[int] = None) -> None:
        with self._lock:
            if self._f.closed:        # straggler after close(): drop it
                return
            failed = req.error is not None
            status = "done"
            if failed:
                status = _STATUS_BY_EXC.get(
                    type(req.exception).__name__, "failed")
            # a disconnect is the CLIENT's decision: its own counter,
            # never inflating requests_failed (the server did nothing
            # wrong — ci alerts stay meaningful under churny clients)
            disconnected = status == "disconnected"
            self.requests_failed += int(failed and not disconnected)
            self.requests_done += int(not failed)
            self.requests_shed += int(status == "shed")
            self.requests_quarantined += int(status == "quarantined")
            self.requests_disconnected += int(disconnected)
            self.tokens_out += len(req.tokens)
            rep = self._rep(replica_id)
            if rep is not None:
                rep.failed += int(failed and not disconnected)
                rep.done += int(not failed)
                rep.shed += int(status == "shed")
                rep.quarantined += int(status == "quarantined")
                rep.disconnected += int(disconnected)
                rep.tokens_out += len(req.tokens)
            ttft = req.ttft_s
            lat = req.avg_token_latency_s
            if ttft is not None:
                self._ttft_sum += ttft
                self._ttft_n += 1
                self._ttfts.append(ttft)
            if lat is not None:
                self._lat_sum += lat
                self._lat_n += 1
                self._lats.append(lat)
            tenant_cells = self._tenant_cells(req)
            agg = self._cls(tenant_cells[1])
            if agg is not None:
                agg.done += int(not failed)
                agg.shed += int(status == "shed")
                if ttft is not None:
                    agg.ttfts.append(ttft)
            # submit offset in the collector's clock: the arrival
            # process, reconstructible from disk (ISSUE 15)
            t_sub = getattr(req, "submit_t", None)
            t_sub_cell = ("" if not t_sub
                          else f"{t_sub - self._t0:.4f}")
            self._w.writerow([
                f"{self._now():.4f}", "request", req.id, status,
                queue_depth, active_slots,
                int(req.prompt.size), len(req.tokens),
                "" if ttft is None else f"{ttft:.5f}",
                "" if lat is None else f"{lat:.5f}",
                self.tokens_out, f"{self.tokens_per_s():.2f}",
                "", "", "", self._rid_cell(replica_id), "", "", "",
                "", "", self._pid_cell(pid),
                t_sub_cell, "", "", "", "", *tenant_cells,
            ])
            self._f.flush()

    def request_rejected(self, queue_depth: int, active_slots: int,
                         replica_id: Optional[int] = None,
                         pid: Optional[int] = None,
                         tenant: Optional[str] = None,
                         slo_class: Optional[str] = None) -> None:
        """Admission control shed a request before it was enqueued (no
        Request object ever existed — the whole point). ``tenant`` /
        ``slo_class`` type WHO was turned away (quota rejects are the
        per-class observable; blank on pre-tenant callers)."""
        with self._lock:
            if self._f.closed:
                return
            self.requests_rejected += 1
            rep = self._rep(replica_id)
            if rep is not None:
                rep.rejected += 1
            agg = self._cls(slo_class)
            if agg is not None:
                agg.rejected += 1
            now = self._now()
            self._w.writerow([
                f"{now:.4f}", "request", "", "rejected",
                queue_depth, active_slots, "", "", "", "",
                self.tokens_out, f"{self.tokens_per_s():.2f}",
                "", "", "", self._rid_cell(replica_id), "", "", "",
                "", "", self._pid_cell(pid),
                # an admission reject happens AT submit: arrival == now
                f"{now:.4f}", "", "", "", "",
                str(tenant or ""), str(slo_class or ""),
            ])
            self._f.flush()

    def _request_event(self, req, status: str, queue_depth: int,
                       active_slots: int, replica_id: Optional[int],
                       pid: Optional[int]) -> None:
        """A lifecycle EVENT row on a still-live request (ISSUE 17:
        ``preempted`` / ``resumed``). new_tokens stays blank — the
        request's tokens are counted once, on its completion row."""
        tenant_cells = self._tenant_cells(req)
        self._w.writerow([
            f"{self._now():.4f}", "request", req.id, status,
            queue_depth, active_slots, int(req.prompt.size), "",
            "", "", self.tokens_out, f"{self.tokens_per_s():.2f}",
            "", "", "", self._rid_cell(replica_id), "", "", "",
            "", "", self._pid_cell(pid), "", "", "", "", "",
            *tenant_cells,
        ])
        self._f.flush()

    def request_preempted(self, req, queue_depth: int,
                          active_slots: int,
                          replica_id: Optional[int] = None,
                          pid: Optional[int] = None) -> None:
        """A running low-priority request was parked at a chunk boundary
        to free its slot for more urgent work (ISSUE 17). The request is
        still live: its stream pauses and later resumes byte-identical,
        so this is an event counter, never a failure."""
        with self._lock:
            if self._f.closed:
                return
            self.requests_preempted += 1
            agg = self._cls(getattr(req, "slo_class", None))
            if agg is not None:
                agg.preempted += 1
            self._request_event(req, "preempted", queue_depth,
                                active_slots, replica_id, pid)

    def request_resumed(self, req, queue_depth: int, active_slots: int,
                        replica_id: Optional[int] = None,
                        pid: Optional[int] = None) -> None:
        """A parked (preempted) request got a slot back and its stream
        continues from the parked cursor (ISSUE 17)."""
        with self._lock:
            if self._f.closed:
                return
            self.requests_resumed += 1
            agg = self._cls(getattr(req, "slo_class", None))
            if agg is not None:
                agg.resumed += 1
            self._request_event(req, "resumed", queue_depth,
                                active_slots, replica_id, pid)

    def engine_restarted(self, replica_id: Optional[int] = None,
                         pid: Optional[int] = None) -> None:
        """A supervisor failover rebuilt the engine."""
        with self._lock:
            if self._f.closed:
                return
            self.engine_restarts += 1
            rep = self._rep(replica_id)
            if rep is not None:
                rep.restarts += 1
            self._w.writerow([
                f"{self._now():.4f}", "engine", "", "restart", "", "",
                "", "", "", "", self.tokens_out,
                f"{self.tokens_per_s():.2f}", "", "", "",
                self._rid_cell(replica_id), *self._program_cells(),
                self._weights_dtype or "", self._kv_dtype or "",
                self._pid_cell(pid), "", "", "", "", "", "", "",
            ])
            self._f.flush()

    def engine_reloaded(self, replica_id: Optional[int] = None,
                        pid: Optional[int] = None) -> None:
        """A rolling weight hot-swap replaced this engine's params (the
        router drained the replica first — no restart, no failures)."""
        with self._lock:
            if self._f.closed:
                return
            self.engine_reloads += 1
            rep = self._rep(replica_id)
            if rep is not None:
                rep.reloads += 1
            self._w.writerow([
                f"{self._now():.4f}", "engine", "", "reload", "", "",
                "", "", "", "", self.tokens_out,
                f"{self.tokens_per_s():.2f}", "", "", "",
                self._rid_cell(replica_id), *self._program_cells(),
                self._weights_dtype or "", self._kv_dtype or "",
                self._pid_cell(pid), "", "", "", "", "", "", "",
            ])
            self._f.flush()

    def autoscale_tick(self, healthy: int, starting: int,
                       backlog_tokens: float,
                       tokens_per_s: Optional[float], decision: int,
                       reason: str) -> None:
        """Autoscaler audit trail (ISSUE 15): one ``kind=autoscale`` row
        per controller tick — the exact snapshot the decision priced
        plus the decision and its reason. ``status`` types the decision
        (``up``/``down``/``hold``); the snapshot's aggregate rate rides
        the ``tokens_per_s`` column. Sim-vs-live validation replays
        these against the cost model's modeled ticks; postmortems stop
        reverse-engineering decisions from replica counts."""
        with self._lock:
            if self._f.closed:
                return
            self.autoscale_ticks += 1
            self.autoscale_ups += int(decision > 0)
            self.autoscale_downs += int(decision < 0)
            status = ("up" if decision > 0
                      else "down" if decision < 0 else "hold")
            self._w.writerow([
                f"{self._now():.4f}", "autoscale", "", status, "", "",
                "", "", "", "", self.tokens_out,
                ("" if tokens_per_s is None
                 else f"{tokens_per_s:.2f}"),
                "", "", "", "", "", "", "", "", "", "",
                "", int(healthy), int(starting),
                f"{float(backlog_tokens):.1f}", str(reason), "", "",
            ])
            self._f.flush()

    def engine_tick(self, stats, queue_depth: int,
                    replica_id: Optional[int] = None,
                    pid: Optional[int] = None) -> None:
        """Per-driver-round sample. ALWAYS updates the tokens/s EWMA
        (admission control reads it live); writes a CSV row only every
        ``engine_log_every``-th call so an idle server doesn't grow the
        CSV unboundedly."""
        with self._lock:
            if self._f.closed:
                # a straggler driver thread may tick after close() — the
                # sample is worthless, the crash would not be
                return
            now = self._now()
            tok = int(stats.tokens_generated)
            rep = self._rep(replica_id)
            rate = self._rate if rep is None else rep.rate
            rate.update(tok, now, int(stats.active_slots), queue_depth,
                        self._ewma_idle_reset_s)
            kv = int(getattr(stats, "kv_blocks_in_use", 0))
            ph = int(getattr(stats, "prefix_hit_blocks", 0))
            rate_fn = getattr(stats, "spec_accept_rate", None)
            sr = rate_fn() if callable(rate_fn) else None
            wd = getattr(stats, "weights_dtype", None)
            kd = getattr(stats, "kv_dtype", None)
            if wd:
                self._weights_dtype = str(wd)
            if kd:
                self._kv_dtype = str(kd)
            if rep is None:
                self._kv_blocks_in_use = kv
                self._prefix_hit_blocks = ph
                self._spec_accept_rate = sr
            else:
                rep.kv_blocks_in_use = kv
                rep.prefix_hit_blocks = ph
                rep.spec_accept_rate = sr
            self._ticks += 1
            if self._ticks % self._every:
                return
            self._w.writerow([
                f"{now:.4f}", "engine", "", "", queue_depth,
                stats.active_slots, "", "", "", "",
                stats.tokens_generated, f"{self.tokens_per_s():.2f}",
                kv, ph, ("" if sr is None else f"{sr:.4f}"),
                self._rid_cell(replica_id), *self._program_cells(),
                self._weights_dtype or "", self._kv_dtype or "",
                self._pid_cell(pid), "", "", "", "", "", "", "",
            ])

    def tokens_per_s(self) -> float:
        dt = self._now()
        return self.tokens_out / dt if dt > 0 else 0.0

    def tokens_per_s_ewma(self, replica_id: Optional[int] = None
                          ) -> Optional[float]:
        """Live service-rate estimate (None until the first productive
        tick) — the admission-control input. ``replica_id`` scopes the
        read to one fleet member; without it, a fleet collector reports
        the AGGREGATE rate (sum of live per-replica EWMAs) and a
        single-engine collector its own."""
        with self._lock:
            if replica_id is not None:
                rep = self._replicas.get(int(replica_id))
                return rep.rate.ewma if rep is not None else None
            if self._replicas:
                live = [r.rate.ewma for r in self._replicas.values()
                        if r.rate.ewma is not None]
                return sum(live) if live else None
            return self._rate.ewma

    def headline(self) -> Dict[str, Any]:
        with self._lock:
            if self._replicas:
                # fleet aggregates: per-replica samples summed; rates
                # summed over live EWMAs; spec rate averaged over
                # replicas that have one
                ewmas = [r.rate.ewma for r in self._replicas.values()
                         if r.rate.ewma is not None]
                ewma = sum(ewmas) if ewmas else None
                kv = sum(r.kv_blocks_in_use
                         for r in self._replicas.values())
                ph = sum(r.prefix_hit_blocks
                         for r in self._replicas.values())
                srs = [r.spec_accept_rate
                       for r in self._replicas.values()
                       if r.spec_accept_rate is not None]
                sr = sum(srs) / len(srs) if srs else None
            else:
                ewma = self._rate.ewma
                kv = self._kv_blocks_in_use
                ph = self._prefix_hit_blocks
                sr = self._spec_accept_rate
            head = {
                "requests_done": self.requests_done,
                "requests_failed": self.requests_failed,
                "requests_shed": self.requests_shed,
                "requests_quarantined": self.requests_quarantined,
                "requests_rejected": self.requests_rejected,
                "requests_disconnected": self.requests_disconnected,
                "requests_preempted": self.requests_preempted,
                "requests_resumed": self.requests_resumed,
                "engine_restarts": self.engine_restarts,
                "engine_reloads": self.engine_reloads,
                "replicas_spawned": self.replicas_spawned,
                "replicas_retired": self.replicas_retired,
                "streams_active": self.streams_active,
                "tokens_out": self.tokens_out,
                "wall_s": round(self._now(), 3),
                "tokens_per_s": round(self.tokens_per_s(), 2),
                "tokens_per_s_ewma": (round(ewma, 2)
                                      if ewma is not None else None),
                "mean_ttft_s": (round(self._ttft_sum / self._ttft_n, 5)
                                if self._ttft_n else None),
                "mean_token_latency_s": (
                    round(self._lat_sum / self._lat_n, 5)
                    if self._lat_n else None),
                "kv_blocks_in_use": kv,
                "prefix_hit_blocks": ph,
                "spec_accept_rate": (
                    round(sr, 4) if sr is not None else None),
                "weights_dtype": self._weights_dtype,
                "kv_dtype": self._kv_dtype,
            }
            if self.autoscale_ticks:
                head["autoscale"] = {
                    "ticks": self.autoscale_ticks,
                    "ups": self.autoscale_ups,
                    "downs": self.autoscale_downs,
                }
            progs = _program_counters()
            if progs is not None:
                # the device-program registry's live counters (hits /
                # builds / xla_compiles / disk_hits / compile_seconds +
                # persistent-cache event totals) — /stats spreads the
                # headline, so this is the wire observable the restart
                # drill and the zero-recompile seams read
                head["programs"] = progs
            if self._replicas:
                head["replicas"] = {
                    str(rid): rep.headline()
                    for rid, rep in sorted(self._replicas.items())}
            if self._classes:
                # per-SLO-class tails + shed/preempt counters (ISSUE
                # 17): the isolation observable — a noisy neighbor
                # shows up as ITS class's rejects/preempts while the
                # victim class's ttft_p99_s stays put
                head["classes"] = {
                    cls: agg.headline()
                    for cls, agg in sorted(self._classes.items())}
            head.update(_percentiles(self._ttfts, "ttft"))
            head.update(_percentiles(self._lats, "token_lat"))
            return head

    def sync(self) -> None:
        # fsync OUTSIDE the lock (lint GT102, the ISSUE-6 concurrency
        # audit's one genuine finding): this lock serializes the HTTP
        # handlers' admission-control reads (tokens_per_s_ewma) and the
        # driver's request_done — holding it across a disk-durability
        # call let one NFS stall wedge the whole serving plane. flush
        # stays inside (the csv writer's buffer is lock-protected);
        # fsync of an fd is safe concurrent with further writes, it may
        # only persist MORE than this call's rows.
        with self._lock:
            if self._f.closed:
                return    # straggler sync after close: drop, like the
                #           row writers' closed-file guards
            self._f.flush()
            # dup the fd under the lock: a concurrent close() cannot
            # invalidate (or let the OS reuse) OUR descriptor mid-fsync
            fd = os.dup(self._f.fileno())
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            self._f.close()


def read_headline(path: str) -> Dict[str, Any]:
    """Recompute the aggregate headline from a ``serve.csv`` on disk —
    the same counters and percentiles ``ServeMetrics.headline`` reports
    live, derived post-hoc from the request rows (so a finished run, a
    synthetic fixture, or another process's CSV all aggregate the same
    way). Engine rows contribute ``engine_restarts`` and
    ``engine_reloads``. Fleet CSVs (rows carrying the ``replica_id``
    column) additionally aggregate a per-replica ``replicas`` section;
    pre-fleet CSVs (no such column, like pre-paging CSVs lack the KV
    columns) produce the same fleet-free headline they always did."""
    counts = {"done": 0, "failed": 0, "shed": 0, "quarantined": 0,
              "rejected": 0, "disconnected": 0,
              # ISSUE 17 event rows (absent in pre-tenant CSVs)
              "preempted": 0, "resumed": 0}
    per_cls: Dict[str, Dict[str, Any]] = {}

    def cls_of(row):
        slo = row.get("slo_class")
        if not slo:
            return None
        return per_cls.setdefault(str(slo), {
            "requests_done": 0, "requests_shed": 0,
            "requests_rejected": 0, "preemptions": 0, "resumes": 0,
            "_ttfts": []})
    restarts = reloads = 0
    tokens_out = 0
    last_ts = 0.0
    ttfts: List[float] = []
    lats: List[float] = []
    kv_blocks, prefix_hits, spec_rate = 0, 0, None
    weights_dtype: Optional[str] = None
    kv_dtype: Optional[str] = None
    programs: Optional[Dict[str, Any]] = None
    per_rep: Dict[str, Dict[str, int]] = {}
    as_ticks = as_ups = as_downs = 0

    def rep_of(row):
        rid = row.get("replica_id")
        if rid is None or rid == "":
            return None
        return per_rep.setdefault(str(int(rid)), {
            "requests_done": 0, "requests_failed": 0,
            "engine_restarts": 0, "engine_reloads": 0, "tokens_out": 0})

    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            last_ts = max(last_ts, float(row["ts_s"] or 0.0))
            if row["kind"] == "engine":
                restarts += int(row["status"] == "restart")
                reloads += int(row["status"] == "reload")
                rep = rep_of(row)
                if rep is not None:
                    rep["engine_restarts"] += int(
                        row["status"] == "restart")
                    rep["engine_reloads"] += int(
                        row["status"] == "reload")
                # paged/spec observables: last engine sample wins (the
                # columns are absent in pre-paging CSVs)
                if row.get("kv_blocks_in_use"):
                    kv_blocks = int(row["kv_blocks_in_use"])
                if row.get("prefix_hit_blocks"):
                    prefix_hits = int(row["prefix_hit_blocks"])
                if row.get("spec_accept_rate"):
                    spec_rate = float(row["spec_accept_rate"])
                # quantized-serving config echo: last engine sample wins
                # (columns absent in pre-quantization CSVs)
                if row.get("weights_dtype"):
                    weights_dtype = row["weights_dtype"]
                if row.get("kv_dtype"):
                    kv_dtype = row["kv_dtype"]
                # registry counters: last engine sample wins (columns
                # absent in pre-registry CSVs)
                if row.get("programs_built"):
                    programs = {
                        "builds": int(row["programs_built"]),
                        "xla_compiles": int(row["programs_compiled"]),
                        "compile_seconds": float(
                            row["program_compile_s"] or 0.0),
                    }
                continue
            if row["kind"] == "autoscale":
                # autoscaler audit rows (ISSUE 15; absent in
                # pre-servesim CSVs — this branch simply never fires)
                as_ticks += 1
                as_ups += int(row["status"] == "up")
                as_downs += int(row["status"] == "down")
                continue
            if row["kind"] != "request":
                continue
            status = row["status"]
            if status in counts:
                counts[status] += 1
            tokens_out += int(row["new_tokens"] or 0)
            rep = rep_of(row)
            if rep is not None:
                rep["requests_done"] += int(status == "done")
                rep["requests_failed"] += int(
                    status in ("failed", "shed", "quarantined"))
                rep["tokens_out"] += int(row["new_tokens"] or 0)
            cls = cls_of(row)
            if cls is not None:
                cls["requests_done"] += int(status == "done")
                cls["requests_shed"] += int(status == "shed")
                cls["requests_rejected"] += int(status == "rejected")
                cls["preemptions"] += int(status == "preempted")
                cls["resumes"] += int(status == "resumed")
                if status not in ("preempted", "resumed") \
                        and row["ttft_s"]:
                    cls["_ttfts"].append(float(row["ttft_s"]))
            if status in ("preempted", "resumed"):
                continue       # event rows: no latency samples
            if row["ttft_s"]:
                ttfts.append(float(row["ttft_s"]))
            if row["avg_token_latency_s"]:
                lats.append(float(row["avg_token_latency_s"]))
    failed = (counts["failed"] + counts["shed"] + counts["quarantined"])
    head: Dict[str, Any] = {
        "requests_done": counts["done"],
        "requests_failed": failed,
        "requests_shed": counts["shed"],
        "requests_quarantined": counts["quarantined"],
        "requests_rejected": counts["rejected"],
        "requests_disconnected": counts["disconnected"],
        "requests_preempted": counts["preempted"],
        "requests_resumed": counts["resumed"],
        "engine_restarts": restarts,
        "engine_reloads": reloads,
        "tokens_out": tokens_out,
        "wall_s": round(last_ts, 3),
        "tokens_per_s": round(tokens_out / last_ts, 2) if last_ts else 0.0,
        "mean_ttft_s": (round(sum(ttfts) / len(ttfts), 5)
                        if ttfts else None),
        "mean_token_latency_s": (round(sum(lats) / len(lats), 5)
                                 if lats else None),
        "kv_blocks_in_use": kv_blocks,
        "prefix_hit_blocks": prefix_hits,
        "spec_accept_rate": spec_rate,
        "weights_dtype": weights_dtype,
        "kv_dtype": kv_dtype,
    }
    if programs is not None:
        head["programs"] = programs
    if as_ticks:
        head["autoscale"] = {"ticks": as_ticks, "ups": as_ups,
                             "downs": as_downs}
    if per_rep:
        head["replicas"] = dict(sorted(per_rep.items()))
    if per_cls:
        classes: Dict[str, Any] = {}
        for slo, agg in sorted(per_cls.items()):
            samples = agg.pop("_ttfts")
            agg.update(_percentiles(samples, "ttft"))
            classes[slo] = agg
        head["classes"] = classes
    head.update(_percentiles(ttfts, "ttft"))
    head.update(_percentiles(lats, "token_lat"))
    return head
