"""Serving observability: ``serve.csv`` + aggregate headline.

CSVLogger-style (``utils/logger.py``): one append-only CSV under the
serve log dir, fsync on ``sync()``, atomic enough for a tail -f. Two row
kinds share the header:

- ``request`` — one row per completed/failed request: TTFT, new-token
  count, mean per-token latency, and the queue/slot state at completion.
  The ``status`` column types the outcome: ``done``, ``failed``,
  ``shed`` (deadline elapsed — queued shed or running cancelled),
  ``quarantined`` (NaN/Inf logits in the slot), ``rejected`` (admission
  control turned it away before it was ever enqueued).
- ``engine``  — a periodic engine sample (every ``engine_log_every``
  ticks of the driver loop): cumulative tokens, rolling tokens/s, queue
  depth, active-slot occupancy, plus the paged-KV/speculative
  observables ``kv_blocks_in_use`` / ``prefix_hit_blocks`` /
  ``spec_accept_rate`` (blank-or-zero on unpaged engines and absent in
  pre-paging CSVs). ``status=restart`` marks a supervisor engine
  rebuild.

Beyond the counters, the collector maintains a tokens/s EWMA over driver
ticks — the live service-rate estimate ``Scheduler.submit`` uses for
admission control — and p50/p95/p99 percentiles of TTFT and per-token
latency (tail latency is the serving observable; a mean hides a wedged
tail completely).

``headline()`` aggregates the run into the one-line JSON surface
``bench.py --serve-only`` / ``--chaos-only`` and the HTTP ``/stats``
endpoint report; ``read_headline(path)`` recomputes the same aggregate
from a ``serve.csv`` on disk (post-hoc analysis, tests on synthetic
files).
"""

from __future__ import annotations

import csv
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

HEADER = [
    "ts_s", "kind", "request_id", "status", "queue_depth", "active_slots",
    "prompt_tokens", "new_tokens", "ttft_s", "avg_token_latency_s",
    "cum_tokens", "tokens_per_s",
    # paged-KV / speculative observables (engine rows; blank on request
    # rows and absent in pre-paging CSVs — read_headline tolerates both)
    "kv_blocks_in_use", "prefix_hit_blocks", "spec_accept_rate",
]

#: EWMA smoothing for the live tokens/s estimate (per driver tick with
#: token progress). 0.2 ≈ a ~5-tick memory: reactive enough to track a
#: fault-induced slowdown, smooth enough not to flap admission control.
EWMA_ALPHA = 0.2

#: A fully idle engine (no active slots, empty queue, no token flow) for
#: this long resets the EWMA to None — cold again, admission turns
#: optimistic. Without this, a transient-slowdown rate measured before an
#: idle period would keep rejecting deadline'd requests forever: rejected
#: requests generate no tokens, so a stale-low EWMA could never refresh.
EWMA_IDLE_RESET_S = 10.0

#: Tail-latency sample window. Serving runs are unbounded; percentiles
#: over the last N requests keep memory flat and the numbers current.
PERCENTILE_WINDOW = 10_000

_PCTS = (50, 95, 99)


def _percentiles(samples, prefix: str) -> Dict[str, Optional[float]]:
    out: Dict[str, Optional[float]] = {}
    arr = np.asarray([s for s in samples if s is not None], np.float64)
    for p in _PCTS:
        out[f"{prefix}_p{p}_s"] = (
            round(float(np.percentile(arr, p)), 5) if arr.size else None)
    return out


#: request-failure exception class → serve.csv status value. Typed by
#: NAME so metrics stays import-decoupled from the scheduler.
_STATUS_BY_EXC = {
    "DeadlineExceededError": "shed",
    "SlotQuarantinedError": "quarantined",
}


class ServeMetrics:
    def __init__(self, out_dir: str, engine_log_every: int = 50,
                 ewma_idle_reset_s: float = EWMA_IDLE_RESET_S):
        os.makedirs(out_dir, exist_ok=True)
        self.path = os.path.join(out_dir, "serve.csv")
        # append, not "w": a server restart over the same run dir must
        # not destroy the previous run's request history — the header is
        # written only when the file is new/empty
        new_file = (not os.path.exists(self.path)
                    or os.path.getsize(self.path) == 0)
        self._f = open(self.path, "a", newline="")
        self._w = csv.writer(self._f)
        if new_file:
            self._w.writerow(HEADER)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._every = max(1, int(engine_log_every))
        self._ticks = 0
        self.requests_done = 0
        self.requests_failed = 0
        self.requests_shed = 0
        self.requests_quarantined = 0
        self.requests_rejected = 0
        self.engine_restarts = 0
        self.tokens_out = 0
        self._ttft_sum = 0.0
        self._ttft_n = 0
        self._lat_sum = 0.0
        self._lat_n = 0
        self._ttfts: deque = deque(maxlen=PERCENTILE_WINDOW)
        self._lats: deque = deque(maxlen=PERCENTILE_WINDOW)
        self._ewma: Optional[float] = None
        self._ewma_last_tok = 0
        self._ewma_last_t: Optional[float] = None
        self._ewma_idle_reset_s = float(ewma_idle_reset_s)
        self._idle_since: Optional[float] = None
        # last engine sample of the paged/speculative observables (an
        # unpaged engine reports 0 blocks and a None accept rate)
        self._kv_blocks_in_use = 0
        self._prefix_hit_blocks = 0
        self._spec_accept_rate: Optional[float] = None

    def _now(self) -> float:
        return time.perf_counter() - self._t0

    def request_done(self, req, queue_depth: int,
                     active_slots: int) -> None:
        with self._lock:
            if self._f.closed:        # straggler after close(): drop it
                return
            failed = req.error is not None
            status = "done"
            if failed:
                status = _STATUS_BY_EXC.get(
                    type(req.exception).__name__, "failed")
            self.requests_failed += int(failed)
            self.requests_done += int(not failed)
            self.requests_shed += int(status == "shed")
            self.requests_quarantined += int(status == "quarantined")
            self.tokens_out += len(req.tokens)
            ttft = req.ttft_s
            lat = req.avg_token_latency_s
            if ttft is not None:
                self._ttft_sum += ttft
                self._ttft_n += 1
                self._ttfts.append(ttft)
            if lat is not None:
                self._lat_sum += lat
                self._lat_n += 1
                self._lats.append(lat)
            self._w.writerow([
                f"{self._now():.4f}", "request", req.id, status,
                queue_depth, active_slots,
                int(req.prompt.size), len(req.tokens),
                "" if ttft is None else f"{ttft:.5f}",
                "" if lat is None else f"{lat:.5f}",
                self.tokens_out, f"{self.tokens_per_s():.2f}",
                "", "", "",
            ])
            self._f.flush()

    def request_rejected(self, queue_depth: int,
                         active_slots: int) -> None:
        """Admission control shed a request before it was enqueued (no
        Request object ever existed — the whole point)."""
        with self._lock:
            if self._f.closed:
                return
            self.requests_rejected += 1
            self._w.writerow([
                f"{self._now():.4f}", "request", "", "rejected",
                queue_depth, active_slots, "", "", "", "",
                self.tokens_out, f"{self.tokens_per_s():.2f}",
                "", "", "",
            ])
            self._f.flush()

    def engine_restarted(self) -> None:
        """A supervisor failover rebuilt the engine."""
        with self._lock:
            if self._f.closed:
                return
            self.engine_restarts += 1
            self._w.writerow([
                f"{self._now():.4f}", "engine", "", "restart", "", "",
                "", "", "", "", self.tokens_out,
                f"{self.tokens_per_s():.2f}", "", "", "",
            ])
            self._f.flush()

    def engine_tick(self, stats, queue_depth: int) -> None:
        """Per-driver-round sample. ALWAYS updates the tokens/s EWMA
        (admission control reads it live); writes a CSV row only every
        ``engine_log_every``-th call so an idle server doesn't grow the
        CSV unboundedly."""
        with self._lock:
            if self._f.closed:
                # a straggler driver thread may tick after close() — the
                # sample is worthless, the crash would not be
                return
            now = self._now()
            tok = int(stats.tokens_generated)
            if self._ewma_last_t is not None:
                d_tok = tok - self._ewma_last_tok
                d_t = now - self._ewma_last_t
                # d_tok < 0 = the engine was rebuilt (counter reset):
                # re-anchor, keep the old EWMA — the rate estimate
                # survives a supervisor failover
                if d_tok > 0 and d_t > 0:
                    inst = d_tok / d_t
                    self._ewma = (inst if self._ewma is None else
                                  EWMA_ALPHA * inst
                                  + (1.0 - EWMA_ALPHA) * self._ewma)
                    self._idle_since = None
                elif int(stats.active_slots) == 0 and queue_depth == 0:
                    # fully idle: after a while the old rate says nothing
                    # about the next request — go cold (optimistic admit)
                    # rather than reject on a stale-low estimate. A
                    # BUSY-but-stalled engine keeps its honest low rate.
                    if self._idle_since is None:
                        self._idle_since = now
                    elif (now - self._idle_since >= self._ewma_idle_reset_s
                          and self._ewma is not None):
                        self._ewma = None
                else:
                    self._idle_since = None
            self._ewma_last_tok, self._ewma_last_t = tok, now
            self._kv_blocks_in_use = int(
                getattr(stats, "kv_blocks_in_use", 0))
            self._prefix_hit_blocks = int(
                getattr(stats, "prefix_hit_blocks", 0))
            rate_fn = getattr(stats, "spec_accept_rate", None)
            self._spec_accept_rate = rate_fn() if callable(rate_fn) \
                else None
            self._ticks += 1
            if self._ticks % self._every:
                return
            self._w.writerow([
                f"{now:.4f}", "engine", "", "", queue_depth,
                stats.active_slots, "", "", "", "",
                stats.tokens_generated, f"{self.tokens_per_s():.2f}",
                self._kv_blocks_in_use, self._prefix_hit_blocks,
                ("" if self._spec_accept_rate is None
                 else f"{self._spec_accept_rate:.4f}"),
            ])

    def tokens_per_s(self) -> float:
        dt = self._now()
        return self.tokens_out / dt if dt > 0 else 0.0

    def tokens_per_s_ewma(self) -> Optional[float]:
        """Live service-rate estimate (None until the first productive
        tick) — the admission-control input."""
        with self._lock:
            return self._ewma

    def headline(self) -> Dict[str, Any]:
        with self._lock:
            head = {
                "requests_done": self.requests_done,
                "requests_failed": self.requests_failed,
                "requests_shed": self.requests_shed,
                "requests_quarantined": self.requests_quarantined,
                "requests_rejected": self.requests_rejected,
                "engine_restarts": self.engine_restarts,
                "tokens_out": self.tokens_out,
                "wall_s": round(self._now(), 3),
                "tokens_per_s": round(self.tokens_per_s(), 2),
                "tokens_per_s_ewma": (round(self._ewma, 2)
                                      if self._ewma is not None else None),
                "mean_ttft_s": (round(self._ttft_sum / self._ttft_n, 5)
                                if self._ttft_n else None),
                "mean_token_latency_s": (
                    round(self._lat_sum / self._lat_n, 5)
                    if self._lat_n else None),
                "kv_blocks_in_use": self._kv_blocks_in_use,
                "prefix_hit_blocks": self._prefix_hit_blocks,
                "spec_accept_rate": (
                    round(self._spec_accept_rate, 4)
                    if self._spec_accept_rate is not None else None),
            }
            head.update(_percentiles(self._ttfts, "ttft"))
            head.update(_percentiles(self._lats, "token_lat"))
            return head

    def sync(self) -> None:
        # fsync OUTSIDE the lock (lint GT102, the ISSUE-6 concurrency
        # audit's one genuine finding): this lock serializes the HTTP
        # handlers' admission-control reads (tokens_per_s_ewma) and the
        # driver's request_done — holding it across a disk-durability
        # call let one NFS stall wedge the whole serving plane. flush
        # stays inside (the csv writer's buffer is lock-protected);
        # fsync of an fd is safe concurrent with further writes, it may
        # only persist MORE than this call's rows.
        with self._lock:
            if self._f.closed:
                return    # straggler sync after close: drop, like the
                #           row writers' closed-file guards
            self._f.flush()
            # dup the fd under the lock: a concurrent close() cannot
            # invalidate (or let the OS reuse) OUR descriptor mid-fsync
            fd = os.dup(self._f.fileno())
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            self._f.close()


def read_headline(path: str) -> Dict[str, Any]:
    """Recompute the aggregate headline from a ``serve.csv`` on disk —
    the same counters and percentiles ``ServeMetrics.headline`` reports
    live, derived post-hoc from the request rows (so a finished run, a
    synthetic fixture, or another process's CSV all aggregate the same
    way). Engine rows contribute only ``engine_restarts``."""
    counts = {"done": 0, "failed": 0, "shed": 0, "quarantined": 0,
              "rejected": 0}
    restarts = 0
    tokens_out = 0
    last_ts = 0.0
    ttfts: List[float] = []
    lats: List[float] = []
    kv_blocks, prefix_hits, spec_rate = 0, 0, None
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            last_ts = max(last_ts, float(row["ts_s"] or 0.0))
            if row["kind"] == "engine":
                restarts += int(row["status"] == "restart")
                # paged/spec observables: last engine sample wins (the
                # columns are absent in pre-paging CSVs)
                if row.get("kv_blocks_in_use"):
                    kv_blocks = int(row["kv_blocks_in_use"])
                if row.get("prefix_hit_blocks"):
                    prefix_hits = int(row["prefix_hit_blocks"])
                if row.get("spec_accept_rate"):
                    spec_rate = float(row["spec_accept_rate"])
                continue
            if row["kind"] != "request":
                continue
            status = row["status"]
            if status in counts:
                counts[status] += 1
            tokens_out += int(row["new_tokens"] or 0)
            if row["ttft_s"]:
                ttfts.append(float(row["ttft_s"]))
            if row["avg_token_latency_s"]:
                lats.append(float(row["avg_token_latency_s"]))
    failed = (counts["failed"] + counts["shed"] + counts["quarantined"])
    head: Dict[str, Any] = {
        "requests_done": counts["done"],
        "requests_failed": failed,
        "requests_shed": counts["shed"],
        "requests_quarantined": counts["quarantined"],
        "requests_rejected": counts["rejected"],
        "engine_restarts": restarts,
        "tokens_out": tokens_out,
        "wall_s": round(last_ts, 3),
        "tokens_per_s": round(tokens_out / last_ts, 2) if last_ts else 0.0,
        "mean_ttft_s": (round(sum(ttfts) / len(ttfts), 5)
                        if ttfts else None),
        "mean_token_latency_s": (round(sum(lats) / len(lats), 5)
                                 if lats else None),
        "kv_blocks_in_use": kv_blocks,
        "prefix_hit_blocks": prefix_hits,
        "spec_accept_rate": spec_rate,
    }
    head.update(_percentiles(ttfts, "ttft"))
    head.update(_percentiles(lats, "token_lat"))
    return head
