"""Continuous-batching inference engine: one jitted decode step, N slots.

The design inverts ``generate_fast``'s: instead of one compiled program
per request signature (prompt length × new tokens × sampling config —
every new shape recompiles), the engine compiles a FIXED-SHAPE program
set once and runs every request through it:

- **Decode step** (compiled once per ``(config, num_slots)``): the whole
  slot batch advances one token. Each slot is an independent sequence at
  its own cache position — the model's per-row cursors/masks
  (``models/nanogpt.py:_decode_attend``) keep rows isolated — and the
  per-slot sampling params (temperature / top-k / top-p / PRNG key) ride
  in as vectors, applied by a vmapped ``sample_logits``. Inactive slots
  compute garbage that is never read and their integer cursors are
  frozen, so a free slot can idle forever without overflowing.
- **Prefill** (compiled once per power-of-two bucket): a single request's
  prompt, right-padded to the bucket length, fills a fresh single-row
  cache and samples the first token at the TRUE last prompt position
  (padded positions are causally masked away from real queries and
  overwritten before any later query can attend to them). Total prefill
  compilations are bounded by ``⌈log2(block_size)⌉ + 1`` — the bucket
  count — instead of one per distinct prompt length.
- **Admit/evict** (compiled once): the prefilled row is scattered into
  the engine cache at the slot index and the slot's cursors rewound to
  the true prompt length. Admission and eviction happen BETWEEN decode
  steps (continuous batching): a finished slot frees mid-flight while
  its neighbors keep decoding — no drain-the-batch barrier.

Parity oracle (tests/test_serve.py): for a single request the engine's
token stream is IDENTICAL to ``generate_fast`` with the same sampling
config and seed — both use the shared ``sample_logits`` kernel and the
``fold_in(PRNGKey(seed), token_index)`` key schedule, and the per-row
cache math is the same program modulo batch width.

**Paged KV + prefix sharing** (``paged=True``; PagedAttention, arXiv
2309.06180): the cache becomes a POOL of fixed-size pages addressed
through a per-slot block table (``models/nanogpt.py:_decode_attend_paged``
— same static-[block_size] reductions and masks as the unpaged attend,
which is what keeps paged token streams bit-identical). A ref-counted
``BlockAllocator`` plus an exact-content prefix hash table admit a
prompt whose longest block-aligned prefix is already resident WITHOUT
re-prefilling or copying those blocks: prefill processes only the
suffix (one bucket-padded dispatch), and a fully-matched final block is
copy-on-written so its last token can be re-forwarded for the
first-token logits without perturbing other readers. Blocks a request
may ever write (suffix pads + the whole decode budget) are reserved at
admit, so shared pages are full, immutable prompt blocks by
construction and the jitted programs never need to allocate.

**Speculative decoding** (``spec_tokens=γ``; arXiv 2302.01318), fused
into the ``decode_chunk`` scan: draft γ tokens per slot by on-device
n-gram lookup over the slot's token history, verify them in ONE batched
``γ+1``-token model call, vectorized per-slot accept/reject with a
cursor-rewind rollback (rejected K/V sit past the cursor in slot-owned
blocks, masked until overwritten). Every position is sampled from the
true conditional with the request's own key schedule, so the emitted
stream equals the non-speculative engine's EXACTLY for every sampling
configuration — drafts only decide how many samples one dispatch keeps.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.nanogpt import GPT, GPTConfig, decode_config
from ..programs import default_registry
from ..programs.serve_defs import (cow_def, paged_decode_def,
                                   paged_prefill_def, prefill_def,
                                   slot_admit_def, slot_decode_def,
                                   spec_decode_def)
from ..utils.resilience import fault_point

PyTree = Any


class NoFreeSlotError(RuntimeError):
    """``admit()`` was called with every slot occupied — a scheduler bug
    (the driver must check ``free_slots()`` first). Subclasses
    ``RuntimeError`` so pre-existing callers keep working."""


class NoFreeBlocksError(RuntimeError):
    """The paged KV pool cannot currently supply enough blocks for this
    admission. Unlike ``NoFreeSlotError`` this is an EXPECTED transient
    under load (an undersized pool serving long requests): the scheduler
    keeps the request queued and retries once running requests release
    their blocks. ``InferenceEngine.validate`` rejects up front any
    request whose worst-case block need exceeds the whole pool, so a
    queued request always eventually fits."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration — mirrors ``generate_fast``'s
    signature so a request and a ``generate_fast`` call are comparable.
    ``eos_token`` stops the request early (in addition to
    ``max_new_tokens``); ``None`` disables the check."""

    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token: Optional[int] = None
    seed: int = 0


@dataclasses.dataclass
class ParkedSlot:
    """Host-side snapshot of one preempted slot (paged engines only).
    The block-table REFERENCES move into the snapshot — pages stay
    pinned in the pool at their current refcounts, exactly like the
    slot-owned write blocks the spec-decode rewind masks — so a later
    ``resume`` continues the generation byte-identical to an
    uncontended run: everything a decode dispatch reads about a slot
    (block table, cursors, token history, sampling vectors, the
    ``fold_in(base, gen_idx)`` key schedule) is per-step host input.
    ``released`` marks a consumed snapshot (resumed or dropped)."""

    block_table: np.ndarray
    pos: int
    hist: np.ndarray
    prompt_len: int
    next_tok: int
    gen_idx: int
    generated: int
    max_new: int
    eos: int
    temp: float
    top_k: int
    top_p: float
    base_key: np.ndarray
    released: bool = False


@dataclasses.dataclass
class TokenEvent:
    """One generated token, as seen by the scheduler. ``poisoned`` marks
    a token from a quarantined slot (non-finite logits): the value is
    garbage and the scheduler must fail the request, not deliver it."""

    slot: int
    token: int
    finished: bool
    poisoned: bool = False


@dataclasses.dataclass
class EngineStats:
    tokens_generated: int = 0
    decode_steps: int = 0
    prefills: int = 0
    prefill_compiles: int = 0            # new bucket programs THIS engine hit
    prefill_buckets: Tuple[int, ...] = ()
    prefill_tokens: int = 0              # padded tokens dispatched through
    #                                      prefill — the prefix-sharing
    #                                      work-elision observable
    active_slots: int = 0
    num_slots: int = 0
    quarantined: int = 0                 # slots shut down on NaN/Inf logits
    # paged-KV observables (0 on an unpaged engine)
    kv_blocks_in_use: int = 0            # pages referenced by live slots
    kv_blocks_cached: int = 0            # resident reusable prefix blocks
    prefix_hit_blocks: int = 0           # cumulative blocks served from the
    #                                      prefix cache instead of prefilled
    # speculative-decoding counters (0 with speculation off)
    spec_drafted: int = 0
    spec_accepted: int = 0
    # preemptible-decode counters (ISSUE 17): slots parked for a more
    # urgent request / parked snapshots resumed into a slot
    preemptions: int = 0
    resumes: int = 0
    # quantized-serving config echo (ISSUE 11): which dtypes this
    # engine's params and KV pools are stored in — ride on stats so
    # metrics/serve.csv/stats report them without reaching into config
    weights_dtype: str = "f32"
    kv_dtype: str = "f32"

    def spec_accept_rate(self) -> Optional[float]:
        """Accepted / drafted speculative tokens (None before the first
        draft) — the EWMA-priceable acceptance observable."""
        if not self.spec_drafted:
            return None
        return self.spec_accepted / self.spec_drafted


def prompt_bucket(n: int, block_size: int) -> int:
    """Power-of-two prefill bucket for an ``n``-token prompt, capped at
    ``block_size`` — the compile-bound lever: all prompt lengths map to at
    most ``⌈log2(block_size)⌉ + 1`` distinct shapes."""
    if n < 1:
        raise ValueError("empty prompt")
    b = 1 << (n - 1).bit_length()
    return min(b, block_size)


def max_prefill_buckets(block_size: int) -> int:
    """The compile-count bound serving any mix of prompt lengths:
    buckets are {1, 2, 4, ..., 2^⌈log2(block_size)⌉ capped} — at most
    ``⌈log2(block_size)⌉ + 1`` of them."""
    return (block_size - 1).bit_length() + 1


class BlockAllocator:
    """Host-side ref-counted page allocator + prefix hash table for the
    paged KV pool (PagedAttention, arXiv 2309.06180).

    Page ids index the device pools (``[kv_pages, page_size, H, hd]``
    per layer); page 0 is the reserved NULL page — never allocated,
    the write-redirect target for deactivated rows. A page's refcount
    counts ACTIVE slot users; pages holding full, block-aligned PROMPT
    blocks are additionally content-registered in the prefix cache under
    an exact chain key ``(parent_chain_id, block_token_bytes)``. The
    parent id is a monotonically increasing content id — never a page
    id — so a recycled page can never falsely revalidate a stale child
    entry. A cached page at refcount 0 stays RESIDENT (that is the
    point: the next request with the same prefix reuses it copy-free)
    and is evicted LRU only when the free list runs dry.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"kv_pages must be >= 2 (null page + one real page), "
                f"got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free = list(range(num_pages - 1, 0, -1))   # pop() → low ids
        self._ref: Dict[int, int] = {}
        # chain key → (page, content id); insertion order is LRU order
        # (lookup hits refresh recency)
        from collections import OrderedDict
        self._cache: "OrderedDict[Tuple[int, bytes], Tuple[int, int]]" = \
            OrderedDict()
        self._key_of: Dict[int, Tuple[int, bytes]] = {}
        self._cid = 0

    # -- observables ------------------------------------------------------

    def in_use(self) -> int:
        return sum(1 for r in self._ref.values() if r > 0)

    def cached(self) -> int:
        return len(self._cache)

    def available(self, exclude=()) -> int:
        """Pages an ``alloc`` burst could obtain right now: the free list
        plus evictable (refcount-0 cached) pages. ``exclude`` treats the
        given pages as unavailable — a planned admission must not count
        the very prefix blocks it is about to pin as evictable slack."""
        ex = set(exclude)
        n = len(self._free)
        for _key, (pg, _cid) in self._cache.items():
            if self._ref.get(pg, 0) == 0 and pg not in ex:
                n += 1
        return n

    # -- allocation -------------------------------------------------------

    def alloc(self) -> int:
        """Allocate a page at refcount 1, evicting the LRU refcount-0
        cached page when the free list is empty."""
        if self._free:
            pg = self._free.pop()
        else:
            pg = self._evict_one()
        self._ref[pg] = 1
        return pg

    def _evict_one(self) -> int:
        for key, (pg, _cid) in self._cache.items():      # oldest first
            if self._ref.get(pg, 0) == 0:
                del self._cache[key]
                del self._key_of[pg]
                self._ref.pop(pg, None)
                return pg
        raise NoFreeBlocksError(
            f"paged KV pool exhausted: all {self.num_pages - 1} pages "
            f"are referenced by running requests")

    def incref(self, page: int) -> None:
        self._ref[page] = self._ref.get(page, 0) + 1

    def decref(self, page: int) -> None:
        r = self._ref.get(page, 0) - 1
        if r < 0:
            raise ValueError(f"page {page} double-freed")
        self._ref[page] = r
        if r == 0 and page not in self._key_of:
            # plain owned page → straight back to the free list; cached
            # pages stay resident (evictable) for future prefix hits
            self._ref.pop(page)
            self._free.append(page)

    # -- prefix cache -----------------------------------------------------

    def lookup(self, parent_cid: int, block: bytes):
        """Resident ``(page, cid)`` for this chain link, or None. A hit
        refreshes the entry's LRU recency."""
        key = (parent_cid, block)
        ent = self._cache.get(key)
        if ent is not None:
            self._cache.move_to_end(key)
        return ent

    def touch(self, page: int) -> None:
        """Refresh a cached page's LRU recency by page id — admission
        commits touch their hit pages so a hot prefix is not the
        eviction victim just because planning probes never counted."""
        key = self._key_of.get(page)
        if key is not None:
            self._cache.move_to_end(key)

    def probe(self, parent_cid: int, block: bytes):
        """``lookup`` without the LRU touch — for capacity planning and
        scheduler ordering probes that may never admit."""
        return self._cache.get((parent_cid, block))

    def register(self, parent_cid: int, block: bytes, page: int) -> int:
        """Content-register an owned full prompt block; returns the chain
        id for the NEXT block's parent. If the key is already cached the
        existing entry wins (its cid is returned and our page stays a
        plain owned page) — chains dedupe onto the canonical lineage."""
        key = (parent_cid, block)
        ent = self._cache.get(key)
        if ent is not None:
            return ent[1]
        self._cid += 1
        self._cache[key] = (page, self._cid)
        self._key_of[page] = key
        return self._cid


class InferenceEngine:
    """Slot-level mechanics: caches, prefill, the shared decode step.

    Request-level concerns (queueing, backpressure, completion futures)
    live in ``scheduler.Scheduler``; the engine only knows slots. Not
    thread-safe — one driver thread calls ``admit``/``step``/``release``
    (the scheduler serializes access).
    """

    def __init__(self, params: PyTree, config: GPTConfig,
                 num_slots: int = 8, decode_chunk: int = 1,
                 paged: bool = False, page_size: int = 16,
                 kv_pages: Optional[int] = None, spec_tokens: int = 0,
                 weights_tag: Optional[str] = None):
        """``decode_chunk``: decode steps fused into one dispatch (a
        device-side scan with on-device EOS/max-token bookkeeping).
        1 = purest continuous batching — admission/eviction can happen
        after every token. Larger chunks amortize per-dispatch overhead
        (the lever that beats ``generate_fast``'s whole-request scan on
        throughput) at the cost of slot-turnaround latency: a slot
        finishing mid-chunk frees only at the chunk boundary.

        ``paged=True`` switches the KV cache to a page POOL
        (``kv_pages`` pages of ``page_size`` tokens; default pool =
        1 null page + ``num_slots`` full windows) with a per-slot block
        table, a ref-counted allocator and a prefix hash table: a prompt
        whose longest block-aligned prefix is already resident is
        admitted WITHOUT re-prefilling or copying those blocks.
        ``spec_tokens=γ > 0`` (paged only) adds self-drafting
        speculative decoding: each decode iteration drafts γ tokens by
        n-gram lookup and verifies them in one batched model call —
        token streams stay EXACTLY equal to the non-speculative engine
        (see ``programs.serve_defs.build_spec_decode``).

        ``weights_tag`` names the parameter set this engine serves (e.g.
        ``"step-120"``) — pure observability for the fleet router's
        zero-downtime weight hot-swap: after a rolling reload, ``/stats``
        proves which checkpoint each replica is generating from."""
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if decode_chunk < 1:
            raise ValueError(
                f"decode_chunk must be >= 1, got {decode_chunk}")
        if spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {spec_tokens}")
        if spec_tokens and not paged:
            raise ValueError(
                "speculative decoding rides on the paged KV path — pass "
                "paged=True (the rollback contract needs slot-owned "
                "write blocks)")
        self.paged = bool(paged)
        self.spec_tokens = int(spec_tokens)
        self.weights_tag = weights_tag
        self.weights_dtype = str(getattr(config, "weights_dtype", "f32"))
        self.kv_dtype = str(getattr(config, "kv_dtype", "f32"))
        if self.weights_dtype not in ("f32", "int8", "int4"):
            raise ValueError(
                f"weights_dtype must be 'f32', 'int8' or 'int4', got "
                f"{self.weights_dtype!r}")
        if self.kv_dtype not in ("f32", "int8"):
            raise ValueError(
                f"kv_dtype must be 'f32' or 'int8', got "
                f"{self.kv_dtype!r}")
        base_cfg = decode_config(config)
        self.block_size = int(config.block_size)
        self.num_slots = int(num_slots)
        self.decode_chunk = int(decode_chunk)
        if self.paged:
            if page_size < 1 or self.block_size % page_size:
                raise ValueError(
                    f"page_size must be >= 1 and divide block_size "
                    f"{self.block_size}, got {page_size}")
            self.page_size = int(page_size)
            self.max_blocks = self.block_size // self.page_size
            if kv_pages is None:
                # null page + one full window per slot + one page of
                # copy-on-write headroom (also satisfies the 1-slot
                # minimum below)
                kv_pages = 2 + self.num_slots * self.max_blocks
            if kv_pages < 2 + self.max_blocks:
                raise ValueError(
                    f"kv_pages={kv_pages} too small: need the null page "
                    f"+ one full window ({self.max_blocks} blocks) + one "
                    f"copy-on-write page")
            self.kv_pages = int(kv_pages)
            self.config = dataclasses.replace(
                base_cfg, page_size=self.page_size, kv_pages=self.kv_pages)
            self._alloc = BlockAllocator(self.kv_pages, self.page_size)
        else:
            self.page_size = 0
            self.max_blocks = 0
            self.kv_pages = 0
            self.config = base_cfg
            self._alloc = None
        if self.weights_dtype != "f32":
            # quantize-at-load: accept either an f32 checkpoint tree or
            # a pre-quantized one (load_for_serving quantizes once; the
            # fleet's factory rebuilds then detect and skip)
            from .load import params_are_quantized, quantize_params
            if not params_are_quantized(params):
                params = quantize_params(params, self.config)
        self.params = jax.tree.map(jnp.asarray, params)
        self.weights_bytes = int(sum(x.nbytes
                                     for x in jax.tree.leaves(self.params)))
        self._cfg_tuple = dataclasses.astuple(self.config)
        # every program comes from the process-wide device-program
        # registry (gym_tpu.programs): engines over the same config —
        # replicas, supervisor rebuilds, hot-swapped generations —
        # share ONE compiled executable per key, and the entries this
        # engine holds are pinned against capacity eviction for its
        # lifetime (released via weakref when the engine is collected)
        self._registry = default_registry()
        if self.paged:
            self._admit_prog = None
            self._decode_prog = self._acquire(paged_decode_def(
                self._cfg_tuple, self.num_slots, self.decode_chunk))
            self._cow_prog = self._acquire(cow_def(self._cfg_tuple))
            self._spec_prog = (
                self._acquire(spec_decode_def(
                    self._cfg_tuple, self.num_slots, self.decode_chunk,
                    self.spec_tokens))
                if self.spec_tokens else None)
        else:
            self._admit_prog = self._acquire(slot_admit_def(
                self._cfg_tuple, self.num_slots))
            self._decode_prog = self._acquire(slot_decode_def(
                self._cfg_tuple, self.num_slots, self.decode_chunk))
            self._cow_prog = None
            self._spec_prog = None
        self._step1_prog = None          # lazy chunk-1 twin (teacher forcing)
        self._prefill_progs: Dict[int, Any] = {}   # bucket → handle
        self._seen_buckets: set = set()
        self._cache = self._init_cache()
        s = self.num_slots
        if self.paged:
            self._bt = np.zeros((s, self.max_blocks), np.int32)
            self._pos = np.zeros(s, np.int32)          # per-slot KV cursor
            self._hist = np.zeros((s, self.block_size), np.int32)
            self._prompt_len = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._next_tok = np.zeros(s, np.int32)     # input token per slot
        self._gen_idx = np.zeros(s, np.int32)      # key-schedule index
        self._generated = np.zeros(s, np.int64)    # tokens emitted so far
        self._max_new = np.zeros(s, np.int64)
        self._eos = np.full(s, -1, np.int64)       # -1 = disabled
        self._temp = np.ones(s, np.float32)
        self._top_k = np.full(s, self.config.vocab_size, np.int32)
        self._top_p = np.ones(s, np.float32)
        self._base_keys = np.zeros((s, 2), np.uint32)
        self.stats = EngineStats(num_slots=s,
                                 weights_dtype=self.weights_dtype,
                                 kv_dtype=self.kv_dtype)
        self.last_logits: Optional[np.ndarray] = None  # [S, V] post-step

    # -- quantized-serving observables ------------------------------------

    @property
    def kv_elem_bytes(self) -> int:
        """Bytes per stored KV element (1 under int8, 4 under f32)."""
        return 1 if self.kv_dtype == "int8" else 4

    @property
    def kv_blocks_capacity_effective(self) -> int:
        """Usable block capacity normalized to the f32 payload budget:
        an int8 pool stores 4 KV elements in every f32 element's bytes,
        so the byte budget an f32 ``kv_pages`` pool's PAYLOAD occupies
        holds ``4 x (kv_pages - 1)`` usable int8 blocks. The per-(page
        slot, head) scale sidecar (4/hd of the int8 payload — 6.25% at
        head dim 64) is NOT hidden inside this number: it is reported
        separately by ``kv_pool_bytes``. Equals the plain usable-block
        count on an f32 engine; 0 unpaged."""
        if not self.paged:
            return 0
        return (self.kv_pages - 1) * (4 // self.kv_elem_bytes)

    def kv_pool_bytes(self) -> Dict[str, int]:
        """Actual device bytes of the KV cache, split into the K/V
        payload and the quantization-scale sidecar (0 at f32) — the
        honest-accounting observable behind the 4x capacity claim."""
        payload = scales = 0

        def walk(node):
            nonlocal payload, scales
            if hasattr(node, "items"):
                for name, sub in node.items():
                    if hasattr(sub, "items"):
                        walk(sub)
                    elif name in ("k", "v"):
                        payload += int(sub.nbytes)
                    elif name.endswith("_scale"):
                        scales += int(sub.nbytes)

        walk(self._cache)
        return {"payload": payload, "scales": scales}

    # -- device programs (registry-backed) --------------------------------

    def _acquire(self, pdef):
        return self._registry.acquire(pdef, pin_owner=self)

    def _prefill_prog(self, bucket: int):
        """Registry handle for this bucket's prefill program, ensured
        built; bumps ``stats.prefill_compiles`` when the acquisition
        actually built a new program (the bounded-compilation
        observable — a program another engine over the same config
        already built is a hit, not a compile)."""
        h = self._prefill_progs.get(bucket)
        if h is None:
            pdef = (paged_prefill_def(self._cfg_tuple, bucket)
                    if self.paged
                    else prefill_def(self._cfg_tuple, bucket))
            h = self._acquire(pdef)
            self._prefill_progs[bucket] = h
        # exact per-key attribution: ensure_reporting is True only if
        # THIS call ran the build — a global-counter diff would charge
        # concurrent warmup/sibling-replica builds to this request
        if h.ensure_reporting():
            self.stats.prefill_compiles += 1
        return h

    def warmup_defs(self) -> List[Any]:
        """This engine's COMPLETE program family — what the background
        warmup precompiles so no request ever pays a compile: the full
        power-of-two prefill-bucket family plus the decode/admit (or
        paged decode/CoW/spec) programs, traffic-critical first."""
        buckets: List[int] = []
        b = 1
        while b < self.block_size:
            buckets.append(b)
            b <<= 1
        buckets.append(self.block_size)
        cfg, s, chunk = self._cfg_tuple, self.num_slots, self.decode_chunk
        if self.paged:
            defs = [paged_decode_def(cfg, s, chunk)]
            if self.spec_tokens:
                defs.append(spec_decode_def(cfg, s, chunk,
                                            self.spec_tokens))
            defs.append(cow_def(cfg))
            if chunk != 1 or self.spec_tokens:
                # the lazy chunk-1 twin (teacher forcing / eval
                # harnesses) is part of the family too — without it a
                # warmed or disk-restored process pays its compile on
                # the first override_tokens step
                defs.append(paged_decode_def(cfg, s, 1))
            defs.extend(paged_prefill_def(cfg, b) for b in buckets)
        else:
            defs = [slot_decode_def(cfg, s, chunk),
                    slot_admit_def(cfg, s)]
            if chunk != 1:
                defs.append(slot_decode_def(cfg, s, 1))
            defs.extend(prefill_def(cfg, b) for b in buckets)
        return defs

    def _init_cache(self) -> PyTree:
        model = GPT(self.config)
        dummy = jnp.zeros((self.num_slots, 1), jnp.int32)
        if self.paged:
            # the pool is batch-shape independent ([kv_pages, page, H,
            # hd] per layer): a 1-row prefill and an S-row decode run
            # against the SAME buffers — that is what makes the prefix
            # blocks shareable without an admit-scatter program
            shapes = jax.eval_shape(
                lambda: model.init(
                    {"params": jax.random.PRNGKey(0)}, dummy, train=False,
                    block_table=jnp.zeros(
                        (self.num_slots, self.max_blocks), jnp.int32),
                    cache_pos=jnp.zeros((self.num_slots,), jnp.int32)))
        else:
            shapes = jax.eval_shape(
                lambda: model.init({"params": jax.random.PRNGKey(0)},
                                   dummy, train=False))
        return jax.tree.map(lambda sh: jnp.zeros(sh.shape, sh.dtype),
                            shapes["cache"])

    # -- slot lifecycle ---------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if not self._active[i]]

    def validate(self, prompt: np.ndarray, sp: SamplingParams) -> None:
        """Typed rejection of requests the decode path cannot serve
        honestly — callers (scheduler.submit, the HTTP handler) fail fast
        with a ValueError instead of poisoning a slot: cache overflow
        (the same error ``generate_fast`` raises), out-of-vocab token ids
        (XLA's gather would silently CLAMP them to vocab_size-1 and serve
        a completion for a prompt the client never sent), and
        non-positive temperature (logits/0 → NaN → garbage tokens;
        greedy decoding is ``top_k=1``, not ``temperature=0``)."""
        prompt = np.asarray(prompt)
        n = int(prompt.size)
        if n < 1:
            raise ValueError("empty prompt")
        if prompt.min() < 0 or prompt.max() >= self.config.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, {self.config.vocab_size})"
                f"; got range [{int(prompt.min())}, {int(prompt.max())}]")
        if sp.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {sp.max_new_tokens}")
        if not sp.temperature > 0:
            raise ValueError(
                f"temperature must be > 0 (got {sp.temperature}); use "
                f"top_k=1 for greedy decoding")
        if n + sp.max_new_tokens > self.block_size:
            raise ValueError(
                f"prompt {n} + {sp.max_new_tokens} new tokens exceeds the "
                f"KV cache (block_size {self.block_size}); crop the prompt "
                f"to block_size - max_new_tokens, or use `generate`, whose "
                f"full-context resampling slides the context window")
        if self.paged:
            # worst case (zero prefix hits, +1 copy-on-write headroom)
            # must fit the pool EVER, so a queued request always
            # eventually admits once running slots release their blocks
            worst = -(-(n + sp.max_new_tokens) // self.page_size) + 1
            if worst > self.kv_pages - 1:
                raise ValueError(
                    f"request needs up to {worst} KV blocks but the "
                    f"paged pool holds {self.kv_pages - 1}; raise "
                    f"kv_pages or shrink prompt/max_new_tokens")

    # -- paged planning ---------------------------------------------------

    def _walk_prefix(self, prompt: np.ndarray):
        """Consecutive resident full prompt blocks: ``(hit_pages,
        chain_cids)`` — THE prefix probe, shared by planning, capacity
        checks and the scheduler's ordering score (no LRU touch; only a
        committing admission refreshes recency)."""
        page, al = self.page_size, self._alloc
        hit_pages: List[int] = []
        chain: List[int] = []
        cid = 0
        for b in range(len(prompt) // page):
            ent = al.probe(cid, prompt[b * page:(b + 1) * page].tobytes())
            if ent is None:
                break
            hit_pages.append(ent[0])
            cid = ent[1]
            chain.append(cid)
        return hit_pages, chain

    def _plan_paged(self, prompt: np.ndarray, max_new: int):
        """Plan a paged admission without mutating allocator state:
        returns ``(hit_pages, chain_cids, cow_src, parent_cid, start,
        suffix, bucket, n_new, need)``. ``hit_pages`` are the resident
        shared-prefix blocks (to be pinned), ``cow_src`` a fully-matched
        final block to copy-on-write (its last token is re-forwarded for
        the first-token logits — recomputing INTO the shared page would
        perturb other readers by the recompute's rounding), ``n_new``
        the fresh blocks to allocate and ``need`` the total pages the
        admission must obtain (n_new + the CoW page)."""
        n = len(prompt)
        page, s_max = self.page_size, self.block_size
        full = n // page
        hit_pages, chain = self._walk_prefix(prompt)
        cid = chain[-1] if chain else 0
        cow_src = None
        if hit_pages and len(hit_pages) * page == n:
            cow_src = hit_pages.pop()
            chain.pop()
            cid = chain[-1] if chain else 0
        matched = len(hit_pages) * page
        suffix = n - matched
        # pad writes (suffix rounded up to its bucket) must stay inside
        # the [block_size] window: un-share blocks until they do. Rare —
        # only near-full-window prompts with a large unshared suffix.
        # The CoW path is exempt: its real suffix is ONE token (bucket
        # 1, start n-1 ≤ block_size-1 always fits) — running the guard
        # on the stale pre-override suffix could otherwise pop hits
        # whose table slots the CoW branch does not re-point.
        while cow_src is None and hit_pages \
                and matched + prompt_bucket(suffix, s_max) > s_max:
            hit_pages.pop()
            chain.pop()
            cid = chain[-1] if chain else 0
            matched -= page
            suffix += page
        if cow_src is not None:
            start, suffix, bucket = n - 1, 1, 1
            first_new = full                 # CoW page covers block full-1
        else:
            start = matched
            bucket = prompt_bucket(suffix, s_max)
            first_new = matched // page
        end_tokens = max(n + max_new, start + bucket)
        n_new = -(-end_tokens // page) - first_new
        need = n_new + (1 if cow_src is not None else 0)
        return (hit_pages, chain, cow_src, cid, start, suffix, bucket,
                n_new, need)

    def admit_probe(self, prompt, sp: SamplingParams) -> Tuple[bool, int]:
        """ONE planning walk answering both scheduler questions:
        ``(would admit() succeed right now, resident-prefix score)``.
        The capacity answer is exact, not conservative — it runs the
        same plan ``admit`` would and excludes the would-be-pinned
        prefix blocks from the evictable supply. Unpaged:
        ``(True, 0)`` — ordering degrades to FCFS."""
        if not self.paged:
            return True, 0
        p = np.asarray(prompt, np.int32).reshape(-1)
        hit_pages, _chain, cow_src, _cid, _start, _suffix, _bucket, \
            _n_new, need = self._plan_paged(p, sp.max_new_tokens)
        pinned = hit_pages + ([cow_src] if cow_src is not None else [])
        score = len(hit_pages) + (1 if cow_src is not None else 0)
        return self._alloc.available(exclude=pinned) >= need, score

    def resident_prefix_blocks(self, prompt) -> int:
        """How many leading full blocks of ``prompt`` the prefix cache
        could serve right now. 0 on an unpaged engine."""
        if not self.paged:
            return 0
        p = np.asarray(prompt, np.int32).reshape(-1)
        return len(self._walk_prefix(p)[0])

    def has_capacity(self, prompt, sp: SamplingParams) -> bool:
        """Whether an ``admit`` of this request would succeed RIGHT NOW
        (block supply; the caller checks ``free_slots`` itself)."""
        return self.admit_probe(prompt, sp)[0]

    def admit(self, prompt: np.ndarray,
              sp: SamplingParams) -> Tuple[int, TokenEvent]:
        """Prefill ``prompt`` into a free slot and sample its first token.
        Returns ``(slot, event)``; when the first token already finishes
        the request (``max_new_tokens == 1`` or instant EOS) the slot is
        released before returning."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate(prompt, sp)
        free = self.free_slots()
        if not free:
            raise NoFreeSlotError(
                "no free slot — admit() requires one (scheduler bug: "
                "check free_slots() first)")
        slot = free[0]
        fault_point("serve.prefill")
        n = len(prompt)
        base_key = np.asarray(jax.random.PRNGKey(sp.seed), np.uint32)
        top_k = (self.config.vocab_size if sp.top_k is None
                 else int(sp.top_k))
        top_p = 1.0 if sp.top_p is None else float(sp.top_p)
        if self.paged:
            first = self._prefill_paged(slot, prompt, sp, base_key,
                                        top_k, top_p)
        else:
            bucket = prompt_bucket(n, self.block_size)
            self._seen_buckets.add(bucket)
            prefill = self._prefill_prog(bucket)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = prompt
            tok, row_cache = prefill(
                self.params, jnp.asarray(padded), np.int32(n),
                jnp.asarray(base_key), np.float32(sp.temperature),
                np.int32(top_k), np.float32(top_p))
            self._cache = self._admit_prog(self._cache, row_cache,
                                           np.int32(slot), np.int32(n))
            first = int(np.asarray(tok)[0])
            self.stats.prefill_tokens += bucket
        self.stats.prefills += 1
        self.stats.tokens_generated += 1
        # slot bookkeeping: the first token came from the prefill (key
        # index 0); decode steps continue the schedule at index 1
        self._active[slot] = True
        self._next_tok[slot] = first
        self._gen_idx[slot] = 1
        self._generated[slot] = 1
        self._max_new[slot] = sp.max_new_tokens
        self._eos[slot] = -1 if sp.eos_token is None else int(sp.eos_token)
        self._temp[slot] = sp.temperature
        self._top_k[slot] = top_k
        self._top_p[slot] = top_p
        self._base_keys[slot] = base_key
        if self.paged:
            # token history feeds the n-gram draft; the first token is
            # emitted (index n), giving hist_len == cursor + 1
            self._hist[slot, n] = first
        finished = (sp.max_new_tokens <= 1
                    or (sp.eos_token is not None and first == sp.eos_token))
        if finished:
            self._active[slot] = False
            if self.paged:
                self._release_pages(slot)
        self.stats.active_slots = int(self._active.sum())
        self.stats.prefill_buckets = tuple(sorted(self._seen_buckets))
        return slot, TokenEvent(slot, first, finished)

    def _prefill_paged(self, slot: int, prompt: np.ndarray,
                       sp: SamplingParams, base_key, top_k: int,
                       top_p: float) -> int:
        """Prefix-aware paged prefill: pin the resident shared-prefix
        blocks, copy-on-write a fully-matched final block, allocate the
        owned blocks (prefill pads + the whole decode budget — blocks
        are reserved at admit, so mid-decode writes can never need an
        allocation the jitted program couldn't perform), dispatch the
        SUFFIX-only prefill, then content-register this prompt's own
        full blocks for future requests to hit."""
        n = len(prompt)
        page, al = self.page_size, self._alloc
        full = n // page
        hit_pages, chain, cow_src, cid, start, suffix, bucket, n_new, \
            need = self._plan_paged(prompt, sp.max_new_tokens)
        # `held` tracks every page reference this admission currently
        # owns; ANY failure past this point (capacity shortfall, a
        # compile/dispatch error in CoW or prefill) unwinds it exactly —
        # an admission that fails its request must not shrink the pool
        held: List[int] = []
        try:
            # pin before the capacity check: a pinned page is neither
            # evictable nor double-counted as supply
            for pg in hit_pages:
                al.incref(pg)
                held.append(pg)
            if cow_src is not None:
                al.incref(cow_src)
                held.append(cow_src)
            if al.available() < need:
                raise NoFreeBlocksError(
                    f"paged KV pool cannot supply {need} blocks right "
                    f"now — retry after running requests release")
            row = np.zeros(self.max_blocks, np.int32)
            row[:len(hit_pages)] = hit_pages
            next_b = len(hit_pages)
            if cow_src is not None:
                dst = al.alloc()
                held.append(dst)
                row[next_b] = dst
                next_b += 1
                self._cache = self._cow_prog(
                    self._cache, np.int32(cow_src), np.int32(dst))
                al.decref(cow_src)       # pinned only for the copy
                held.remove(cow_src)
            for k in range(n_new):
                pg = al.alloc()
                held.append(pg)
                row[next_b + k] = pg
            self._bt[slot] = 0
            self._bt[slot, :next_b + n_new] = row[:next_b + n_new]
            self._seen_buckets.add(bucket)
            prefill = self._prefill_prog(bucket)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :suffix] = prompt[start:]
            tok, self._cache = prefill(
                self.params, self._cache,
                jnp.asarray(self._bt[slot][None]),
                jnp.asarray(np.asarray([start], np.int32)),
                jnp.asarray(padded), np.int32(suffix),
                jnp.asarray(base_key), np.float32(sp.temperature),
                np.int32(top_k), np.float32(top_p))
        except BaseException:
            for pg in held:
                al.decref(pg)
            self._bt[slot] = 0
            raise
        # only a COMMITTING admission refreshes hit recency — planning
        # probes must not keep a never-admitted prefix artificially hot
        for pg in hit_pages:
            al.touch(pg)
        if cow_src is None:
            # register the freshly-prefilled full PROMPT blocks (their
            # content is immutable — decode writes start past them);
            # the CoW path has nothing new: every block was cached
            reg_cid = cid
            for b in range(len(hit_pages), full):
                reg_cid = al.register(
                    reg_cid, prompt[b * page:(b + 1) * page].tobytes(),
                    int(row[b]))
        self._pos[slot] = n
        self._hist[slot] = 0
        self._hist[slot, :n] = prompt
        self._prompt_len[slot] = n
        self.stats.prefix_hit_blocks += (len(hit_pages)
                                         + (1 if cow_src is not None
                                            else 0))
        self.stats.prefill_tokens += bucket
        self.stats.kv_blocks_in_use = al.in_use()
        self.stats.kv_blocks_cached = al.cached()
        return int(np.asarray(tok)[0])

    def _release_pages(self, slot: int) -> None:
        """Drop this slot's block-table references (idempotent: an
        already-cleared row is a no-op). Cached prefix blocks stay
        resident at refcount 0; plain owned blocks return to the free
        list."""
        if not self.paged:
            return
        for pg in self._bt[slot]:
            if pg:
                self._alloc.decref(int(pg))
        self._bt[slot] = 0
        self.stats.kv_blocks_in_use = self._alloc.in_use()
        self.stats.kv_blocks_cached = self._alloc.cached()

    def release(self, slot: int) -> None:
        """Free a slot between decode steps (EOS/max-tokens eviction or a
        cancelled request). Unpaged, the cache rows stay as-is — the next
        admit overwrites them wholesale; paged, the slot's block-table
        references are dropped (shared prefix blocks stay resident for
        future hits)."""
        self._active[slot] = False
        self._release_pages(slot)
        self.stats.active_slots = int(self._active.sum())

    # -- preemptible decode (park / resume) --------------------------------

    def park(self, slot: int) -> ParkedSlot:
        """Preempt an ACTIVE slot at a chunk boundary (between ``step``
        dispatches): snapshot its entire host-side cursor state and
        block table WITHOUT decreffing the pages — the snapshot owns the
        references — deactivate the row, and return the snapshot. Pure
        host bookkeeping: no device work, no copies of KV state. Paged
        engines only (an unpaged slot's cache rows are overwritten
        wholesale by the next admit, so nothing parkable survives)."""
        if not self.paged:
            raise ValueError(
                "park() requires a paged engine — unpaged cache rows do "
                "not survive the next admit")
        if not self._active[slot]:
            raise ValueError(f"slot {slot} is not active — nothing to park")
        parked = ParkedSlot(
            block_table=self._bt[slot].copy(),
            pos=int(self._pos[slot]),
            hist=self._hist[slot].copy(),
            prompt_len=int(self._prompt_len[slot]),
            next_tok=int(self._next_tok[slot]),
            gen_idx=int(self._gen_idx[slot]),
            generated=int(self._generated[slot]),
            max_new=int(self._max_new[slot]),
            eos=int(self._eos[slot]),
            temp=float(self._temp[slot]),
            top_k=int(self._top_k[slot]),
            top_p=float(self._top_p[slot]),
            base_key=self._base_keys[slot].copy())
        self._active[slot] = False
        # references moved to the snapshot: zero the row WITHOUT decref
        # so release()/step()'s page sweep cannot double-free them
        self._bt[slot] = 0
        self.stats.preemptions += 1
        self.stats.active_slots = int(self._active.sum())
        return parked

    def resume(self, parked: ParkedSlot) -> int:
        """Restore a parked snapshot into a free slot. No device work —
        the KV pool is shared across slots and the block table is a
        per-dispatch host input, so the resumed generation continues
        from exactly the token it was preempted at, byte-identical by
        the per-token key schedule. Raises ``NoFreeSlotError`` when
        every slot is busy (the scheduler checks first)."""
        if parked.released:
            raise ValueError("parked snapshot already consumed")
        free = self.free_slots()
        if not free:
            raise NoFreeSlotError(
                "no free slot to resume the parked request into")
        slot = free[0]
        self._bt[slot] = parked.block_table
        self._pos[slot] = parked.pos
        self._hist[slot] = parked.hist
        self._prompt_len[slot] = parked.prompt_len
        self._active[slot] = True
        self._next_tok[slot] = parked.next_tok
        self._gen_idx[slot] = parked.gen_idx
        self._generated[slot] = parked.generated
        self._max_new[slot] = parked.max_new
        self._eos[slot] = parked.eos
        self._temp[slot] = parked.temp
        self._top_k[slot] = parked.top_k
        self._top_p[slot] = parked.top_p
        self._base_keys[slot] = parked.base_key
        parked.released = True
        self.stats.resumes += 1
        self.stats.active_slots = int(self._active.sum())
        return slot

    def release_parked(self, parked: ParkedSlot) -> None:
        """Drop a parked snapshot's page references without resuming it
        (deadline/cancel/shutdown caught the request while parked).
        Idempotent via the ``released`` flag."""
        if parked.released:
            return
        parked.released = True
        for pg in parked.block_table:
            if pg:
                self._alloc.decref(int(pg))
        self.stats.kv_blocks_in_use = self._alloc.in_use()
        self.stats.kv_blocks_cached = self._alloc.cached()

    def step(self, override_tokens: Optional[Dict[int, int]] = None
             ) -> List[TokenEvent]:
        """Advance every active slot by up to ``decode_chunk`` tokens (one
        dispatch); returns the new tokens in generation order. Slots that
        finish (EOS / max-tokens, decided ON DEVICE mid-chunk) come back
        inactive and are free for the next admit — eviction happens
        between dispatches, admission too: continuous batching at chunk
        granularity.

        ``override_tokens`` (teacher forcing, tests/eval only) replaces a
        slot's INPUT token for ONE single step — the call runs a chunk-1
        program regardless of ``decode_chunk`` and the returned logits
        (``self.last_logits``) are the model's prediction conditioned on
        the forced history, while sampling proceeds normally.
        """
        prog = self._decode_prog
        spec_run = self._spec_prog is not None
        if override_tokens:
            for slot, tok in override_tokens.items():
                self._next_tok[slot] = int(tok)
            spec_run = False
            if self.decode_chunk != 1 or self._spec_prog is not None:
                if self._step1_prog is None:
                    self._step1_prog = self._acquire(
                        paged_decode_def(self._cfg_tuple,
                                         self.num_slots, 1)
                        if self.paged
                        else slot_decode_def(self._cfg_tuple,
                                             self.num_slots, 1))
                prog = self._step1_prog
        elif spec_run:
            prog = self._spec_prog
        if not self._active.any():
            return []
        # hit-counted AFTER the idle early-out so hit N is the Nth REAL
        # decode dispatch — "hang at dispatch 2" reproduces exactly
        fault_point("serve.decode")
        was_active = self._active.copy()
        remaining = (self._max_new - self._generated).astype(np.int32)
        tail = (jnp.asarray(self._next_tok), jnp.asarray(self._active),
                jnp.asarray(self._base_keys), jnp.asarray(self._gen_idx),
                jnp.asarray(remaining),
                jnp.asarray(self._eos.astype(np.int32)),
                jnp.asarray(self._temp), jnp.asarray(self._top_k),
                jnp.asarray(self._top_p))
        if self.paged:
            head = (self.params, self._cache, jnp.asarray(self._bt))
            if spec_run:
                head += (jnp.asarray(self._hist),)
            tok_a, act_a, keys_a, gidx_a, rem_a, eos_a, t_a, k_a, p_a = \
                tail
            toks, emitted, lg, final_tok, final_active, final_pos, \
                nan_seen, cache = prog(*head, tok_a, act_a,
                                       jnp.asarray(self._pos), keys_a,
                                       gidx_a, rem_a, eos_a, t_a, k_a,
                                       p_a)
            self._pos = np.asarray(final_pos).astype(np.int32).copy()
            nan_seen = np.asarray(nan_seen)
        else:
            toks, emitted, lg, final_tok, final_active, cache = prog(
                self.params, self._cache, *tail)
            nan_seen = None
        self._cache = cache
        toks = np.asarray(toks)
        emitted = np.asarray(emitted)
        if toks.ndim == 2:
            # non-speculative programs emit one token per scanned step;
            # widen to the speculative [chunk, S, γ+1] layout so ONE host
            # replay path routes both
            toks = toks[..., None]
            emitted = emitted[..., None]
        self.last_logits = np.asarray(lg)
        self._next_tok = np.asarray(final_tok).astype(np.int32).copy()
        self._active = np.asarray(final_active).copy()
        # numerical quarantine: non-finite logits fail ONLY their own
        # slot — the model's per-row cache math keeps rows isolated (and
        # the decode attends NaN-poison an overflowing row/position on
        # purpose, so this is the designated catch point). Unpaged, the
        # check reads the LAST scanned step's logits for every slot
        # that emitted anywhere in this chunk: a poisoned slot that
        # finishes mid-chunk goes inactive but keeps attending its own
        # NaN cache rows, so the poison stays visible in the final
        # logits. Paged, that witness FAILS — a finished row's table is
        # redirected to the null page, so its later iterations read
        # clean garbage — and the programs instead LATCH non-finite
        # logits per iteration while the row is active (`nan_seen`).
        if nan_seen is not None:
            bad = nan_seen
        else:
            bad = emitted.any(axis=(0, 2)) & ~np.isfinite(
                self.last_logits).all(axis=1)
        for slot in np.nonzero(bad)[0]:
            self._active[slot] = False           # quarantine = evict
            self.stats.quarantined += 1
        events: List[TokenEvent] = []
        n_steps = toks.shape[0]
        for k in range(n_steps):
            for slot in np.nonzero(emitted[k].any(axis=1))[0]:
                if spec_run:
                    # acceptance accounting: γ drafted per active slot
                    # per iteration; all emitted beyond the one
                    # guaranteed token were accepted drafts
                    self.stats.spec_drafted += self.spec_tokens
                    self.stats.spec_accepted += int(
                        emitted[k, slot].sum()) - 1
                for j in np.nonzero(emitted[k, slot])[0]:
                    tok = int(toks[k, slot, j])
                    if self.paged:
                        hl = (int(self._prompt_len[slot])
                              + int(self._generated[slot]))
                        if hl < self.block_size:
                            self._hist[slot, hl] = tok
                    self._gen_idx[slot] += 1
                    self._generated[slot] += 1
                    # finished iff the device stopped emitting for this
                    # slot (its last emitted token) and it came back
                    # inactive
                    last_emit = (not emitted[k, slot, j + 1:].any()
                                 and not emitted[k + 1:, slot].any())
                    finished = bool(last_emit and not self._active[slot])
                    events.append(TokenEvent(int(slot), tok, finished,
                                             poisoned=bool(bad[slot])))
        if self.paged:
            # blocks of slots that finished (or were quarantined) this
            # chunk go back to the allocator; shared prefix blocks stay
            # resident for future hits
            for slot in np.nonzero(was_active & ~self._active)[0]:
                self._release_pages(slot)
        self.stats.tokens_generated += len(events)
        self.stats.decode_steps += int(was_active.any()) * n_steps
        self.stats.active_slots = int(self._active.sum())
        return events
