"""Continuous-batching inference engine: one jitted decode step, N slots.

The design inverts ``generate_fast``'s: instead of one compiled program
per request signature (prompt length × new tokens × sampling config —
every new shape recompiles), the engine compiles a FIXED-SHAPE program
set once and runs every request through it:

- **Decode step** (compiled once per ``(config, num_slots)``): the whole
  slot batch advances one token. Each slot is an independent sequence at
  its own cache position — the model's per-row cursors/masks
  (``models/nanogpt.py:_decode_attend``) keep rows isolated — and the
  per-slot sampling params (temperature / top-k / top-p / PRNG key) ride
  in as vectors, applied by a vmapped ``sample_logits``. Inactive slots
  compute garbage that is never read and their integer cursors are
  frozen, so a free slot can idle forever without overflowing.
- **Prefill** (compiled once per power-of-two bucket): a single request's
  prompt, right-padded to the bucket length, fills a fresh single-row
  cache and samples the first token at the TRUE last prompt position
  (padded positions are causally masked away from real queries and
  overwritten before any later query can attend to them). Total prefill
  compilations are bounded by ``⌈log2(block_size)⌉ + 1`` — the bucket
  count — instead of one per distinct prompt length.
- **Admit/evict** (compiled once): the prefilled row is scattered into
  the engine cache at the slot index and the slot's cursors rewound to
  the true prompt length. Admission and eviction happen BETWEEN decode
  steps (continuous batching): a finished slot frees mid-flight while
  its neighbors keep decoding — no drain-the-batch barrier.

Parity oracle (tests/test_serve.py): for a single request the engine's
token stream is IDENTICAL to ``generate_fast`` with the same sampling
config and seed — both use the shared ``sample_logits`` kernel and the
``fold_in(PRNGKey(seed), token_index)`` key schedule, and the per-row
cache math is the same program modulo batch width.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.nanogpt import GPT, GPTConfig, decode_config, sample_logits
from ..utils.resilience import fault_point

PyTree = Any


class NoFreeSlotError(RuntimeError):
    """``admit()`` was called with every slot occupied — a scheduler bug
    (the driver must check ``free_slots()`` first). Subclasses
    ``RuntimeError`` so pre-existing callers keep working."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration — mirrors ``generate_fast``'s
    signature so a request and a ``generate_fast`` call are comparable.
    ``eos_token`` stops the request early (in addition to
    ``max_new_tokens``); ``None`` disables the check."""

    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token: Optional[int] = None
    seed: int = 0


@dataclasses.dataclass
class TokenEvent:
    """One generated token, as seen by the scheduler. ``poisoned`` marks
    a token from a quarantined slot (non-finite logits): the value is
    garbage and the scheduler must fail the request, not deliver it."""

    slot: int
    token: int
    finished: bool
    poisoned: bool = False


@dataclasses.dataclass
class EngineStats:
    tokens_generated: int = 0
    decode_steps: int = 0
    prefills: int = 0
    prefill_compiles: int = 0            # new bucket programs THIS engine hit
    prefill_buckets: Tuple[int, ...] = ()
    active_slots: int = 0
    num_slots: int = 0
    quarantined: int = 0                 # slots shut down on NaN/Inf logits


def prompt_bucket(n: int, block_size: int) -> int:
    """Power-of-two prefill bucket for an ``n``-token prompt, capped at
    ``block_size`` — the compile-bound lever: all prompt lengths map to at
    most ``⌈log2(block_size)⌉ + 1`` distinct shapes."""
    if n < 1:
        raise ValueError("empty prompt")
    b = 1 << (n - 1).bit_length()
    return min(b, block_size)


def max_prefill_buckets(block_size: int) -> int:
    """The compile-count bound serving any mix of prompt lengths:
    buckets are {1, 2, 4, ..., 2^⌈log2(block_size)⌉ capped} — at most
    ``⌈log2(block_size)⌉ + 1`` of them."""
    return (block_size - 1).bit_length() + 1


# Program caches are GLOBAL (keyed by config/shape signature, like
# models.nanogpt._cached_decode_program) so several engines over the same
# model — tests, bench arms, server restarts in one process — share
# compilations. Each engine still counts the buckets it touches for the
# bounded-compilation observable.
@functools.lru_cache(maxsize=64)
def _prefill_program(cfg_tuple, bucket: int):
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)

    @jax.jit
    def prefill(params, tokens, true_len, key, temp, top_k, top_p):
        """tokens [1, bucket] right-padded; returns the sampled first
        token [1] and the filled single-row cache. The first token is
        sampled INSIDE the program (key schedule index 0) at the true
        last prompt position, so no per-``true_len`` slicing program
        exists outside this bucket's compile."""
        logits, varsc = model.apply({"params": params}, tokens,
                                    train=False, mutable=["cache"])
        last = jax.lax.dynamic_index_in_dim(logits, true_len - 1, axis=1,
                                            keepdims=False)   # [1, V]
        tok = sample_logits(last, jax.random.fold_in(key, 0),
                            temp, top_k, top_p)
        return tok, varsc["cache"]

    return prefill


@functools.lru_cache(maxsize=32)
def _slot_programs(cfg_tuple, num_slots: int, chunk: int):
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)

    # the engine cache is DONATED through both programs: it is multi-MB
    # (num_slots × block_size × n_embd × 2 × n_layer) and threaded
    # linearly through the step loop — without donation every dispatch
    # memcpys the whole thing, which on CPU dominates the step
    @functools.partial(jax.jit, donate_argnums=(0,))
    def admit(cache, row_cache, slot, true_len):
        """Scatter a freshly prefilled single-row cache into slot ``slot``
        and rewind that slot's integer cursors to ``true_len`` (the
        prefill ran over the PADDED bucket, so its own cursor reads the
        bucket length; pad K/V beyond ``true_len`` stays in the row but is
        causally masked until each position is overwritten by decode)."""
        def leaf(c, n):
            if c.dtype == jnp.int32:     # per-row cursor ('i'/'pos') leaves
                return c.at[slot].set(true_len)
            return c.at[slot].set(n[0])

        return jax.tree.map(leaf, cache, row_cache)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, tok, active, base_keys, gen_idx,
               remaining, eos, temp, top_k, top_p):
        """``chunk`` decode steps for the whole slot batch in ONE
        dispatch (a ``lax.scan``, amortizing per-dispatch overhead the
        way ``generate_fast``'s whole-request scan does). Each scanned
        step feeds every slot its current token and samples its next
        with its own key/params. Slot lifecycle bookkeeping runs ON
        DEVICE so no host round trip is needed mid-chunk: a slot that
        hits EOS or exhausts ``remaining`` flips inactive and freezes —
        its token and integer cursors stop advancing (no cache-overflow
        creep, no garbage emission; its masked compute is the price of
        the fixed shape until the next admit).

        Returns ``(toks [chunk, S], emitted [chunk, S], last_logits
        [S, V], final_tok, final_active, cache)`` — ``emitted`` marks
        which scanned steps each slot was active for; the host replays
        it to route tokens to requests."""
        def body(carry, _):
            cache, tok, act, gidx, rem, _lg = carry
            logits, varsc = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, mutable=["cache"])
            lg = logits[:, 0]                               # [S, V]
            keys = jax.vmap(jax.random.fold_in)(base_keys, gidx)
            nxt = jax.vmap(sample_logits)(lg, keys, temp, top_k, top_p)
            nxt = jnp.where(act, nxt, tok).astype(jnp.int32)
            new_cache = jax.tree.map(
                lambda n, o: jnp.where(act, n, o)
                if n.dtype == jnp.int32 else n,
                varsc["cache"], cache)
            emitted = act
            gidx = jnp.where(act, gidx + 1, gidx)
            rem = jnp.where(act, rem - 1, rem)
            done = act & ((rem <= 0) | ((eos >= 0) & (nxt == eos)))
            # last step's logits ride in the CARRY (teacher-forcing /
            # debug observable) — stacking [chunk, S, V] would move the
            # whole vocab per scanned step at GPT-2 vocab sizes
            return ((new_cache, nxt, act & ~done, gidx, rem, lg),
                    (nxt, emitted))

        lg0 = jnp.zeros((num_slots, cfg.vocab_size), jnp.float32)
        (cache, tok, active, gen_idx, remaining, lg), (toks, emitted) = \
            jax.lax.scan(body,
                         (cache, tok, active, gen_idx, remaining, lg0),
                         None, length=chunk)
        return toks, emitted, lg, tok, active, cache

    return admit, decode


class InferenceEngine:
    """Slot-level mechanics: caches, prefill, the shared decode step.

    Request-level concerns (queueing, backpressure, completion futures)
    live in ``scheduler.Scheduler``; the engine only knows slots. Not
    thread-safe — one driver thread calls ``admit``/``step``/``release``
    (the scheduler serializes access).
    """

    def __init__(self, params: PyTree, config: GPTConfig,
                 num_slots: int = 8, decode_chunk: int = 1):
        """``decode_chunk``: decode steps fused into one dispatch (a
        device-side scan with on-device EOS/max-token bookkeeping).
        1 = purest continuous batching — admission/eviction can happen
        after every token. Larger chunks amortize per-dispatch overhead
        (the lever that beats ``generate_fast``'s whole-request scan on
        throughput) at the cost of slot-turnaround latency: a slot
        finishing mid-chunk frees only at the chunk boundary."""
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if decode_chunk < 1:
            raise ValueError(
                f"decode_chunk must be >= 1, got {decode_chunk}")
        self.config = decode_config(config)
        self.block_size = int(config.block_size)
        self.num_slots = int(num_slots)
        self.decode_chunk = int(decode_chunk)
        self.params = jax.tree.map(jnp.asarray, params)
        self._cfg_tuple = dataclasses.astuple(self.config)
        self._admit_prog, self._decode_prog = _slot_programs(
            self._cfg_tuple, self.num_slots, self.decode_chunk)
        self._step1_prog = None          # lazy chunk-1 twin (teacher forcing)
        self._seen_buckets: set = set()
        self._cache = self._init_cache()
        s = self.num_slots
        self._active = np.zeros(s, bool)
        self._next_tok = np.zeros(s, np.int32)     # input token per slot
        self._gen_idx = np.zeros(s, np.int32)      # key-schedule index
        self._generated = np.zeros(s, np.int64)    # tokens emitted so far
        self._max_new = np.zeros(s, np.int64)
        self._eos = np.full(s, -1, np.int64)       # -1 = disabled
        self._temp = np.ones(s, np.float32)
        self._top_k = np.full(s, self.config.vocab_size, np.int32)
        self._top_p = np.ones(s, np.float32)
        self._base_keys = np.zeros((s, 2), np.uint32)
        self.stats = EngineStats(num_slots=s)
        self.last_logits: Optional[np.ndarray] = None  # [S, V] post-step

    def _init_cache(self) -> PyTree:
        model = GPT(self.config)
        dummy = jnp.zeros((self.num_slots, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda: model.init({"params": jax.random.PRNGKey(0)}, dummy,
                               train=False))
        return jax.tree.map(lambda sh: jnp.zeros(sh.shape, sh.dtype),
                            shapes["cache"])

    # -- slot lifecycle ---------------------------------------------------

    def free_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if not self._active[i]]

    def validate(self, prompt: np.ndarray, sp: SamplingParams) -> None:
        """Typed rejection of requests the decode path cannot serve
        honestly — callers (scheduler.submit, the HTTP handler) fail fast
        with a ValueError instead of poisoning a slot: cache overflow
        (the same error ``generate_fast`` raises), out-of-vocab token ids
        (XLA's gather would silently CLAMP them to vocab_size-1 and serve
        a completion for a prompt the client never sent), and
        non-positive temperature (logits/0 → NaN → garbage tokens;
        greedy decoding is ``top_k=1``, not ``temperature=0``)."""
        prompt = np.asarray(prompt)
        n = int(prompt.size)
        if n < 1:
            raise ValueError("empty prompt")
        if prompt.min() < 0 or prompt.max() >= self.config.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, {self.config.vocab_size})"
                f"; got range [{int(prompt.min())}, {int(prompt.max())}]")
        if sp.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {sp.max_new_tokens}")
        if not sp.temperature > 0:
            raise ValueError(
                f"temperature must be > 0 (got {sp.temperature}); use "
                f"top_k=1 for greedy decoding")
        if n + sp.max_new_tokens > self.block_size:
            raise ValueError(
                f"prompt {n} + {sp.max_new_tokens} new tokens exceeds the "
                f"KV cache (block_size {self.block_size}); crop the prompt "
                f"to block_size - max_new_tokens, or use `generate`, whose "
                f"full-context resampling slides the context window")

    def admit(self, prompt: np.ndarray,
              sp: SamplingParams) -> Tuple[int, TokenEvent]:
        """Prefill ``prompt`` into a free slot and sample its first token.
        Returns ``(slot, event)``; when the first token already finishes
        the request (``max_new_tokens == 1`` or instant EOS) the slot is
        released before returning."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate(prompt, sp)
        free = self.free_slots()
        if not free:
            raise NoFreeSlotError(
                "no free slot — admit() requires one (scheduler bug: "
                "check free_slots() first)")
        slot = free[0]
        fault_point("serve.prefill")
        n = len(prompt)
        bucket = prompt_bucket(n, self.block_size)
        self._seen_buckets.add(bucket)
        # count true program-cache misses: the compile-bound observable is
        # XLA compilations, and a program another engine over the same
        # config already compiled is a hit, not a compile
        before = _prefill_program.cache_info().misses
        prefill = _prefill_program(self._cfg_tuple, bucket)
        if _prefill_program.cache_info().misses > before:
            self.stats.prefill_compiles += 1
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = prompt
        base_key = np.asarray(jax.random.PRNGKey(sp.seed), np.uint32)
        top_k = (self.config.vocab_size if sp.top_k is None
                 else int(sp.top_k))
        top_p = 1.0 if sp.top_p is None else float(sp.top_p)
        tok, row_cache = prefill(
            self.params, jnp.asarray(padded), np.int32(n),
            jnp.asarray(base_key), np.float32(sp.temperature),
            np.int32(top_k), np.float32(top_p))
        self._cache = self._admit_prog(self._cache, row_cache,
                                       np.int32(slot), np.int32(n))
        first = int(np.asarray(tok)[0])
        self.stats.prefills += 1
        self.stats.tokens_generated += 1
        # slot bookkeeping: the first token came from the prefill (key
        # index 0); decode steps continue the schedule at index 1
        self._active[slot] = True
        self._next_tok[slot] = first
        self._gen_idx[slot] = 1
        self._generated[slot] = 1
        self._max_new[slot] = sp.max_new_tokens
        self._eos[slot] = -1 if sp.eos_token is None else int(sp.eos_token)
        self._temp[slot] = sp.temperature
        self._top_k[slot] = top_k
        self._top_p[slot] = top_p
        self._base_keys[slot] = base_key
        finished = (sp.max_new_tokens <= 1
                    or (sp.eos_token is not None and first == sp.eos_token))
        if finished:
            self._active[slot] = False
        self.stats.active_slots = int(self._active.sum())
        self.stats.prefill_buckets = tuple(sorted(self._seen_buckets))
        return slot, TokenEvent(slot, first, finished)

    def release(self, slot: int) -> None:
        """Free a slot between decode steps (EOS/max-tokens eviction or a
        cancelled request). The cache rows stay as-is — the next admit
        overwrites them wholesale."""
        self._active[slot] = False
        self.stats.active_slots = int(self._active.sum())

    def step(self, override_tokens: Optional[Dict[int, int]] = None
             ) -> List[TokenEvent]:
        """Advance every active slot by up to ``decode_chunk`` tokens (one
        dispatch); returns the new tokens in generation order. Slots that
        finish (EOS / max-tokens, decided ON DEVICE mid-chunk) come back
        inactive and are free for the next admit — eviction happens
        between dispatches, admission too: continuous batching at chunk
        granularity.

        ``override_tokens`` (teacher forcing, tests/eval only) replaces a
        slot's INPUT token for ONE single step — the call runs a chunk-1
        program regardless of ``decode_chunk`` and the returned logits
        (``self.last_logits``) are the model's prediction conditioned on
        the forced history, while sampling proceeds normally.
        """
        prog = self._decode_prog
        if override_tokens:
            for slot, tok in override_tokens.items():
                self._next_tok[slot] = int(tok)
            if self.decode_chunk != 1:
                if self._step1_prog is None:
                    _, self._step1_prog = _slot_programs(
                        self._cfg_tuple, self.num_slots, 1)
                prog = self._step1_prog
        if not self._active.any():
            return []
        # hit-counted AFTER the idle early-out so hit N is the Nth REAL
        # decode dispatch — "hang at dispatch 2" reproduces exactly
        fault_point("serve.decode")
        was_active = self._active.copy()
        remaining = (self._max_new - self._generated).astype(np.int32)
        toks, emitted, lg, final_tok, final_active, cache = prog(
            self.params, self._cache, jnp.asarray(self._next_tok),
            jnp.asarray(self._active), jnp.asarray(self._base_keys),
            jnp.asarray(self._gen_idx), jnp.asarray(remaining),
            jnp.asarray(self._eos.astype(np.int32)),
            jnp.asarray(self._temp), jnp.asarray(self._top_k),
            jnp.asarray(self._top_p))
        self._cache = cache
        toks = np.asarray(toks)                    # [chunk, S]
        emitted = np.asarray(emitted)              # [chunk, S] bool
        self.last_logits = np.asarray(lg)
        self._next_tok = np.asarray(final_tok).astype(np.int32).copy()
        self._active = np.asarray(final_active).copy()
        # numerical quarantine: non-finite logits fail ONLY their own
        # slot — the model's per-row cache math keeps rows isolated (and
        # _decode_attend NaN-poisons an overflowing row on purpose, so
        # this is the designated catch point). The check reads the LAST
        # scanned step's logits for every slot that emitted ANYWHERE in
        # this chunk: a poisoned slot that hits max-tokens mid-chunk
        # goes inactive, but its final-step logits still flow from the
        # NaN K/V in its cache rows, so the poison stays visible (NaN
        # never compares equal to EOS, so EOS can't self-evict it
        # either). Slots inactive for the whole chunk are excluded —
        # their garbage compute quarantines no one.
        bad = emitted.any(axis=0) & ~np.isfinite(self.last_logits).all(
            axis=1)
        for slot in np.nonzero(bad)[0]:
            self._active[slot] = False           # quarantine = evict
            self.stats.quarantined += 1
        events: List[TokenEvent] = []
        n_steps = toks.shape[0]
        for k in range(n_steps):
            for slot in np.nonzero(emitted[k])[0]:
                tok = int(toks[k, slot])
                self._gen_idx[slot] += 1
                self._generated[slot] += 1
                # finished iff the device stopped emitting for this slot
                # (its last emitted step) and it came back inactive
                last_emit = not emitted[k + 1:, slot].any()
                finished = bool(last_emit and not self._active[slot])
                events.append(TokenEvent(int(slot), tok, finished,
                                         poisoned=bool(bad[slot])))
        self.stats.tokens_generated += len(events)
        self.stats.decode_steps += int(was_active.any()) * n_steps
        self.stats.active_slots = int(self._active.sum())
        return events
