"""``python -m gym_tpu.serve.worker`` — one fleet replica as a process.

The out-of-process fleet (ROADMAP item 2, ISSUE 13) runs each replica
as a real subprocess: its own interpreter (no shared GIL), its own XLA
client, its own failure domain — a crash or ``kill -9`` takes down ONE
replica, and the router splices the affected streams onto a sibling.
This module is the worker side: it builds exactly the PR-5
engine+scheduler+supervisor stack ``create_server`` builds in-process,
then serves the ``serve/wire.py`` frame protocol over a local AF_UNIX
socket instead of HTTP:

- ``submit`` → ``accepted`` → ``chunk``\\* → ``done`` | ``error`` —
  tokens stream back at decode-chunk granularity (``Request.
  wait_progress``), so the router's first byte waits on the FIRST
  token, not the last. A ``prefix`` on the submit (failover splice) is
  re-derived by the deterministic engine, VERIFIED token-by-token, and
  suppressed from the stream: the router's concatenated client stream
  is byte-identical to an uncontended run.
- ``cancel`` → the request is cancelled at the next decode-chunk
  boundary (``Scheduler.cancel``) and its slot freed — the client-
  disconnect path, end to end.
- ``health`` → ``health_ok`` with the dispatch observables the router
  prices (backlog tokens, per-replica tokens/s EWMA, ``pid``,
  ``programs_compiled``) — the same least-loaded inputs the in-process
  router reads directly.
- ``reload`` → rolling weight hot-swap, worker-local half: pause
  admission, drain in-flight, rebuild the engine from the new params
  snapshot (warm through the program registry — and through the
  persistent tier under ``--program-cache-dir``), resume.
- ``stop`` / SIGTERM → graceful drain (answer in-flight, fail queued
  typed), flush ``serve.csv``, exit 0.

Params arrive either as a checkpoint run dir (``--ckpt``, the
standalone path) or as a pickled numpy tree + config JSON written by
the parent router process (``--params-file``/``--config-json`` — the
fleet-spawn path: one restore in the parent, N cheap loads; the file
lives in the router's private runtime dir, same trust domain as the
socket). With ``--program-cache-dir`` pointing at a warmed registry
tier, a spawned worker deserializes its entire program family and
reports ``programs_compiled=0`` — the property that makes autoscaler
spawns cheap enough to be load-adaptive.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import pickle
import signal
import socket
import sys
import threading
import time
import traceback
from typing import Any, Dict, Optional

import numpy as np

from . import wire


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gym_tpu.serve.worker",
        description="One fleet replica: engine+scheduler+supervisor "
                    "serving the wire protocol over a local socket.")
    p.add_argument("--socket", required=True, metavar="PATH",
                   help="AF_UNIX socket path to listen on (created; an "
                        "existing file is replaced)")
    src = p.add_argument_group("model source (one of)")
    src.add_argument("--ckpt", default=None, metavar="RUN_DIR",
                     help="checkpoint run dir (standalone worker)")
    src.add_argument("--step", type=int, default=None)
    src.add_argument("--config", default=None, metavar="CONFIG_JSON",
                     help="explicit config.json for --ckpt run dirs "
                          "predating the in-dir snapshot")
    src.add_argument("--params-file", default=None, metavar="PKL",
                     help="pickled numpy params tree written by the "
                          "router (fleet spawn path)")
    src.add_argument("--config-json", default=None, metavar="JSON",
                     help="GPTConfig fields as JSON (with --params-file)")
    p.add_argument("--replica-id", type=int, default=0)
    p.add_argument("--num_slots", type=int, default=4)
    p.add_argument("--decode_chunk", type=int, default=1)
    p.add_argument("--page_size", type=int, default=16)
    p.add_argument("--kv_pages", type=int, default=None)
    p.add_argument("--spec_tokens", type=int, default=0)
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--quotas-json", default=None, metavar="JSON",
                   help="per-SLO-class token-rate quotas as JSON, e.g. "
                        "'{\"batch\": {\"share\": 0.5}}' or "
                        "'{\"batch\": {\"tokens_per_s\": 200}}'; "
                        "absent = no quota enforcement (the "
                        "single-tenant default)")
    p.add_argument("--preempt", action="store_true",
                   help="allow parking low-priority decodes at chunk "
                        "boundaries when strictly more urgent work is "
                        "queued and no slot is free")
    p.add_argument("--dispatch-timeout", type=float,
                   default=float(os.environ.get(
                       "GYM_TPU_SERVE_WATCHDOG_S", 120.0)))
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--drain-deadline", type=float, default=300.0)
    p.add_argument("--metrics-dir", default=None,
                   help="this worker's serve.csv dir (default: a "
                        "private temp dir)")
    p.add_argument("--program-cache-dir", default=None,
                   help="persistent program tier (spawned replicas "
                        "start at programs_compiled=0 against a warm "
                        "cache)")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--weights-tag", default=None)
    p.add_argument("--device", default=None,
                   help="'cpu' pins the CPU backend")
    return p


#: Submit-frame fields this worker version understands. Anything else
#: is IGNORED WITH A NOTE, never rejected: a mixed-version fleet (newer
#: router teaching frames new fields, older worker) must degrade to
#: serving the fields it knows — the wire codec already passes unknown
#: fields through, this pins the worker's side of that contract.
_SUBMIT_FIELDS = frozenset({
    "type", "id", "prompt", "sampling", "prefix", "deadline_s",
    "stream", "submit_timeout", "coalesce_s", "tenant", "slo_class",
})


class WorkerReloadError(RuntimeError):
    """A worker-side rolling reload could not complete (drain timeout,
    concurrent reload) — reported typed over the wire; the router maps
    it into its ``FleetReloadError`` surface."""


class WorkerServer:
    """Frame dispatch over accepted connections. One reader thread per
    connection; per-request streamer threads; all writes on a
    connection serialized by its lock (frames interleave, never tear).
    """

    def __init__(self, scheduler, supervisor, metrics, params_box,
                 engine_factory, replica_id: int, *,
                 warmup=None, weights_tag: Optional[str] = None):
        self.scheduler = scheduler
        self.supervisor = supervisor
        self.metrics = metrics
        self.params_box = params_box
        self.engine_factory = engine_factory
        self.replica_id = int(replica_id)
        self.warmup = warmup
        self.stop_event = threading.Event()
        self._reload_lock = threading.Lock()

    # -- observability -----------------------------------------------------

    def health_frame(self) -> Dict[str, Any]:
        from .. import programs as programs_mod
        sched = self.scheduler
        stats = sched.engine.stats    # advisory cross-thread read
        return {
            "type": "health_ok",
            "pid": os.getpid(),
            "replica_id": self.replica_id,
            "dead": self.supervisor.failed is not None,
            "backlog_tokens": sched.backlog_tokens(),
            "backlog_by_class": sched.backlog_tokens_by_class(),
            "preempt": bool(getattr(sched, "preempt", False)),
            "tenants": sched.tenant_snapshot(),
            "queue_depth": sched.queue_depth(),
            "active_requests": sched.active_requests(),
            "active_slots": int(stats.active_slots),
            "num_slots": int(stats.num_slots),
            "tokens_generated": int(stats.tokens_generated),
            "decode_steps": int(stats.decode_steps),
            "prefills": int(stats.prefills),
            "tokens_per_s_ewma": self.metrics.tokens_per_s_ewma(),
            "programs_compiled": programs_mod.xla_compile_counter(),
            "programs_built": programs_mod.compile_counter(),
            "engine_generation": self.supervisor.generation,
            "engine_restarts": self.supervisor.restarts,
            "weights_tag": self.params_box.get("tag"),
            "warmup": (self.warmup.stats()
                       if self.warmup is not None else None),
        }

    # -- per-connection serving --------------------------------------------

    def serve_connection(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        live: Dict[Any, Any] = {}      # request id -> scheduler Request
        # cancels that arrived BEFORE their submit registered (the
        # submit can block in Scheduler.submit for seconds under a full
        # queue — exactly when clients give up): applied the moment the
        # request exists instead of silently dropped
        cancelled: set = set()

        def send(frame: Dict[str, Any]) -> bool:
            try:
                with wlock:
                    wire.send_frame(conn, frame)
                return True
            except (OSError, wire.WireError):
                return False           # router gone; streamers cancel

        send({"type": "hello", "pid": os.getpid(),
              "replica_id": self.replica_id,
              **{k: v for k, v in self.health_frame().items()
                 if k != "type"}})
        reg_lock = threading.Lock()   # live/cancelled registration —
        #                               closes the cancel-vs-submit race
        graceful = False
        try:
            while not self.stop_event.is_set():
                try:
                    frame = wire.recv_frame(conn)
                except OSError:
                    return         # connection torn down under us
                except wire.WireError as e:
                    sys.stderr.write(
                        f"gym_tpu.serve.worker: protocol error from "
                        f"router — {type(e).__name__}: {e}; closing "
                        f"connection\n")
                    return
                if frame is None:
                    return             # router closed cleanly
                ftype = frame["type"]
                if ftype == "submit":
                    threading.Thread(
                        target=self._stream_request,
                        args=(frame, send, live, cancelled, reg_lock),
                        name=f"worker-stream-{frame.get('id')}",
                        daemon=True).start()
                elif ftype == "cancel":
                    with reg_lock:
                        req = live.get(frame.get("id"))
                        if req is None:
                            cancelled.add(frame.get("id"))
                    if req is not None:
                        self.scheduler.cancel(req)
                elif ftype == "health":
                    send(self.health_frame())
                elif ftype == "stats":
                    send({"type": "stats_ok", "id": frame.get("id"),
                          "headline": self.metrics.headline(),
                          **{k: v for k, v in self.health_frame().items()
                             if k != "type"}})
                elif ftype == "reload":
                    threading.Thread(
                        target=self._reload, args=(frame, send),
                        name="worker-reload", daemon=True).start()
                elif ftype == "stop":
                    send({"type": "stop_ok", "id": frame.get("id")})
                    graceful = True
                    self.stop_event.set()
                    return
                # unknown-but-valid types: ignore (forward compat)
        finally:
            # router connection GONE (not a graceful stop): its clients
            # are unreachable — cancel every stream it owned at the
            # next chunk boundary. A stop frame instead leaves them
            # running for the main drain (answer in-flight, like the
            # in-process Router.close contract).
            if not graceful:
                for req in list(live.values()):
                    self.scheduler.cancel(req,
                                          reason="router disconnected")

    def _stream_request(self, frame: Dict[str, Any], send, live,
                        cancelled, reg_lock) -> None:
        rid = frame.get("id")
        unknown = sorted(set(frame) - _SUBMIT_FIELDS)
        if unknown:
            # ignored-with-note, never rejected: the router may be a
            # newer version teaching submit frames new fields
            sys.stderr.write(
                f"gym_tpu.serve.worker: submit {rid} carries unknown "
                f"fields {unknown} — ignoring them (newer router?), "
                f"serving the fields this worker understands\n")
        try:
            prompt = np.asarray(frame["prompt"], np.int32).reshape(-1)
            sp = wire.sampling_from_dict(frame.get("sampling") or {})
            prefix = [int(t) for t in (frame.get("prefix") or [])]
            deadline_s = frame.get("deadline_s")
            req = self.scheduler.submit(
                prompt, sp, block=True,
                timeout=float(frame.get("submit_timeout", 30.0)),
                deadline_s=(None if deadline_s is None
                            else float(deadline_s)),
                tenant=frame.get("tenant"),
                slo_class=frame.get("slo_class"))
        except Exception as e:  # noqa: BLE001 — typed over the wire;
            # the router maps it back to the same class
            with reg_lock:
                cancelled.discard(rid)   # an early cancel for a never-
                #                          registered request must not
                #                          linger in the set
            send(wire.exception_to_frame(rid, e))
            return
        with reg_lock:
            live[rid] = req
            was_cancelled = rid in cancelled
            cancelled.discard(rid)
        if was_cancelled:
            # the cancel beat the registration: apply it now
            self.scheduler.cancel(req, reason="cancelled before admit")
        if not send({"type": "accepted", "id": rid}):
            self.scheduler.cancel(req, reason="router disconnected")
            live.pop(rid, None)
            return
        streaming = bool(frame.get("stream", True))
        # after the FIRST chunk (TTFB is sacred), coalesce subsequent
        # tokens for a few ms per frame: at full decode rate this
        # batches tokens-per-frame instead of paying frame+wakeup cost
        # per token — the difference between a streaming fleet that
        # matches the in-process one and one that loses half its
        # throughput to chunk overhead
        coalesce = float(frame.get("coalesce_s", 0.02))
        try:
            seen = 0
            sent_any = False
            while True:
                if not streaming:
                    # result-only request: no chunk frames at all, and
                    # no per-token wakeups either — wait on the
                    # TERMINAL event itself (the progress Condition
                    # broadcasts every token; a streamer parked on it
                    # would burn a GIL slice per token for nothing)
                    if req._event.wait(timeout=1.0):
                        break
                    continue
                snapshot, terminal = req.wait_progress(seen, timeout=1.0)
                if (not terminal and sent_any and coalesce > 0
                        and len(snapshot) > seen):
                    time.sleep(coalesce)
                    snapshot, terminal = req.wait_progress(seen, 0.0)
                if len(snapshot) > seen:
                    # failover splice: verify the replayed prefix (the
                    # engine is deterministic — a mismatch means the
                    # fleet is NOT serving one model; fail typed, never
                    # ship a corrupted stream), ship only what follows
                    for i in range(seen, min(len(snapshot), len(prefix))):
                        if snapshot[i] != prefix[i]:
                            self.scheduler.cancel(
                                req, reason="splice mismatch")
                            send(wire.exception_to_frame(
                                rid, _splice_mismatch(i, prefix[i],
                                                      snapshot[i])))
                            return
                    start = max(seen, len(prefix))
                    if len(snapshot) > start:
                        if not send({"type": "chunk", "id": rid,
                                     "tokens": snapshot[start:]}):
                            self.scheduler.cancel(
                                req, reason="router disconnected")
                            return
                        sent_any = True
                    seen = len(snapshot)
                if terminal:
                    break
            from .scheduler import RequestFailedError, RequestStatus
            if req.status is RequestStatus.DONE:
                done = {"type": "done", "id": rid,
                        "tokens_total": len(req.tokens),
                        "new_tokens": len(req.tokens) - len(prefix),
                        "ttft_s": req.ttft_s,
                        "avg_token_latency_s": req.avg_token_latency_s}
                if not streaming:
                    # verify the prefix even result-only (splice
                    # correctness holds on every path)
                    toks = list(req.tokens)
                    if toks[:len(prefix)] != prefix:
                        bad = next(
                            (i for i, want in enumerate(prefix)
                             if i >= len(toks) or toks[i] != want),
                            0)
                        send(wire.exception_to_frame(
                            rid, _splice_mismatch(
                                bad, prefix[bad],
                                toks[bad] if bad < len(toks) else -1)))
                        return
                    done["tokens"] = toks[len(prefix):]
                send(done)
            else:
                send(wire.exception_to_frame(
                    rid, req.exception
                    or RequestFailedError(req.error or "failed")))
        finally:
            live.pop(rid, None)

    def _reload(self, frame: Dict[str, Any], send) -> None:
        """Worker half of the rolling hot-swap: drain, rebuild warm,
        swap, resume — the same sequence ``Router.reload`` runs against
        an in-process replica, driven over the wire."""
        rid = frame.get("id")
        t0 = time.perf_counter()
        if not self._reload_lock.acquire(blocking=False):
            send(wire.exception_to_frame(rid, WorkerReloadError(
                "a reload is already in progress on this worker")))
            return
        try:
            with open(frame["params_file"], "rb") as f:
                params = pickle.load(f)
            self.params_box["params"] = params
            if frame.get("tag") is not None:
                self.params_box["tag"] = frame["tag"]
            self.scheduler.pause_admission()
            try:
                deadline = (time.perf_counter()
                            + float(frame.get("drain_timeout_s", 300.0)))
                while (self.scheduler.inflight()
                       and self.supervisor.failed is None):
                    if time.perf_counter() > deadline:
                        raise WorkerReloadError(
                            "worker did not drain within the reload "
                            "drain_timeout_s bound")
                    time.sleep(0.002)
                engine = self.engine_factory()
                self.scheduler.replace_engine(engine)
                self.metrics.engine_reloaded()
            finally:
                self.scheduler.resume_admission()
            send({"type": "reload_ok", "id": rid,
                  "tag": self.params_box.get("tag"),
                  "wall_s": round(time.perf_counter() - t0, 3)})
        except Exception as e:  # noqa: BLE001 — reload failures are
            # the router's problem, typed; the worker keeps serving
            sys.stderr.write(
                f"gym_tpu.serve.worker: reload failed:\n"
                f"{traceback.format_exc()}")
            send(wire.exception_to_frame(rid, e))
        finally:
            self._reload_lock.release()


def _splice_mismatch(i: int, want: int, got: int) -> BaseException:
    from .scheduler import EngineFailedError
    return EngineFailedError(
        f"failover splice verification failed: replayed token {i} is "
        f"{got}, client already received {want} — replicas are not "
        f"serving identical models")


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")

    # Per-replica fault arming (SDC drills): GYM_TPU_FAULTS is process-
    # global, so a spawner env would arm EVERY replica — corrupting the
    # failover target along with the victim. A replica-suffixed spec
    # arms just this worker.
    per_replica = os.environ.get(
        f"GYM_TPU_FAULTS_REPLICA_{args.replica_id}")
    if per_replica:
        from ..utils.resilience import faults
        faults.configure(per_replica)
        sys.stderr.write(
            f"gym_tpu.serve.worker: replica {args.replica_id} armed "
            f"with faults: {per_replica}\n")

    from .. import programs as programs_mod
    if args.program_cache_dir or os.environ.get(
            "GYM_TPU_PROGRAM_CACHE_DIR"):
        resolved = programs_mod.enable_disk_tier(args.program_cache_dir)
        sys.stderr.write(
            f"gym_tpu.serve.worker: program registry disk tier at "
            f"{resolved}\n")

    from ..models.nanogpt import GPTConfig
    from .engine import InferenceEngine
    from .metrics import ServeMetrics
    from .scheduler import Scheduler
    from .supervisor import Supervisor

    if args.params_file:
        if not args.config_json:
            print("gym_tpu.serve.worker: --params-file needs "
                  "--config-json", file=sys.stderr)
            return 1
        with open(args.params_file, "rb") as f:
            params = pickle.load(f)
        with open(args.config_json) as f:
            raw = json.load(f)
        fields = {f.name for f in dataclasses.fields(GPTConfig)}
        cfg = GPTConfig(**{k: v for k, v in raw.items() if k in fields})
    elif args.ckpt:
        from .load import load_for_serving
        params, cfg, info = load_for_serving(
            args.ckpt, step=args.step, config_path=args.config)
        if args.weights_tag is None and info.get("step") is not None:
            args.weights_tag = f"step-{info['step']}"
    else:
        print("gym_tpu.serve.worker: need --ckpt or "
              "--params-file/--config-json", file=sys.stderr)
        return 1

    page_size = args.page_size
    if page_size and cfg.block_size % page_size:
        page_size = 0
    paged = page_size > 0

    metrics_dir = args.metrics_dir
    if metrics_dir is None:
        import tempfile
        metrics_dir = tempfile.mkdtemp(
            prefix=f"gym_tpu_worker{args.replica_id}_")
    metrics = ServeMetrics(metrics_dir)

    box: Dict[str, Any] = {"params": params, "tag": args.weights_tag}

    def factory():
        return InferenceEngine(
            box["params"], cfg, num_slots=args.num_slots,
            decode_chunk=args.decode_chunk, paged=paged,
            page_size=page_size or 16, kv_pages=args.kv_pages,
            spec_tokens=args.spec_tokens if paged else 0,
            weights_tag=box.get("tag"))

    quotas = None
    if args.quotas_json:
        from .scheduler import ClassQuota
        quotas = {cls: ClassQuota(**spec)
                  for cls, spec in json.loads(args.quotas_json).items()}
    sched = Scheduler(factory(), max_queue=args.max_queue,
                      metrics=metrics, quotas=quotas,
                      preempt=args.preempt)
    sup = Supervisor(sched, factory,
                     dispatch_timeout_s=args.dispatch_timeout,
                     max_restarts=args.max_restarts, metrics=metrics,
                     log=lambda *a, **k: print(
                         *a, file=sys.stderr,
                         **{k_: v for k_, v in k.items()
                            if k_ != "flush"}, flush=True))
    sup.start()
    warm = None
    if not args.no_warmup:
        warm = programs_mod.warm_engine_programs(
            sched.engine, log=sys.stderr.write)

    server = WorkerServer(sched, sup, metrics, box, factory,
                          args.replica_id, warmup=warm,
                          weights_tag=args.weights_tag)

    sock_path = args.socket
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(4)
    listener.settimeout(0.25)

    def _on_term(signum, frame):
        server.stop_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_term)

    sys.stderr.write(
        f"gym_tpu.serve.worker: ready — replica {args.replica_id} "
        f"pid {os.getpid()} on {sock_path} "
        f"({args.num_slots} slots, "
        f"{'paged' if paged else 'unpaged'} kv)\n")
    sys.stderr.flush()

    conns: list = []
    ppid0 = os.getppid()
    try:
        while not server.stop_event.is_set():
            if os.getppid() != ppid0:
                # the router process died (crash, kill -9, a bench that
                # never reached close()): a worker must NEVER outlive
                # its parent — drain and exit instead of leaking
                sys.stderr.write(
                    f"gym_tpu.serve.worker: parent {ppid0} is gone — "
                    f"shutting down\n")
                break
            try:
                conn, _ = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=server.serve_connection,
                                 args=(conn,),
                                 name="worker-conn", daemon=True)
            t.start()
            conns.append((conn, t))
    finally:
        listener.close()
        try:
            os.unlink(sock_path)
        except OSError:
            pass
        # graceful drain, exactly the serve __main__ SIGTERM sequence:
        # answer in-flight, fail queued typed, flush the CSV, exit 0
        if warm is not None:
            warm.stop()
            warm.join(timeout=120.0)
        if sup.stop(join_timeout_s=args.drain_deadline):
            sched.shutdown(finish_running=True,
                           deadline_s=args.drain_deadline)
        else:
            from ..utils.resilience import dump_thread_stacks
            sys.stderr.write(dump_thread_stacks(
                f"gym_tpu.serve.worker: driver wedged past the "
                f"{args.drain_deadline:.0f}s drain deadline:"))
            sched.shutdown(finish_running=False, deadline_s=0.0)
        for conn, _t in conns:
            try:
                conn.close()
            except OSError:
                pass
        metrics.sync()
        head = metrics.headline()
        sys.stderr.write(
            f"gym_tpu.serve.worker: replica {args.replica_id} shut "
            f"down cleanly — {head['requests_done']} done, "
            f"{head['requests_failed']} failed, "
            f"tokens_per_s={head['tokens_per_s']}\n")
        metrics.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
