"""``python -m gym_tpu.serve --ckpt <run_dir>`` — stdlib-HTTP serving.

No framework: ``http.server.ThreadingHTTPServer`` + the scheduler under
an engine ``Supervisor``. One driver thread runs the engine loop inside
a watchdog; handler threads submit and block on the request future.
Endpoints:

- ``POST /generate`` — JSON body with either ``prompt`` (a list of token
  ids) or ``text`` (char-level corpora only: encoded via the shakespeare
  ``CHAR_VOCAB``), plus optional ``max_new_tokens`` / ``temperature`` /
  ``top_k`` / ``top_p`` / ``eos_token`` / ``seed`` / ``deadline_s``.
  ``deadline_s`` (also settable per request via the ``X-Deadline-S``
  header; the body field wins) bounds the request end to end: admission
  control rejects it up front (HTTP 429 + ``Retry-After``) when the
  live tokens/s EWMA says the backlog cannot meet it; a queued request
  past deadline is shed before prefill and a running one cancelled at
  the next chunk boundary (HTTP 504, typed). Replies with the new
  ``tokens`` (and ``text`` when the vocab is char-level), TTFT and
  per-token latency.
- ``GET /stats`` (alias ``/healthz``) — engine + metrics headline JSON,
  including supervisor state (engine generation / restarts) and, with
  ``--replicas N``, the fleet view: per-replica health/EWMA/weights
  sections, ``failovers``, ``healthy_replicas``, ``weight_reloads``
  (rolling ROLLOUTS; the collector's ``engine_reloads`` counts
  per-replica engine swaps — one rollout × N replicas).
- ``POST /reload`` — zero-downtime weight hot-swap: re-reads the
  checkpoint run dir (optionally ``{"ckpt": ..., "step": ...}``) and
  rolls the new params through the replicas one at a time (drain →
  warm rebuild through the global program LRUs → re-admit) without
  dropping an in-flight request. ``--reload-watch S`` does the same
  automatically whenever the trainer commits a newer checkpoint.

``--replicas N`` runs N in-process engine+scheduler+supervisor stacks
behind the health-aware router (``serve/router.py``): least-loaded +
prefix-cache-affine dispatch, and a replica that dies mid-request has
the request transparently retried on a sibling under its remaining
deadline — the client sees 200, ``/stats`` sees ``failovers``.

Typed failure → status mapping (never a traceback-500 for a fault the
serving stack understands):

====================== ======================================
400                     malformed JSON / bad params / prompt
                        too long (typed ``ValueError`` body)
429 + ``Retry-After``   queue full, admission-control reject
503 + ``Retry-After``   shutting down, engine failed/rebuilt,
                        slot quarantined (NaN), injected IO
504                     deadline exceeded (shed or cancelled)
====================== ======================================

Shutdown drill (ISSUE 4 acceptance): SIGTERM/SIGINT triggers a graceful
drain — stop accepting, FAIL queued requests (typed, reported to their
waiting handlers, never dropped), ANSWER in-flight requests (the engine
keeps stepping until the running slots finish, bounded by
``--drain-deadline``), close the listener, flush ``serve.csv``, print a
final ``tokens_per_s`` headline, exit 0. A wedged drain dumps every
thread's stack (``utils.resilience.dump_thread_stacks``) instead of
hanging silently.

Chaos drill (ISSUE 5 acceptance, ``scripts/ci_chaos.sh``): with
``GYM_TPU_FAULTS=serve.decode:hang@…`` injected the supervisor abandons
the wedged driver, fails in-flight requests typed (503, inside their
deadline), rebuilds the engine warm and keeps serving — the HTTP server
never dies with its engine.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gym_tpu.serve",
        description="Serve a trained gym_tpu checkpoint over HTTP "
                    "(continuous-batching KV-cache decode).")
    p.add_argument("--ckpt", required=True, metavar="RUN_DIR",
                   help="checkpoint run dir: fit(save_dir=...)/<run_name>")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest valid)")
    p.add_argument("--config", default=None, metavar="CONFIG_JSON",
                   help="explicit config.json (for run dirs predating the "
                        "in-dir snapshot: logs/<run_name>/config.json)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num_slots", type=int, default=4,
                   help="concurrent decode slots (the batch width)")
    p.add_argument("--replicas", type=int, default=1,
                   help="engine replicas behind the health-aware router "
                        "(fleet serving: failover + rolling weight "
                        "hot-swap need >= 2)")
    p.add_argument("--out-of-process", action="store_true",
                   help="run each replica as a worker SUBPROCESS over a "
                        "local socket (its own GIL, its own failure "
                        "domain) instead of an in-process thread stack; "
                        "responses can stream and a killed replica "
                        "process splices mid-stream onto a sibling")
    p.add_argument("--autoscale", action="store_true",
                   help="with --out-of-process: spawn/retire replica "
                        "processes from the live per-replica tokens/s "
                        "EWMAs and backlog (bounds: --min-replicas/"
                        "--max-replicas); also respawns killed workers")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="autoscaler floor (default: --replicas)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscaler ceiling (default: "
                        "max(--replicas, 4))")
    p.add_argument("--autoscale-interval", type=float, default=1.0,
                   help="autoscaler tick interval in seconds")
    p.add_argument("--worker-startup-timeout", type=float, default=240.0,
                   help="seconds to wait for spawned worker processes "
                        "to come healthy at startup")
    p.add_argument("--failover-retries", type=int, default=None,
                   help="per-request failover re-dispatch budget "
                        "(default: min(2, replicas-1) — a single "
                        "replica keeps the PR-5 typed-503 behavior)")
    p.add_argument("--reload-watch", type=float, default=0.0,
                   help="poll the checkpoint run dir every S seconds "
                        "and hot-swap newer checkpoints into the fleet "
                        "(0 = off; POST /reload always works)")
    p.add_argument("--decode_chunk", type=int, default=1,
                   help="decode steps fused per dispatch (chunk boundary "
                        "= deadline-cancellation granularity)")
    p.add_argument("--page_size", type=int, default=16,
                   help="paged KV cache page size in tokens (must divide "
                        "block_size; 0 reverts to the unpaged per-slot "
                        "cache). Paging enables copy-free prefix sharing "
                        "across requests")
    p.add_argument("--kv_pages", type=int, default=None,
                   help="physical pages in the paged KV pool (default: "
                        "null page + num_slots full windows; smaller "
                        "pools admit lazily as blocks free)")
    p.add_argument("--spec_tokens", type=int, default=0,
                   help="speculative decoding draft length γ (0 = off; "
                        "paged only). Token streams stay exactly equal "
                        "to non-speculative decoding")
    p.add_argument("--quant", choices=("int8", "int4"), default=None,
                   help="quantize the restored params at load: per-tile "
                        "int8/int4 + f32 scales (QuantizeCodec tiling), "
                        "dequant fused into the consuming matmuls. "
                        "Embedding/lm_head stay f32 unless "
                        "--quant-embed. Default: f32 (no quantization)")
    p.add_argument("--quant-embed", action="store_true",
                   help="with --quant: also quantize the tied "
                        "embedding/lm_head (they dominate quality — "
                        "gated separately)")
    p.add_argument("--kv-quant", choices=("int8",), default=None,
                   help="store the decode KV cache/page pools int8 with "
                        "per-(page-slot, head) f32 scales — the same "
                        "kv_pages budget holds 4x the resident KV "
                        "payload. Default: f32")
    p.add_argument("--max_queue", type=int, default=64,
                   help="FCFS queue bound (backpressure: submits beyond "
                        "it wait, then 429)")
    p.add_argument("--quotas", default=None, metavar="JSON",
                   help="per-SLO-class token-rate quotas as JSON, e.g. "
                        "'{\"batch\": {\"share\": 0.5}}' or "
                        "'{\"interactive\": {\"tokens_per_s\": 500}}' "
                        "(share = fraction of the live tokens/s EWMA; "
                        "exceeding the refill bucket -> 429 + "
                        "Retry-After). Default: no quotas — the "
                        "single-tenant behavior")
    p.add_argument("--preempt", action="store_true",
                   help="preemptible decode: park a low-priority "
                        "running request at a chunk boundary when a "
                        "strictly more urgent one is queued and no slot "
                        "is free; the parked stream resumes "
                        "byte-identical")
    p.add_argument("--request_timeout", type=float, default=600.0,
                   help="per-request wall-clock bound inside a handler")
    p.add_argument("--default-deadline", type=float, default=None,
                   help="deadline_s applied to requests that don't set "
                        "one (default: none)")
    p.add_argument("--dispatch-timeout", type=float,
                   default=float(os.environ.get(
                       "GYM_TPU_SERVE_WATCHDOG_S", 120.0)),
                   help="supervisor watchdog: a dispatch wedged past this "
                        "triggers engine failover (env "
                        "GYM_TPU_SERVE_WATCHDOG_S)")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="engine rebuilds before the supervisor declares "
                        "the engine unrecoverable")
    p.add_argument("--drain-deadline", type=float, default=300.0,
                   help="SIGTERM: max seconds to finish in-flight "
                        "requests before failing them")
    p.add_argument("--metrics_dir", default=None,
                   help="serve.csv location (default: <RUN_DIR>/serve)")
    p.add_argument("--program-cache-dir", default=None,
                   help="enable the device-program registry's persistent "
                        "executable tier at this directory (or set "
                        "GYM_TPU_PROGRAM_CACHE_DIR): a restart against "
                        "the same config deserializes every program "
                        "instead of compiling — /stats "
                        "programs_compiled stays 0")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the background AOT program warmup at "
                        "startup (cold requests then pay compiles "
                        "on-path — the pre-registry behavior)")
    p.add_argument("--device", default=None,
                   help="'cpu' pins the CPU backend (skips accelerator "
                        "plugin init)")
    return p


@dataclasses.dataclass
class ServerHandle:
    """Everything a caller (main() or an in-process test) needs to drive
    and tear down one serving stack. ``scheduler``/``supervisor``/
    ``engine_factory`` are replica 0's (the pre-fleet surface, kept so
    single-replica callers and tests read exactly what they always
    did); ``router`` is the fleet."""

    httpd: ThreadingHTTPServer
    scheduler: Any
    supervisor: Any
    metrics: Any
    engine_factory: Any
    info: Dict[str, Any]
    router: Any = None
    warmup: Any = None
    autoscaler: Any = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def stop_warmup(self) -> None:
        """Stop AND join the background warmup before teardown: the
        warmup daemon thread may be inside an XLA compile/deserialize —
        interpreter teardown while C++ holds that thread aborts the
        process (SIGABRT after the clean-shutdown line; the ci_serve
        restart drill caught it). stop() bounds the wait to the one
        in-flight build. Shared by close() and main()'s SIGTERM drain
        so the invariant cannot drift between the two paths."""
        if self.warmup is not None:
            self.warmup.stop()
            self.warmup.join(timeout=120.0)

    def close(self, drain_deadline_s: float = 30.0) -> None:
        """Test-path teardown: stop every replica's driver, drain it
        (wedged replicas get their stacks dumped and their requests
        failed typed — handler threads blocked in result() must not pin
        server_close open), close sockets. Process fleets additionally
        stop the autoscaler first (no respawns during teardown) and
        reap every worker child."""
        self.stop_warmup()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.router.close(drain_deadline_s=drain_deadline_s)
        self.httpd.shutdown()
        self.httpd.server_close()
        self.metrics.close()


def create_server(params, cfg, *, host: str = "127.0.0.1", port: int = 0,
                  num_slots: int = 4, decode_chunk: int = 1,
                  max_queue: int = 64, request_timeout: float = 600.0,
                  default_deadline: Optional[float] = None,
                  dispatch_timeout: float = 120.0, max_restarts: int = 5,
                  metrics_dir: Optional[str] = None,
                  info: Optional[Dict[str, Any]] = None,
                  stop_event: Optional[threading.Event] = None,
                  page_size: int = 16, kv_pages: Optional[int] = None,
                  spec_tokens: int = 0, replicas: int = 1,
                  failover_retries: Optional[int] = None,
                  reload_source: Optional[Any] = None,
                  warmup: bool = True,
                  program_cache_dir: Optional[str] = None,
                  out_of_process: bool = False,
                  autoscale: bool = False,
                  min_replicas: Optional[int] = None,
                  max_replicas: Optional[int] = None,
                  autoscale_policy: Optional[Any] = None,
                  autoscale_interval_s: float = 1.0,
                  fleet_dir: Optional[str] = None,
                  worker_startup_timeout_s: float = 240.0,
                  worker_env: Optional[Dict[str, str]] = None,
                  quotas: Optional[Dict[str, Any]] = None,
                  preempt: bool = False
                  ) -> ServerHandle:
    """Build the full serving stack — replica fleet (engines, schedulers,
    supervisors, router), metrics, HTTP server — WITHOUT entering
    ``serve_forever``. ``main`` and the in-process chaos tests share
    this path, so what the tests exercise is exactly what
    ``python -m gym_tpu.serve`` runs. ``port=0`` binds an ephemeral
    port (``handle.port`` reports it). ``reload_source(body) ->
    (params, weights_tag)`` supplies ``POST /reload``'s checkpoint
    re-read (absent: /reload answers 400; ``Router.reload`` still works
    programmatically).

    ``warmup=True`` starts a background thread precompiling the fleet's
    COMPLETE program family (all power-of-two prefill buckets + the
    decode/admit or paged/spec programs) through the device-program
    registry before traffic needs them — cold-start p99 TTFT pays no
    compiles.  ``program_cache_dir`` (or ``GYM_TPU_PROGRAM_CACHE_DIR``)
    additionally enables the registry's persistent executable tier: a
    restart against the same config deserializes every program instead
    of compiling (``/stats`` → ``programs_compiled`` stays 0, pinned by
    the ``scripts/ci_serve.sh`` restart drill)."""
    from ..data.build_dataset import CHAR_VOCAB
    from ..utils.checkpoint import CheckpointNotFoundError
    from ..utils.resilience import fault_point
    from .autoscale import AutoscalePolicy, Autoscaler
    from .engine import SamplingParams
    from .metrics import ServeMetrics
    from .router import (FleetReloadError, NoHealthyReplicaError,
                         build_fleet, build_process_fleet)
    from .scheduler import (AdmissionRejectedError, DeadlineExceededError,
                            EngineFailedError, QueueFullError,
                            RequestCancelledError, SchedulerClosedError,
                            SlotQuarantinedError)

    info = dict(info or {"step": None, "num_nodes": None})
    stop = stop_event or threading.Event()
    if metrics_dir is None:
        # per-instance dir: a fixed shared default would interleave two
        # servers' rows in one append-mode serve.csv
        import tempfile
        metrics_dir = tempfile.mkdtemp(prefix="gym_tpu_serve_")

    if page_size and cfg.block_size % page_size:
        # a page size that doesn't divide this checkpoint's window can't
        # page — serve unpaged rather than refuse the checkpoint
        sys.stderr.write(
            f"gym_tpu.serve: page_size {page_size} does not divide "
            f"block_size {cfg.block_size} — serving unpaged"
            + (", speculative decoding disabled (it requires the paged "
               "cache)" if spec_tokens else "") + "\n")
        page_size = 0
    paged = page_size > 0
    if spec_tokens and not paged:
        sys.stderr.write(
            "gym_tpu.serve: --spec_tokens requires the paged cache "
            "(--page_size > 0) — speculative decoding disabled\n")

    from .. import programs as programs_mod
    if program_cache_dir or os.environ.get("GYM_TPU_PROGRAM_CACHE_DIR"):
        resolved = programs_mod.enable_disk_tier(program_cache_dir)
        sys.stderr.write(
            f"gym_tpu.serve: program registry disk tier at {resolved}\n")

    metrics = ServeMetrics(metrics_dir)
    weights_tag = (f"step-{info['step']}"
                   if info.get("step") is not None else None)
    autoscaler = None
    warm_thread = None
    if out_of_process:
        # process fleet: each replica is a worker SUBPROCESS speaking
        # the wire protocol over a unix socket in a private runtime
        # dir; the parent materializes the params snapshot once and
        # every worker loads it (and warms ITSELF — with a persistent
        # --program-cache-dir a spawned worker deserializes its whole
        # program family: programs_compiled=0)
        import tempfile
        base = fleet_dir or tempfile.mkdtemp(prefix="gym_tpu_fleet_")
        router = build_process_fleet(
            params, cfg, base, replicas=replicas, num_slots=num_slots,
            decode_chunk=decode_chunk,
            page_size=(page_size or 16) if paged else 0,
            kv_pages=kv_pages,
            spec_tokens=spec_tokens if paged else 0,
            max_queue=max_queue, metrics=metrics,
            dispatch_timeout_s=dispatch_timeout,
            max_restarts=max_restarts, max_failovers=failover_retries,
            weights_tag=weights_tag,
            program_cache_dir=program_cache_dir,
            no_warmup=not warmup, device=None, env=worker_env,
            quotas=quotas, preempt=preempt,
            log=lambda *a, **k: print(*a, file=sys.stderr, flush=True))
        router.start()
        router.wait_ready(n=replicas,
                          timeout_s=worker_startup_timeout_s)
        if autoscale:
            lo = replicas if min_replicas is None else int(min_replicas)
            hi = (max(replicas, 4) if max_replicas is None
                  else int(max_replicas))
            if autoscale_policy is not None:
                # an explicit policy supplies the watermark/patience
                # knobs; EXPLICIT replica-bound arguments still win (a
                # caller asking for min_replicas=2 must never scale
                # below 2 because the policy object defaulted to 1)
                policy = dataclasses.replace(
                    autoscale_policy,
                    min_replicas=(int(min_replicas)
                                  if min_replicas is not None
                                  else autoscale_policy.min_replicas),
                    max_replicas=(int(max_replicas)
                                  if max_replicas is not None
                                  else autoscale_policy.max_replicas))
            else:
                policy = AutoscalePolicy(min_replicas=lo,
                                         max_replicas=hi)
            autoscaler = Autoscaler(
                router, policy,
                interval_s=autoscale_interval_s,
                metrics=metrics,   # ISSUE 15: per-tick audit rows
                log=lambda *a, **k: print(*a, file=sys.stderr,
                                          flush=True)).start()
        sched = sup = None
    else:
        # the params live in memory (restored from the checkpoint at
        # startup); the process-wide device-program registry makes every
        # replica's engine — and any failover/hot-swap rebuild — warm:
        # same config, no recompiles
        router = build_fleet(
            params, cfg, replicas=replicas, num_slots=num_slots,
            decode_chunk=decode_chunk, paged=paged,
            page_size=page_size or 16, kv_pages=kv_pages,
            spec_tokens=spec_tokens if paged else 0, max_queue=max_queue,
            metrics=metrics, dispatch_timeout_s=dispatch_timeout,
            max_restarts=max_restarts, max_failovers=failover_retries,
            weights_tag=weights_tag, quotas=quotas, preempt=preempt)
        rep0 = router.replicas[0]
        sched, sup = rep0.scheduler, rep0.supervisor
        if warmup:
            # background AOT warmup over ONE replica's program family —
            # all replicas share config, so one pass warms the whole
            # fleet (and any future failover rebuild / hot-swap
            # generation) through the shared registry; a request
            # arriving mid-warmup single-flights into the same build
            # instead of compiling twice
            warm_thread = programs_mod.warm_engine_programs(
                rep0.scheduler.engine, log=sys.stderr.write)
    char_level = cfg.vocab_size <= len(CHAR_VOCAB) + 1

    def agg_tenant_snapshots(snaps):
        """Fold per-replica ``tenant_snapshot``s into one /stats
        ``tenants`` block: counters sum; per-class quota fill reports
        the MOST CONSTRAINED replica (min — the fill a client's next
        request actually prices against on the worst-placed replica)."""
        agg: Dict[str, Any] = {"preemptions": 0, "resumes": 0,
                               "parked": 0, "quota_rejections": {},
                               "quota_fill": {}, "backlog_by_class": {}}
        for s in snaps:
            if not s:
                continue
            agg["preemptions"] += int(s.get("preemptions", 0) or 0)
            agg["resumes"] += int(s.get("resumes", 0) or 0)
            agg["parked"] += int(s.get("parked", 0) or 0)
            for k, v in (s.get("quota_rejections") or {}).items():
                agg["quota_rejections"][k] = (
                    agg["quota_rejections"].get(k, 0) + int(v or 0))
            for k, v in (s.get("backlog_by_class") or {}).items():
                agg["backlog_by_class"][k] = (
                    agg["backlog_by_class"].get(k, 0) + int(v or 0))
            for k, v in (s.get("quota_fill") or {}).items():
                if v is None:
                    agg["quota_fill"].setdefault(k, None)
                else:
                    prev = agg["quota_fill"].get(k)
                    agg["quota_fill"][k] = (float(v) if prev is None
                                            else min(prev, float(v)))
        return agg

    def encode_text(text: str):
        table = {c: i for i, c in enumerate(CHAR_VOCAB)}
        toks = [table[c] for c in text if c in table]
        if not toks:
            raise ValueError("text encodes to an empty prompt under the "
                             "char vocab")
        return np.asarray(toks, np.int32)

    def decode_text(tokens):
        return "".join(CHAR_VOCAB[t] for t in tokens
                       if 0 <= t < len(CHAR_VOCAB))

    class Handler(BaseHTTPRequestHandler):
        # quiet structured access log — one line per request on stderr
        def log_message(self, fmt, *a):
            sys.stderr.write("gym_tpu.serve: " + fmt % a + "\n")

        def _reply(self, code: int, payload: dict,
                   retry_after_s: Optional[float] = None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(retry_after_s))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path not in ("/stats", "/healthz"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            if getattr(router, "kind", "thread") == "process":
                self._stats_process()
                return
            fleet = router.status()
            engines = [rep.scheduler.engine for rep in router.replicas]
            stats = [e.stats for e in engines]
            eng0 = engines[0]
            buckets = sorted({b for s in stats for b in s.prefill_buckets})
            drafted = sum(s.spec_drafted for s in stats)
            accepted = sum(s.spec_accepted for s in stats)
            head = metrics.headline()
            rep_counters = head.pop("replicas", {})
            # ONE per-replica section: live engine samples + the
            # metrics collector's per-replica counters folded into the
            # router's health entries
            for entry, s in zip(fleet["replicas"], stats):
                entry.update(active_slots=s.active_slots,
                             tokens_generated=s.tokens_generated,
                             quarantined=s.quarantined)
                entry.update(rep_counters.get(str(entry["id"]), {}))
            dead = sum(1 for rep in router.replicas if rep.dead)
            self._reply(200, {
                **head,                 # first: the LIVE engine stats
                #                         below win over its tick samples
                "status": ("draining" if stop.is_set() else
                           "degraded" if dead else "ok"),
                "step": info["step"],
                "num_slots": sum(s.num_slots for s in stats),
                "active_slots": sum(s.active_slots for s in stats),
                "queue_depth": sum(rep.scheduler.queue_depth()
                                   for rep in router.replicas),
                "tokens_generated": sum(s.tokens_generated
                                        for s in stats),
                "decode_steps": sum(s.decode_steps for s in stats),
                "prefills": sum(s.prefills for s in stats),
                "prefill_buckets": buckets,
                "prefill_tokens": sum(s.prefill_tokens for s in stats),
                "paged": bool(getattr(eng0, "paged", False)),
                "page_size": int(getattr(eng0, "page_size", 0)),
                "kv_pages": int(getattr(eng0, "kv_pages", 0)),
                "spec_tokens": int(getattr(eng0, "spec_tokens", 0)),
                # quantized serving (ISSUE 11): config echo + the
                # f32-normalized pool capacity and actual byte
                # footprints (honest accounting — scale sidecars
                # reported, not hidden)
                "weights_dtype": getattr(eng0, "weights_dtype", "f32"),
                "kv_dtype": getattr(eng0, "kv_dtype", "f32"),
                "kv_blocks_capacity_effective": sum(
                    int(getattr(e, "kv_blocks_capacity_effective", 0))
                    for e in engines),
                "weights_bytes": int(getattr(eng0, "weights_bytes", 0)),
                "kv_blocks_in_use": sum(s.kv_blocks_in_use
                                        for s in stats),
                "kv_blocks_cached": sum(s.kv_blocks_cached
                                        for s in stats),
                "prefix_hit_blocks": sum(s.prefix_hit_blocks
                                         for s in stats),
                "spec_accept_rate": (accepted / drafted
                                     if drafted else None),
                # device-program registry: XLA compiles this process has
                # actually run (disk-tier deserializations excluded) —
                # THE restart-drill observable (0 across a restart with
                # a warm disk tier) — plus background-warmup progress
                "programs_compiled": programs_mod.xla_compile_counter(),
                "warmup": (warm_thread.stats()
                           if warm_thread is not None else None),
                # multi-tenant serving (ISSUE 17): live quota fill,
                # preemption/park counters and per-class backlog
                "tenants": agg_tenant_snapshots(
                    [rep.scheduler.tenant_snapshot()
                     for rep in router.replicas if not rep.dead]),
                # pre-fleet surface: replica 0's supervisor state (the
                # keys every existing dashboard/drill greps)
                **sup.status(),
                # the fleet view: per-replica health/load/weights,
                # failovers, reloads — wins over the aggregates above
                # where keys collide (replicas, failovers, …)
                **fleet,
            })

        def _stats_process(self):
            """/stats for the OUT-OF-PROCESS fleet: the router process
            holds no engines — per-replica engine samples come from the
            workers' health frames (cached by the dispatcher's reader
            loop), each entry carrying the worker ``pid`` and its OWN
            ``programs_compiled`` (the spawn-cheapness observable the
            ci_serve drill pins at 0 against a warm cache dir)."""
            fleet = router.status()
            live = [r for r in fleet["replicas"] if not r["retired"]]
            head = metrics.headline()
            head.pop("replicas", None)
            # degraded = fewer healthy workers than the fleet's floor
            # (dead replicas stay listed for the post-mortem, but a
            # respawned fleet is OK again — alerts must clear)
            floor = (autoscaler.policy.min_replicas
                     if autoscaler is not None else replicas)
            self._reply(200, {
                **head,
                "status": ("draining" if stop.is_set() else
                           "degraded"
                           if fleet["healthy_replicas"] < floor
                           else "ok"),
                "step": info["step"],
                "num_slots": sum(r.get("num_slots") or 0
                                 for r in live if r["healthy"]),
                "active_slots": sum(r.get("active_slots") or 0
                                    for r in live),
                "queue_depth": sum(r.get("queue_depth") or 0
                                   for r in live),
                "tokens_generated": sum(r.get("tokens_generated") or 0
                                        for r in live),
                # the ROUTER process's own compile counter (should stay
                # ~0: it dispatches, it does not decode); per-replica
                # programs_compiled lives in each replicas[] entry
                "programs_compiled": programs_mod.xla_compile_counter(),
                "autoscaler": (autoscaler.status()
                               if autoscaler is not None else None),
                # multi-tenant block off the workers' health frames
                "tenants": agg_tenant_snapshots(
                    [r.get("tenants") for r in live]),
                **fleet,
            })

        def do_POST(self):
            if self.path == "/reload":
                self._do_reload()
                return
            if self.path != "/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                fault_point("serve.http")
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError as e:
                    raise ValueError(f"malformed JSON body: {e}")
                if not isinstance(body, dict):
                    raise ValueError(
                        f"JSON body must be an object, got "
                        f"{type(body).__name__}")
                if "prompt" in body:
                    prompt = np.asarray(body["prompt"], np.int32)
                elif "text" in body and char_level:
                    prompt = encode_text(body["text"])
                elif "text" in body:
                    raise ValueError(
                        "text prompts need a char-level vocab; this model "
                        f"has vocab_size={cfg.vocab_size} — send token "
                        "ids as 'prompt'")
                else:
                    raise ValueError("body needs 'prompt' (token ids) "
                                     "or 'text'")
                sp = SamplingParams(
                    max_new_tokens=int(body.get("max_new_tokens", 64)),
                    temperature=float(body.get("temperature", 1.0)),
                    top_k=(None if body.get("top_k") is None
                           else int(body["top_k"])),
                    top_p=(None if body.get("top_p") is None
                           else float(body["top_p"])),
                    eos_token=(None if body.get("eos_token") is None
                               else int(body["eos_token"])),
                    seed=int(body.get("seed", 0)))
                # body field wins over the X-Deadline-S header; both win
                # over the server-wide default
                deadline = body.get("deadline_s",
                                    self.headers.get("X-Deadline-S"))
                deadline = (default_deadline if deadline is None
                            else float(deadline))
                stream = bool(body.get("stream", False))
                # multi-tenant tags (ISSUE 17): body field wins over
                # the header; both optional — absent = the default
                # tenant/class (single-tenant behavior)
                tenant = body.get("tenant",
                                  self.headers.get("X-Tenant"))
                slo_class = body.get("slo_class",
                                     self.headers.get("X-SLO-Class"))
                if tenant is not None:
                    tenant = str(tenant)
                if slo_class is not None:
                    slo_class = str(slo_class)
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": str(e)})
                return
            except OSError as e:      # serve.http injected IO fault
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=1.0)
                return
            try:
                # the process router skips per-chunk wire frames for
                # result-only requests; the in-process router has no
                # such knob (tokens are already shared memory)
                submit_kw = ({"stream": stream}
                             if getattr(router, "kind", "") == "process"
                             else {})
                req = router.submit(prompt, sp, timeout=30.0,
                                    deadline_s=deadline, tenant=tenant,
                                    slo_class=slo_class, **submit_kw)
            except AdmissionRejectedError as e:
                self._reply(429, {"error": str(e)},
                            retry_after_s=e.retry_after_s)
                return
            except QueueFullError as e:
                self._reply(429, {"error": str(e)}, retry_after_s=2.0)
                return
            except NoHealthyReplicaError as e:
                self._reply(503, {"error": str(e)},
                            retry_after_s=e.retry_after_s)
                return
            except SchedulerClosedError as e:
                self._reply(503, {"error": str(e)}, retry_after_s=10.0)
                return
            except ValueError as e:
                # a prompt the KV cache can't fit, bad sampling params
                self._reply(400, {"error": str(e)})
                return
            except OSError as e:      # serve.admit injected IO fault
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=1.0)
                return
            # the handler's own wait honors the request deadline: even if
            # the driver is wedged (the watchdog will reap it), the
            # client gets its typed answer within deadline + grace
            wait_s = request_timeout
            if deadline is not None:
                wait_s = min(wait_s, deadline + 5.0)
            if stream:
                self._stream_reply(req, prompt, wait_s)
                return
            try:
                tokens = req.result(timeout=wait_s)
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e),
                                  "tokens_before_deadline":
                                  len(req.tokens)})
                return
            except TimeoutError as e:
                self._reply(504, {"error": str(e)})
                return
            except (EngineFailedError, SlotQuarantinedError,
                    SchedulerClosedError) as e:
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=2.0)
                return
            except AdmissionRejectedError as e:
                # a failover retry shed at the SIBLING's admission (the
                # remaining deadline is infeasible there): same 429 +
                # Retry-After contract as a front-door shed
                self._reply(429, {"error": str(e)},
                            retry_after_s=e.retry_after_s)
                return
            except QueueFullError as e:
                self._reply(429, {"error": str(e)}, retry_after_s=2.0)
                return
            except NoHealthyReplicaError as e:
                self._reply(503, {"error": str(e)},
                            retry_after_s=e.retry_after_s)
                return
            except OSError as e:
                # a request failed by an IO fault (e.g. serve.prefill
                # oserror) stores that exception; it must surface as a
                # typed 503, not escape the handler as a traceback
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=1.0)
                return
            except RuntimeError as e:
                self._reply(503, {"error": str(e)})
                return
            out = {"tokens": tokens,
                   "prompt_tokens": int(prompt.size),
                   "ttft_s": round(req.ttft_s, 5),
                   "latency_s": round(req.done_t - req.submit_t, 5),
                   "replica": req.replica_id,
                   "failovers": req.failovers}
            if char_level:
                out["text"] = decode_text(tokens)
            self._reply(200, out)

        def _sse(self, obj: dict) -> None:
            self.wfile.write(b"data: " + json.dumps(obj).encode()
                             + b"\n\n")
            self.wfile.flush()

        def _stream_reply(self, req, prompt, wait_s: float) -> None:
            """``"stream": true`` — chunked SSE: one ``data:`` event per
            decode chunk, then a final summary event. TTFB collapses
            from completion time to FIRST-token time; a mid-stream
            replica death is spliced by the router (the concatenated
            events are byte-identical to an uncontended run); a client
            that disconnects (EPIPE on the chunked write) has its
            request cancelled at the next decode-chunk boundary and
            recorded ``status=disconnected`` — never a traceback."""
            metrics.stream_started()
            tokens = []
            try:
                try:
                    # header writes can ALREADY raise EPIPE (client
                    # gone before the first byte) — they must sit
                    # inside the disconnect guard or the generation
                    # runs for nobody and the handler tracebacks
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for chunk in req.stream(timeout=wait_s):
                        tokens.extend(chunk)
                        self._sse({"tokens": chunk,
                                   "replica": req.replica_id})
                    out = {"done": True,
                           "tokens_total": len(tokens),
                           "prompt_tokens": int(prompt.size),
                           "ttft_s": (round(req.ttft_s, 5)
                                      if req.ttft_s is not None
                                      else None),
                           "latency_s": (round(req.done_t - req.submit_t,
                                               5)
                                         if req.done_t is not None
                                         else None),
                           "replica": req.replica_id,
                           "failovers": req.failovers}
                    if char_level:
                        out["text"] = decode_text(tokens)
                    self._sse(out)
                except (BrokenPipeError, ConnectionResetError):
                    # the client went away mid-stream: cancel at the
                    # next chunk boundary, free the slot; metrics land
                    # as status=disconnected via RequestCancelledError
                    req.cancel(reason="client disconnected mid-stream")
                    self.close_connection = True
                except (DeadlineExceededError, TimeoutError,
                        AdmissionRejectedError, QueueFullError,
                        EngineFailedError, SlotQuarantinedError,
                        SchedulerClosedError, NoHealthyReplicaError,
                        RequestCancelledError, OSError,
                        RuntimeError) as e:
                    # headers are gone — the typed failure travels as a
                    # terminal SSE event instead of a status code
                    try:
                        self._sse({"error": str(e),
                                   "error_type": type(e).__name__,
                                   "tokens_total": len(tokens)})
                    except (BrokenPipeError, ConnectionResetError):
                        req.cancel(reason="client disconnected")
                        self.close_connection = True
            finally:
                metrics.stream_ended()

        def _do_reload(self):
            """Zero-downtime weight hot-swap over HTTP: re-read the
            checkpoint (body: optional ``ckpt``/``step``), roll it
            through the fleet. 400 bad body/source, 409 when a reload
            is already rolling, 503 when a replica failed to drain."""
            if reload_source is None:
                self._reply(400, {
                    "error": "no reload source configured — start the "
                             "server via `python -m gym_tpu.serve "
                             "--ckpt ...` to enable /reload"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                body = json.loads(raw)
                if not isinstance(body, dict):
                    raise ValueError(
                        f"JSON body must be an object, got "
                        f"{type(body).__name__}")
                drain_s = float(body.get("drain_timeout_s", 300.0))
            except (json.JSONDecodeError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"malformed reload body: {e}"})
                return
            try:
                new_params, tag = reload_source(body)
            except (CheckpointNotFoundError, FileNotFoundError,
                    ValueError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                return
            except OSError as e:
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=5.0)
                return
            try:
                result = router.reload(
                    new_params, weights_tag=tag, drain_timeout_s=drain_s)
            except FleetReloadError as e:
                if e.retry_after_s is not None:
                    # a replica failed to drain in time — transient
                    self._reply(503, {"error": str(e)},
                                retry_after_s=e.retry_after_s)
                else:       # another rollout already in flight
                    self._reply(409, {"error": str(e)})
                return
            except SchedulerClosedError as e:
                self._reply(503, {"error": str(e)}, retry_after_s=10.0)
                return
            if tag and tag.startswith("step-"):
                # /stats "step" tracks the weights actually serving
                try:
                    info["step"] = int(tag[5:])
                except ValueError:
                    pass
            self._reply(200, result)

    httpd = ThreadingHTTPServer((host, port), Handler)
    # answered-before-closed: server_close waits for handler threads, so
    # every accepted request gets its JSON reply before the process exits
    httpd.daemon_threads = False
    httpd.block_on_close = True
    if not out_of_process:
        router.start()        # process fleets started above (their
        #                       workers need the pre-listen wait)
    return ServerHandle(httpd=httpd, scheduler=sched, supervisor=sup,
                        metrics=metrics,
                        engine_factory=(None if out_of_process
                                        else router.replicas[0]
                                        .engine_factory),
                        info=info, router=router, warmup=warm_thread,
                        autoscaler=autoscaler)


def main(argv=None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)
    if getattr(args, "quant_embed") and not args.quant:
        # refuse, don't silently no-op: quant_embed only has meaning on
        # a quantized weight tree
        parser.error("--quant-embed requires --quant {int8,int4}")
    if args.device == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")

    from ..utils.checkpoint import CheckpointNotFoundError
    from .load import CheckpointWatcher, load_for_serving

    quant_kw = dict(weights_dtype=args.quant,
                    kv_dtype=getattr(args, "kv_quant"),
                    quant_embed=getattr(args, "quant_embed"))
    try:
        params, cfg, info = load_for_serving(
            args.ckpt, step=args.step, config_path=args.config,
            **quant_kw)
    except (CheckpointNotFoundError, FileNotFoundError, ValueError) as e:
        print(f"gym_tpu.serve: cannot load {args.ckpt}: {e}",
              file=sys.stderr)
        return 1
    quant_note = ""
    if args.quant or getattr(args, "kv_quant"):
        quant_note = (f", quantized (weights {cfg.weights_dtype}"
                      + (", embed" if cfg.quant_embed else "")
                      + f", kv {cfg.kv_dtype})")
    print(f"gym_tpu.serve: restored step {info['step']} "
          f"({info['num_nodes']}-node average) from {args.ckpt}"
          f"{quant_note}", flush=True)

    def reload_source(body):
        """POST /reload + the checkpoint watcher: re-read the run dir
        (newest valid step unless pinned) and hand back the node-
        averaged params with a ``step-N`` weights tag — quantized
        through the same load-time step as startup, so a hot-swap never
        silently changes serving dtype. The architecture must match —
        the fleet's compiled programs are config-keyed."""
        ckpt = body.get("ckpt") or args.ckpt
        new_params, new_cfg, new_info = load_for_serving(
            ckpt, step=body.get("step"), config_path=args.config,
            **quant_kw)
        if new_cfg != cfg:
            raise ValueError(
                f"checkpoint {ckpt} carries a different model config — "
                f"a hot-swap cannot change architecture; restart the "
                f"server")
        return new_params, f"step-{new_info['step']}"

    quotas = None
    if getattr(args, "quotas"):
        from .scheduler import ClassQuota
        try:
            quotas = {cls: ClassQuota(**spec)
                      for cls, spec in json.loads(args.quotas).items()}
        except (json.JSONDecodeError, TypeError, ValueError) as e:
            print(f"gym_tpu.serve: bad --quotas JSON: {e}",
                  file=sys.stderr)
            return 1

    stop = threading.Event()
    handle = create_server(
        params, cfg, host=args.host, port=args.port,
        num_slots=args.num_slots, decode_chunk=args.decode_chunk,
        max_queue=args.max_queue, request_timeout=args.request_timeout,
        default_deadline=getattr(args, "default_deadline"),
        dispatch_timeout=getattr(args, "dispatch_timeout"),
        max_restarts=getattr(args, "max_restarts"),
        metrics_dir=args.metrics_dir or os.path.join(args.ckpt, "serve"),
        info=info, stop_event=stop, page_size=args.page_size,
        kv_pages=args.kv_pages, spec_tokens=args.spec_tokens,
        replicas=args.replicas,
        failover_retries=getattr(args, "failover_retries"),
        reload_source=reload_source,
        warmup=not getattr(args, "no_warmup"),
        program_cache_dir=getattr(args, "program_cache_dir"),
        out_of_process=getattr(args, "out_of_process"),
        autoscale=getattr(args, "autoscale"),
        min_replicas=getattr(args, "min_replicas"),
        max_replicas=getattr(args, "max_replicas"),
        autoscale_interval_s=getattr(args, "autoscale_interval"),
        worker_startup_timeout_s=getattr(args, "worker_startup_timeout"),
        quotas=quotas, preempt=getattr(args, "preempt"))
    httpd, metrics, router = handle.httpd, handle.metrics, handle.router

    watcher = None
    if getattr(args, "reload_watch") > 0:

        def on_new_step(step):
            new_params, tag = reload_source({"step": step})
            res = router.reload(new_params, weights_tag=tag)
            # /stats "step" tracks the live weights — mutate the
            # handler's copy (create_server dict()s the info it is given)
            handle.info["step"] = step
            print(f"gym_tpu.serve: checkpoint watcher — hot-swapped "
                  f"{tag} into replicas {res['swapped']} "
                  f"in {res['wall_s']}s", flush=True)

        watcher = CheckpointWatcher(
            args.ckpt, on_new_step,
            poll_s=getattr(args, "reload_watch"),
            initial_step=info["step"]).start()

    def graceful(signum):
        name = signal.Signals(signum).name
        print(f"gym_tpu.serve: {name} — draining "
              f"(answer in-flight, fail queued)", flush=True)
        deadline = getattr(args, "drain_deadline")
        stop.set()
        if watcher is not None:
            watcher.stop()
        if handle.autoscaler is not None:
            handle.autoscaler.stop()   # no respawns during the drain
        handle.stop_warmup()
        # per-replica drain: answer in-flight, fail queued typed; a
        # WEDGED replica gets its thread stacks dumped and its requests
        # failed typed without its engine ever being stepped from this
        # thread (single-driver contract) — Router.close does both
        if not router.close(drain_deadline_s=deadline):
            print("gym_tpu.serve: one or more replica drivers wedged "
                  "through the drain (stacks dumped above)",
                  file=sys.stderr, flush=True)
        httpd.shutdown()

    def _on_signal(signum, frame):
        # serve_forever blocks the main thread; drain from a helper so the
        # handler returns immediately (a second signal takes default
        # action — grace, not imprisonment)
        threading.Thread(target=graceful, args=(signum,),
                         daemon=True).start()
        signal.signal(signum, signal.SIG_DFL)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)

    if handle.scheduler is not None:
        eng = handle.scheduler.engine
        kv = (f"paged kv: page {eng.page_size} x {eng.kv_pages} pages"
              + (f", spec {eng.spec_tokens}" if eng.spec_tokens else "")
              if eng.paged else "unpaged kv")
        if eng.weights_dtype != "f32" or eng.kv_dtype != "f32":
            kv += f", quant w={eng.weights_dtype} kv={eng.kv_dtype}"
        fleet_note = f"{args.replicas} replica(s)"
    else:
        kv = "worker-side kv"
        fleet_note = (f"{args.replicas} worker process(es)"
                      + (", autoscaling" if handle.autoscaler is not None
                         else ""))
    print(f"gym_tpu.serve: listening on http://{args.host}:{handle.port} "
          f"({fleet_note} x {args.num_slots} slots, "
          f"queue {args.max_queue}, {kv}, "
          f"watchdog {getattr(args, 'dispatch_timeout'):.0f}s)", flush=True)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        if watcher is not None:
            watcher.stop()
        metrics.sync()
        head = metrics.headline()
        fleet = router.status()
        print(f"gym_tpu.serve: shut down cleanly — "
              f"{head['requests_done']} done, "
              f"{head['requests_failed']} failed "
              f"({head['requests_shed']} shed, "
              f"{head['requests_quarantined']} quarantined), "
              f"{head['engine_restarts']} engine restart(s), "
              f"{fleet['failovers']} failover(s), "
              f"{fleet['weight_reloads']} weight reload(s), "
              f"tokens_per_s={head['tokens_per_s']}", flush=True)
        metrics.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
