"""``python -m gym_tpu.serve --ckpt <run_dir>`` — stdlib-HTTP serving.

No framework: ``http.server.ThreadingHTTPServer`` + the scheduler. One
driver thread runs the engine loop; handler threads submit and block on
the request future. Endpoints:

- ``POST /generate`` — JSON body with either ``prompt`` (a list of token
  ids) or ``text`` (char-level corpora only: encoded via the shakespeare
  ``CHAR_VOCAB``), plus optional ``max_new_tokens`` / ``temperature`` /
  ``top_k`` / ``top_p`` / ``eos_token`` / ``seed``. Replies with the new
  ``tokens`` (and ``text`` when the vocab is char-level), TTFT and
  per-token latency.
- ``GET /stats`` (alias ``/healthz``) — engine + metrics headline JSON.

Shutdown drill (ISSUE 4 acceptance): SIGTERM/SIGINT triggers a graceful
drain — stop accepting, FAIL queued requests ("shutting down", reported
to their waiting handlers, never dropped), ANSWER in-flight requests
(the engine keeps stepping until the running slots finish, bounded by
``--drain-deadline``), close the listener, flush ``serve.csv``, print a
final ``tokens_per_s`` headline, exit 0. A wedged drain dumps every
thread's stack (``utils.resilience.dump_thread_stacks``) instead of
hanging silently.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gym_tpu.serve",
        description="Serve a trained gym_tpu checkpoint over HTTP "
                    "(continuous-batching KV-cache decode).")
    p.add_argument("--ckpt", required=True, metavar="RUN_DIR",
                   help="checkpoint run dir: fit(save_dir=...)/<run_name>")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest valid)")
    p.add_argument("--config", default=None, metavar="CONFIG_JSON",
                   help="explicit config.json (for run dirs predating the "
                        "in-dir snapshot: logs/<run_name>/config.json)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num_slots", type=int, default=4,
                   help="concurrent decode slots (the batch width)")
    p.add_argument("--max_queue", type=int, default=64,
                   help="FCFS queue bound (backpressure: submits beyond "
                        "it wait, then 503)")
    p.add_argument("--request_timeout", type=float, default=600.0,
                   help="per-request wall-clock bound inside a handler")
    p.add_argument("--drain-deadline", type=float, default=300.0,
                   help="SIGTERM: max seconds to finish in-flight "
                        "requests before failing them")
    p.add_argument("--metrics_dir", default=None,
                   help="serve.csv location (default: <RUN_DIR>/serve)")
    p.add_argument("--device", default=None,
                   help="'cpu' pins the CPU backend (skips accelerator "
                        "plugin init)")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")

    from ..data.build_dataset import CHAR_VOCAB
    from ..utils.checkpoint import CheckpointNotFoundError
    from ..utils.resilience import dump_thread_stacks
    from .engine import InferenceEngine, SamplingParams
    from .load import load_for_serving
    from .metrics import ServeMetrics
    from .scheduler import QueueFullError, Scheduler

    try:
        params, cfg, info = load_for_serving(
            args.ckpt, step=args.step, config_path=args.config)
    except (CheckpointNotFoundError, FileNotFoundError, ValueError) as e:
        print(f"gym_tpu.serve: cannot load {args.ckpt}: {e}",
              file=sys.stderr)
        return 1
    print(f"gym_tpu.serve: restored step {info['step']} "
          f"({info['num_nodes']}-node average) from {args.ckpt}",
          flush=True)

    engine = InferenceEngine(params, cfg, num_slots=args.num_slots)
    metrics = ServeMetrics(args.metrics_dir
                           or os.path.join(args.ckpt, "serve"))
    sched = Scheduler(engine, max_queue=args.max_queue, metrics=metrics)
    char_level = cfg.vocab_size <= len(CHAR_VOCAB) + 1

    def encode_text(text: str):
        table = {c: i for i, c in enumerate(CHAR_VOCAB)}
        toks = [table[c] for c in text if c in table]
        if not toks:
            raise ValueError("text encodes to an empty prompt under the "
                             "char vocab")
        return np.asarray(toks, np.int32)

    def decode_text(tokens):
        return "".join(CHAR_VOCAB[t] for t in tokens
                       if 0 <= t < len(CHAR_VOCAB))

    stop = threading.Event()
    loop = threading.Thread(target=sched.run, args=(stop,),
                            name="gym-tpu-serve-loop", daemon=True)
    loop.start()

    class Handler(BaseHTTPRequestHandler):
        # quiet structured access log — one line per request on stderr
        def log_message(self, fmt, *a):
            sys.stderr.write("gym_tpu.serve: " + fmt % a + "\n")

        def _reply(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path not in ("/stats", "/healthz"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            s = engine.stats
            self._reply(200, {
                "status": "draining" if stop.is_set() else "ok",
                "step": info["step"],
                "num_slots": s.num_slots,
                "active_slots": s.active_slots,
                "queue_depth": sched.queue_depth(),
                "tokens_generated": s.tokens_generated,
                "decode_steps": s.decode_steps,
                "prefills": s.prefills,
                "prefill_buckets": list(s.prefill_buckets),
                **metrics.headline(),
            })

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if "prompt" in body:
                    prompt = np.asarray(body["prompt"], np.int32)
                elif "text" in body and char_level:
                    prompt = encode_text(body["text"])
                elif "text" in body:
                    raise ValueError(
                        "text prompts need a char-level vocab; this model "
                        f"has vocab_size={cfg.vocab_size} — send token "
                        "ids as 'prompt'")
                else:
                    raise ValueError("body needs 'prompt' (token ids) "
                                     "or 'text'")
                sp = SamplingParams(
                    max_new_tokens=int(body.get("max_new_tokens", 64)),
                    temperature=float(body.get("temperature", 1.0)),
                    top_k=(None if body.get("top_k") is None
                           else int(body["top_k"])),
                    top_p=(None if body.get("top_p") is None
                           else float(body["top_p"])),
                    eos_token=(None if body.get("eos_token") is None
                               else int(body["eos_token"])),
                    seed=int(body.get("seed", 0)))
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})
                return
            try:
                req = sched.submit(prompt, sp, timeout=30.0)
            except QueueFullError as e:
                self._reply(429, {"error": str(e)})
                return
            except (RuntimeError, ValueError) as e:
                # shutting down, or a prompt the KV cache can't fit
                self._reply(503 if "shutting down" in str(e) else 400,
                            {"error": str(e)})
                return
            try:
                tokens = req.result(timeout=args.request_timeout)
            except TimeoutError as e:
                self._reply(504, {"error": str(e)})
                return
            except RuntimeError as e:
                self._reply(503, {"error": str(e)})
                return
            out = {"tokens": tokens,
                   "prompt_tokens": int(prompt.size),
                   "ttft_s": round(req.ttft_s, 5),
                   "latency_s": round(req.done_t - req.submit_t, 5)}
            if char_level:
                out["text"] = decode_text(tokens)
            self._reply(200, out)

    httpd = ThreadingHTTPServer((args.host, args.port), Handler)
    # answered-before-closed: server_close waits for handler threads, so
    # every accepted request gets its JSON reply before the process exits
    httpd.daemon_threads = False
    httpd.block_on_close = True

    def graceful(signum):
        name = signal.Signals(signum).name
        print(f"gym_tpu.serve: {name} — draining "
              f"(answer in-flight, fail queued)", flush=True)
        deadline = getattr(args, "drain_deadline")
        stop.set()               # driver loop exits after its round
        loop.join(timeout=deadline)
        if loop.is_alive():
            # the driver never came back within the drain deadline (a
            # wedged dispatch, not a slow one): do NOT touch the engine
            # from this thread — it is single-driver by contract and a
            # concurrent step() would re-dispatch donated buffers. Dump
            # the evidence and close the listener; in-flight requests
            # stay unanswered, which is the truth of a wedged engine.
            print(dump_thread_stacks(
                "gym_tpu.serve: driver loop wedged past the "
                f"{deadline:.0f}s drain deadline:"),
                file=sys.stderr, flush=True)
        else:
            # shutdown() steps the engine itself until running slots
            # finish — safe now that the driver thread has exited
            sched.shutdown(finish_running=True, deadline_s=deadline)
        httpd.shutdown()

    def _on_signal(signum, frame):
        # serve_forever blocks the main thread; drain from a helper so the
        # handler returns immediately (a second signal takes default
        # action — grace, not imprisonment)
        threading.Thread(target=graceful, args=(signum,),
                         daemon=True).start()
        signal.signal(signum, signal.SIG_DFL)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)

    print(f"gym_tpu.serve: listening on http://{args.host}:{args.port} "
          f"({args.num_slots} slots, queue {args.max_queue})", flush=True)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        metrics.sync()
        head = metrics.headline()
        print(f"gym_tpu.serve: shut down cleanly — "
              f"{head['requests_done']} done, "
              f"{head['requests_failed']} failed, "
              f"tokens_per_s={head['tokens_per_s']}", flush=True)
        metrics.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
