"""``python -m gym_tpu.serve --ckpt <run_dir>`` — stdlib-HTTP serving.

No framework: ``http.server.ThreadingHTTPServer`` + the scheduler under
an engine ``Supervisor``. One driver thread runs the engine loop inside
a watchdog; handler threads submit and block on the request future.
Endpoints:

- ``POST /generate`` — JSON body with either ``prompt`` (a list of token
  ids) or ``text`` (char-level corpora only: encoded via the shakespeare
  ``CHAR_VOCAB``), plus optional ``max_new_tokens`` / ``temperature`` /
  ``top_k`` / ``top_p`` / ``eos_token`` / ``seed`` / ``deadline_s``.
  ``deadline_s`` (also settable per request via the ``X-Deadline-S``
  header; the body field wins) bounds the request end to end: admission
  control rejects it up front (HTTP 429 + ``Retry-After``) when the
  live tokens/s EWMA says the backlog cannot meet it; a queued request
  past deadline is shed before prefill and a running one cancelled at
  the next chunk boundary (HTTP 504, typed). Replies with the new
  ``tokens`` (and ``text`` when the vocab is char-level), TTFT and
  per-token latency.
- ``GET /stats`` (alias ``/healthz``) — engine + metrics headline JSON,
  including supervisor state (engine generation / restarts).

Typed failure → status mapping (never a traceback-500 for a fault the
serving stack understands):

====================== ======================================
400                     malformed JSON / bad params / prompt
                        too long (typed ``ValueError`` body)
429 + ``Retry-After``   queue full, admission-control reject
503 + ``Retry-After``   shutting down, engine failed/rebuilt,
                        slot quarantined (NaN), injected IO
504                     deadline exceeded (shed or cancelled)
====================== ======================================

Shutdown drill (ISSUE 4 acceptance): SIGTERM/SIGINT triggers a graceful
drain — stop accepting, FAIL queued requests (typed, reported to their
waiting handlers, never dropped), ANSWER in-flight requests (the engine
keeps stepping until the running slots finish, bounded by
``--drain-deadline``), close the listener, flush ``serve.csv``, print a
final ``tokens_per_s`` headline, exit 0. A wedged drain dumps every
thread's stack (``utils.resilience.dump_thread_stacks``) instead of
hanging silently.

Chaos drill (ISSUE 5 acceptance, ``scripts/ci_chaos.sh``): with
``GYM_TPU_FAULTS=serve.decode:hang@…`` injected the supervisor abandons
the wedged driver, fails in-flight requests typed (503, inside their
deadline), rebuilds the engine warm and keeps serving — the HTTP server
never dies with its engine.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gym_tpu.serve",
        description="Serve a trained gym_tpu checkpoint over HTTP "
                    "(continuous-batching KV-cache decode).")
    p.add_argument("--ckpt", required=True, metavar="RUN_DIR",
                   help="checkpoint run dir: fit(save_dir=...)/<run_name>")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest valid)")
    p.add_argument("--config", default=None, metavar="CONFIG_JSON",
                   help="explicit config.json (for run dirs predating the "
                        "in-dir snapshot: logs/<run_name>/config.json)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num_slots", type=int, default=4,
                   help="concurrent decode slots (the batch width)")
    p.add_argument("--decode_chunk", type=int, default=1,
                   help="decode steps fused per dispatch (chunk boundary "
                        "= deadline-cancellation granularity)")
    p.add_argument("--page_size", type=int, default=16,
                   help="paged KV cache page size in tokens (must divide "
                        "block_size; 0 reverts to the unpaged per-slot "
                        "cache). Paging enables copy-free prefix sharing "
                        "across requests")
    p.add_argument("--kv_pages", type=int, default=None,
                   help="physical pages in the paged KV pool (default: "
                        "null page + num_slots full windows; smaller "
                        "pools admit lazily as blocks free)")
    p.add_argument("--spec_tokens", type=int, default=0,
                   help="speculative decoding draft length γ (0 = off; "
                        "paged only). Token streams stay exactly equal "
                        "to non-speculative decoding")
    p.add_argument("--max_queue", type=int, default=64,
                   help="FCFS queue bound (backpressure: submits beyond "
                        "it wait, then 429)")
    p.add_argument("--request_timeout", type=float, default=600.0,
                   help="per-request wall-clock bound inside a handler")
    p.add_argument("--default-deadline", type=float, default=None,
                   help="deadline_s applied to requests that don't set "
                        "one (default: none)")
    p.add_argument("--dispatch-timeout", type=float,
                   default=float(os.environ.get(
                       "GYM_TPU_SERVE_WATCHDOG_S", 120.0)),
                   help="supervisor watchdog: a dispatch wedged past this "
                        "triggers engine failover (env "
                        "GYM_TPU_SERVE_WATCHDOG_S)")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="engine rebuilds before the supervisor declares "
                        "the engine unrecoverable")
    p.add_argument("--drain-deadline", type=float, default=300.0,
                   help="SIGTERM: max seconds to finish in-flight "
                        "requests before failing them")
    p.add_argument("--metrics_dir", default=None,
                   help="serve.csv location (default: <RUN_DIR>/serve)")
    p.add_argument("--device", default=None,
                   help="'cpu' pins the CPU backend (skips accelerator "
                        "plugin init)")
    return p


@dataclasses.dataclass
class ServerHandle:
    """Everything a caller (main() or an in-process test) needs to drive
    and tear down one serving stack."""

    httpd: ThreadingHTTPServer
    scheduler: Any
    supervisor: Any
    metrics: Any
    engine_factory: Any
    info: Dict[str, Any]

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def close(self, drain_deadline_s: float = 30.0) -> None:
        """Test-path teardown: stop the driver, drain, close sockets."""
        if self.supervisor.stop(join_timeout_s=drain_deadline_s):
            self.scheduler.shutdown(finish_running=True,
                                    deadline_s=drain_deadline_s)
        else:
            # driver wedged: never step the engine from here, but DO
            # fail queued + in-flight futures typed — handler threads
            # blocked in result() must not pin server_close open
            self.scheduler.shutdown(finish_running=False, deadline_s=0.0)
        self.httpd.shutdown()
        self.httpd.server_close()
        self.metrics.close()


def create_server(params, cfg, *, host: str = "127.0.0.1", port: int = 0,
                  num_slots: int = 4, decode_chunk: int = 1,
                  max_queue: int = 64, request_timeout: float = 600.0,
                  default_deadline: Optional[float] = None,
                  dispatch_timeout: float = 120.0, max_restarts: int = 5,
                  metrics_dir: Optional[str] = None,
                  info: Optional[Dict[str, Any]] = None,
                  stop_event: Optional[threading.Event] = None,
                  page_size: int = 16, kv_pages: Optional[int] = None,
                  spec_tokens: int = 0) -> ServerHandle:
    """Build the full serving stack — engine, scheduler, supervisor,
    metrics, HTTP server — WITHOUT entering ``serve_forever``. ``main``
    and the in-process chaos tests share this path, so what the tests
    exercise is exactly what ``python -m gym_tpu.serve`` runs.
    ``port=0`` binds an ephemeral port (``handle.port`` reports it)."""
    from ..data.build_dataset import CHAR_VOCAB
    from ..utils.resilience import fault_point
    from .engine import InferenceEngine, SamplingParams
    from .metrics import ServeMetrics
    from .scheduler import (AdmissionRejectedError, DeadlineExceededError,
                            EngineFailedError, QueueFullError, Scheduler,
                            SchedulerClosedError, SlotQuarantinedError)
    from .supervisor import Supervisor

    info = dict(info or {"step": None, "num_nodes": None})
    stop = stop_event or threading.Event()
    if metrics_dir is None:
        # per-instance dir: a fixed shared default would interleave two
        # servers' rows in one append-mode serve.csv
        import tempfile
        metrics_dir = tempfile.mkdtemp(prefix="gym_tpu_serve_")

    if page_size and cfg.block_size % page_size:
        # a page size that doesn't divide this checkpoint's window can't
        # page — serve unpaged rather than refuse the checkpoint
        sys.stderr.write(
            f"gym_tpu.serve: page_size {page_size} does not divide "
            f"block_size {cfg.block_size} — serving unpaged"
            + (", speculative decoding disabled (it requires the paged "
               "cache)" if spec_tokens else "") + "\n")
        page_size = 0
    paged = page_size > 0
    if spec_tokens and not paged:
        sys.stderr.write(
            "gym_tpu.serve: --spec_tokens requires the paged cache "
            "(--page_size > 0) — speculative decoding disabled\n")

    def engine_factory():
        # the params live in memory (restored from the checkpoint at
        # startup); the global prefill/decode program LRUs make a rebuild
        # warm — same config, no recompiles
        return InferenceEngine(params, cfg, num_slots=num_slots,
                               decode_chunk=decode_chunk, paged=paged,
                               page_size=page_size or 16,
                               kv_pages=kv_pages,
                               spec_tokens=spec_tokens if paged else 0)

    metrics = ServeMetrics(metrics_dir)
    sched = Scheduler(engine_factory(), max_queue=max_queue,
                      metrics=metrics)
    sup = Supervisor(sched, engine_factory,
                     dispatch_timeout_s=dispatch_timeout,
                     max_restarts=max_restarts, metrics=metrics)
    char_level = cfg.vocab_size <= len(CHAR_VOCAB) + 1

    def encode_text(text: str):
        table = {c: i for i, c in enumerate(CHAR_VOCAB)}
        toks = [table[c] for c in text if c in table]
        if not toks:
            raise ValueError("text encodes to an empty prompt under the "
                             "char vocab")
        return np.asarray(toks, np.int32)

    def decode_text(tokens):
        return "".join(CHAR_VOCAB[t] for t in tokens
                       if 0 <= t < len(CHAR_VOCAB))

    class Handler(BaseHTTPRequestHandler):
        # quiet structured access log — one line per request on stderr
        def log_message(self, fmt, *a):
            sys.stderr.write("gym_tpu.serve: " + fmt % a + "\n")

        def _reply(self, code: int, payload: dict,
                   retry_after_s: Optional[float] = None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 str(max(1, math.ceil(retry_after_s))))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path not in ("/stats", "/healthz"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            s = sched.engine.stats
            eng = sched.engine
            self._reply(200, {
                **metrics.headline(),   # first: the LIVE engine stats
                #                         below win over its tick samples
                "status": ("draining" if stop.is_set() else
                           "degraded" if sup.failed is not None else "ok"),
                "step": info["step"],
                "num_slots": s.num_slots,
                "active_slots": s.active_slots,
                "queue_depth": sched.queue_depth(),
                "tokens_generated": s.tokens_generated,
                "decode_steps": s.decode_steps,
                "prefills": s.prefills,
                "prefill_buckets": list(s.prefill_buckets),
                "prefill_tokens": s.prefill_tokens,
                "paged": bool(getattr(eng, "paged", False)),
                "page_size": int(getattr(eng, "page_size", 0)),
                "kv_pages": int(getattr(eng, "kv_pages", 0)),
                "spec_tokens": int(getattr(eng, "spec_tokens", 0)),
                "kv_blocks_in_use": s.kv_blocks_in_use,
                "kv_blocks_cached": s.kv_blocks_cached,
                "prefix_hit_blocks": s.prefix_hit_blocks,
                "spec_accept_rate": s.spec_accept_rate(),
                **sup.status(),
            })

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                fault_point("serve.http")
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError as e:
                    raise ValueError(f"malformed JSON body: {e}")
                if not isinstance(body, dict):
                    raise ValueError(
                        f"JSON body must be an object, got "
                        f"{type(body).__name__}")
                if "prompt" in body:
                    prompt = np.asarray(body["prompt"], np.int32)
                elif "text" in body and char_level:
                    prompt = encode_text(body["text"])
                elif "text" in body:
                    raise ValueError(
                        "text prompts need a char-level vocab; this model "
                        f"has vocab_size={cfg.vocab_size} — send token "
                        "ids as 'prompt'")
                else:
                    raise ValueError("body needs 'prompt' (token ids) "
                                     "or 'text'")
                sp = SamplingParams(
                    max_new_tokens=int(body.get("max_new_tokens", 64)),
                    temperature=float(body.get("temperature", 1.0)),
                    top_k=(None if body.get("top_k") is None
                           else int(body["top_k"])),
                    top_p=(None if body.get("top_p") is None
                           else float(body["top_p"])),
                    eos_token=(None if body.get("eos_token") is None
                               else int(body["eos_token"])),
                    seed=int(body.get("seed", 0)))
                # body field wins over the X-Deadline-S header; both win
                # over the server-wide default
                deadline = body.get("deadline_s",
                                    self.headers.get("X-Deadline-S"))
                deadline = (default_deadline if deadline is None
                            else float(deadline))
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": str(e)})
                return
            except OSError as e:      # serve.http injected IO fault
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=1.0)
                return
            try:
                req = sched.submit(prompt, sp, timeout=30.0,
                                   deadline_s=deadline)
            except AdmissionRejectedError as e:
                self._reply(429, {"error": str(e)},
                            retry_after_s=e.retry_after_s)
                return
            except QueueFullError as e:
                self._reply(429, {"error": str(e)}, retry_after_s=2.0)
                return
            except SchedulerClosedError as e:
                self._reply(503, {"error": str(e)}, retry_after_s=10.0)
                return
            except ValueError as e:
                # a prompt the KV cache can't fit, bad sampling params
                self._reply(400, {"error": str(e)})
                return
            except OSError as e:      # serve.admit injected IO fault
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=1.0)
                return
            # the handler's own wait honors the request deadline: even if
            # the driver is wedged (the watchdog will reap it), the
            # client gets its typed answer within deadline + grace
            wait_s = request_timeout
            if deadline is not None:
                wait_s = min(wait_s, deadline + 5.0)
            try:
                tokens = req.result(timeout=wait_s)
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e),
                                  "tokens_before_deadline":
                                  len(req.tokens)})
                return
            except TimeoutError as e:
                self._reply(504, {"error": str(e)})
                return
            except (EngineFailedError, SlotQuarantinedError,
                    SchedulerClosedError) as e:
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=2.0)
                return
            except OSError as e:
                # a request failed by an IO fault (e.g. serve.prefill
                # oserror) stores that exception; it must surface as a
                # typed 503, not escape the handler as a traceback
                self._reply(503, {"error": f"{type(e).__name__}: {e}"},
                            retry_after_s=1.0)
                return
            except RuntimeError as e:
                self._reply(503, {"error": str(e)})
                return
            out = {"tokens": tokens,
                   "prompt_tokens": int(prompt.size),
                   "ttft_s": round(req.ttft_s, 5),
                   "latency_s": round(req.done_t - req.submit_t, 5)}
            if char_level:
                out["text"] = decode_text(tokens)
            self._reply(200, out)

    httpd = ThreadingHTTPServer((host, port), Handler)
    # answered-before-closed: server_close waits for handler threads, so
    # every accepted request gets its JSON reply before the process exits
    httpd.daemon_threads = False
    httpd.block_on_close = True
    sup.start()
    return ServerHandle(httpd=httpd, scheduler=sched, supervisor=sup,
                        metrics=metrics, engine_factory=engine_factory,
                        info=info)


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")

    from ..utils.checkpoint import CheckpointNotFoundError
    from ..utils.resilience import dump_thread_stacks
    from .load import load_for_serving

    try:
        params, cfg, info = load_for_serving(
            args.ckpt, step=args.step, config_path=args.config)
    except (CheckpointNotFoundError, FileNotFoundError, ValueError) as e:
        print(f"gym_tpu.serve: cannot load {args.ckpt}: {e}",
              file=sys.stderr)
        return 1
    print(f"gym_tpu.serve: restored step {info['step']} "
          f"({info['num_nodes']}-node average) from {args.ckpt}",
          flush=True)

    stop = threading.Event()
    handle = create_server(
        params, cfg, host=args.host, port=args.port,
        num_slots=args.num_slots, decode_chunk=args.decode_chunk,
        max_queue=args.max_queue, request_timeout=args.request_timeout,
        default_deadline=getattr(args, "default_deadline"),
        dispatch_timeout=getattr(args, "dispatch_timeout"),
        max_restarts=getattr(args, "max_restarts"),
        metrics_dir=args.metrics_dir or os.path.join(args.ckpt, "serve"),
        info=info, stop_event=stop, page_size=args.page_size,
        kv_pages=args.kv_pages, spec_tokens=args.spec_tokens)
    httpd, sched, sup, metrics = (handle.httpd, handle.scheduler,
                                  handle.supervisor, handle.metrics)

    def graceful(signum):
        name = signal.Signals(signum).name
        print(f"gym_tpu.serve: {name} — draining "
              f"(answer in-flight, fail queued)", flush=True)
        deadline = getattr(args, "drain_deadline")
        stop.set()
        if not sup.stop(join_timeout_s=deadline):
            # the driver never came back within the drain deadline (a
            # wedged dispatch, not a slow one): do NOT touch the engine
            # from this thread — it is single-driver by contract and a
            # concurrent step() would re-dispatch donated buffers. Dump
            # the evidence and close the listener; in-flight requests
            # stay unanswered, which is the truth of a wedged engine.
            print(dump_thread_stacks(
                "gym_tpu.serve: driver loop wedged past the "
                f"{deadline:.0f}s drain deadline:"),
                file=sys.stderr, flush=True)
            # still fail queued + in-flight typed (flag writes only, no
            # engine stepping) so blocked handlers get their answer and
            # block_on_close can finish
            sched.shutdown(finish_running=False, deadline_s=0.0)
        else:
            # shutdown() steps the engine itself until running slots
            # finish — safe now that the driver thread has exited
            sched.shutdown(finish_running=True, deadline_s=deadline)
        httpd.shutdown()

    def _on_signal(signum, frame):
        # serve_forever blocks the main thread; drain from a helper so the
        # handler returns immediately (a second signal takes default
        # action — grace, not imprisonment)
        threading.Thread(target=graceful, args=(signum,),
                         daemon=True).start()
        signal.signal(signum, signal.SIG_DFL)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)

    eng = handle.scheduler.engine
    kv = (f"paged kv: page {eng.page_size} x {eng.kv_pages} pages"
          + (f", spec {eng.spec_tokens}" if eng.spec_tokens else "")
          if eng.paged else "unpaged kv")
    print(f"gym_tpu.serve: listening on http://{args.host}:{handle.port} "
          f"({args.num_slots} slots, queue {args.max_queue}, {kv}, "
          f"watchdog {getattr(args, 'dispatch_timeout'):.0f}s)", flush=True)
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        metrics.sync()
        head = metrics.headline()
        print(f"gym_tpu.serve: shut down cleanly — "
              f"{head['requests_done']} done, "
              f"{head['requests_failed']} failed "
              f"({head['requests_shed']} shed, "
              f"{head['requests_quarantined']} quarantined), "
              f"{head['engine_restarts']} engine restart(s), "
              f"tokens_per_s={head['tokens_per_s']}", flush=True)
        metrics.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
