"""gym_tpu.serve — continuous-batching inference over the KV-cache decode
path (the fifth subsystem, alongside ``data/``, ``strategy/``, ``sim/``
and ``utils/``).

``generate_fast`` (``models/nanogpt.py``) made single-request decode fast
but left it fixed-shape (one compile per exact ``(batch, prompt_len,
max_new_tokens)`` signature) with no request layer. This package is the
path from a trained ``fit()`` run dir to tokens-per-second under
concurrent load:

- ``engine``: fixed-capacity slot batch with per-slot ring-position KV
  caches, ONE jitted decode step shared by every request (per-slot
  cursors/masks and vectorized per-slot sampling params), and prefill
  bucketed to powers of two so total compilations are bounded by
  ``O(log block_size)`` instead of one per prompt length. Requests enter
  free slots and leave on EOS/max-tokens BETWEEN decode steps —
  continuous batching, no drain-the-batch barrier. With ``paged=True``
  the KV cache becomes a shared PAGE POOL with per-slot block tables, a
  ref-counted allocator and a prefix hash table: block-aligned shared
  prompt prefixes are prefilled once and reused copy-free across
  requests, and ``spec_tokens=γ`` adds self-drafting speculative
  decoding whose token streams are EXACTLY the non-speculative ones.
- ``scheduler``: FCFS request queue, slot assignment, and a
  backpressure-bounded submit/poll API — with per-request deadlines
  (queued requests past deadline shed before prefill, running ones
  cancelled at chunk boundaries), EWMA-based admission control
  (infeasible deadlines rejected typed before they are enqueued) and
  prefix-aware admit ordering over a bounded lookahead window.
- ``supervisor``: self-healing driver loop — every dispatch runs under a
  watchdog; an engine crash or wedge fails in-flight requests typed,
  rebuilds the engine warm (global program LRUs) and resumes the queue.
- ``router``: the FLEET tier — N replica stacks behind health-aware
  least-loaded + prefix-cache-affine dispatch, transparent failover of
  in-flight requests onto a sibling under their remaining deadline when
  a replica dies, and rolling zero-downtime weight hot-swap
  (``Router.reload``) so a trainer's newest checkpoint enters the fleet
  without dropping a request or recompiling a program.
- ``load``: params-only checkpoint restore — a ``fit(save_dir=...)`` run
  dir serves directly, no optimizer-state template needed.
- ``metrics``: per-request TTFT / per-token latency and engine
  tokens/s / queue depth / slot occupancy, logged CSVLogger-style to
  ``serve.csv``.
- ``wire`` / ``worker`` / ``autoscale``: the OUT-OF-PROCESS fleet tier
  (ISSUE 13) — each replica a real subprocess (its own GIL, its own
  failure domain) speaking a length-prefixed JSON frame protocol over a
  local socket (submit / streamed chunk / health / reload / stop), the
  router's ``ProcessRouter`` as a thin async dispatcher with the SAME
  failover semantics upgraded to streaming (mid-stream replica death
  splices the re-derived token stream byte-identically), and a
  load-adaptive autoscaler spawning/retiring replica processes from the
  per-replica tokens/s EWMAs and backlog.
- ``__main__``: ``python -m gym_tpu.serve --ckpt <run_dir>`` — a
  stdlib-HTTP entrypoint with graceful SIGTERM drain, token streaming
  (``"stream": true`` → chunked SSE, TTFB = first-token time), and
  ``--out-of-process`` / ``--autoscale`` for the process fleet.
"""

from .autoscale import (AutoscaleController, AutoscalePolicy,
                        Autoscaler)
from .engine import (BlockAllocator, EngineStats, InferenceEngine,
                     NoFreeBlocksError, SamplingParams)
from .load import CheckpointWatcher, load_for_serving
from .metrics import ReplicaMetrics, ServeMetrics
from .router import (FleetReloadError, FleetRequest,
                     NoHealthyReplicaError, ProcessReplica,
                     ProcessRouter, ProcRequest, Replica, Router,
                     WorkerSpawner, build_fleet, build_process_fleet)
from .scheduler import (AdmissionRejectedError, DeadlineExceededError,
                        EngineFailedError, QueueFullError, Request,
                        RequestCancelledError, RequestStatus, Scheduler,
                        SchedulerClosedError, SlotQuarantinedError)
from .supervisor import Supervisor

__all__ = [
    "InferenceEngine", "SamplingParams", "EngineStats",
    "BlockAllocator", "NoFreeBlocksError",
    "Scheduler", "Request", "RequestStatus", "QueueFullError",
    "SchedulerClosedError", "DeadlineExceededError",
    "AdmissionRejectedError", "EngineFailedError",
    "SlotQuarantinedError", "RequestCancelledError", "Supervisor",
    "Router", "Replica", "FleetRequest", "build_fleet",
    "NoHealthyReplicaError", "FleetReloadError",
    "ProcessRouter", "ProcessReplica", "ProcRequest", "WorkerSpawner",
    "build_process_fleet",
    "AutoscalePolicy", "AutoscaleController", "Autoscaler",
    "load_for_serving", "CheckpointWatcher",
    "ServeMetrics", "ReplicaMetrics",
]
