"""Params-only checkpoint → serveable (params, GPTConfig).

A training run dir (``fit(save_dir=..., checkpoint_interval=...)``) holds
step-numbered Orbax checkpoints of the FULL train state — per-node
params, optimizer state, strategy state — plus, since the serve
subsystem landed, a ``config.json`` snapshot written next to the step
dirs (``trainer.py``). Serving needs none of the training machinery:

1. ``utils.checkpoint.restore_params`` reads the newest valid step
   template-free and hands back the node-stacked ``params`` tree.
2. The [K] node axis is averaged away — the same node-averaged model a
   ``FitResult.params`` returns (the reference averages final state
   dicts across ranks).
3. ``GPTConfig`` is rebuilt from ``config.json``'s ``model_config`` and
   sanitized for decode by the engine (``models.nanogpt.decode_config``)
   — sharding axes and the pinned MoE dispatch are training-time
   concerns.

``CheckpointNotFoundError`` propagates typed (CLIs surface it as a
one-line message, not a traceback).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.nanogpt import GPTConfig
from ..utils.checkpoint import CheckpointNotFoundError, restore_params

PyTree = Any


# -- quantize-at-load (ISSUE 11: quantized serving) -----------------------


def params_are_quantized(params: PyTree) -> bool:
    """True when the tree already carries quantized leaves (``qkernel``/
    ``qembedding``) — lets every construction path (engine, fleet
    factory, hot-swap reload) accept either an f32 checkpoint tree or a
    pre-quantized one without re-quantizing."""
    found = False

    def walk(node):
        nonlocal found
        if hasattr(node, "items"):
            for name, sub in node.items():
                if name in ("qkernel", "qembedding"):
                    found = True
                walk(sub)

    walk(params)
    return found


def quantize_params(params: PyTree, config) -> PyTree:
    """Quantize an f32 GPT param tree for serving under ``config``
    (``weights_dtype`` 'int8'/'int4', optional ``quant_embed``): every
    2-D block ``kernel`` — and the ``wte`` embedding when
    ``quant_embed`` — becomes ``(qkernel|qembedding, qscale)`` via the
    SAME per-tile max-abs codec the compressed collectives use
    (``strategy/compress.py:QuantizeCodec``, ``stochastic=False`` —
    weights are quantized once, deterministically, not per-step
    gradients). The tile is clamped per-leaf to divide the trailing
    axis (``ops/grouped_matmul.py:quant_tile_for``) so the codec pads
    nothing and scales never straddle rows; biases, LayerNorms and
    ``wpe`` stay f32. The resulting tree is exactly what a
    ``weights_dtype``-configured ``GPT`` consumes (QuantDense /
    QuantEmbed param names) — a no-op at ``weights_dtype='f32'``."""
    wd = getattr(config, "weights_dtype", "f32")
    if wd == "f32":
        return params
    if wd not in ("int8", "int4"):
        raise ValueError(
            f"weights_dtype must be 'f32', 'int8' or 'int4', got {wd!r}")
    from ..ops.grouped_matmul import quant_tile_for
    from ..strategy.compress import QuantizeCodec
    bits = {"int8": 8, "int4": 4}[wd]
    tile = int(getattr(config, "quant_tile", 256))

    def q_leaf(w):
        t = quant_tile_for(w.shape, tile)
        codec = QuantizeCodec(bits=bits, tile=t, stochastic=False)
        q, scale = codec.compress(
            jnp.asarray(w, jnp.float32).reshape(-1), None)
        return q.reshape(w.shape), scale.reshape(-1)

    def walk(node, name=None):
        if not hasattr(node, "items"):
            return node
        d = dict(node)
        kern = d.get("kernel")
        if kern is not None and getattr(kern, "ndim", 0) == 2:
            q, scale = q_leaf(kern)
            out = {"qkernel": q, "qscale": scale}
            if "bias" in d:
                out["bias"] = jnp.asarray(d["bias"], jnp.float32)
            return out
        if (name == "wte" and getattr(config, "quant_embed", False)
                and "embedding" in d):
            q, scale = q_leaf(d["embedding"])
            return {"qembedding": q, "qscale": scale}
        return {k: walk(v, k) for k, v in d.items()}

    return walk(params)


def read_run_config(run_dir: str,
                    config_path: Optional[str] = None) -> Dict[str, Any]:
    """Load the run's captured ``config.json``. Looked up in the run dir
    itself (where the trainer writes it next to the step dirs); an
    explicit ``config_path`` overrides — e.g. for run dirs from before
    the snapshot existed, point at ``logs/<run_name>/config.json``."""
    path = config_path or os.path.join(run_dir, "config.json")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no config.json at {path} — pass config_path= (the CSVLogger "
            f"copy under logs/<run_name>/ works) or an explicit GPTConfig")
    with open(path) as f:
        return json.load(f)


def gpt_config_from_run(config: Dict[str, Any]) -> GPTConfig:
    """Rebuild the ``GPTConfig`` from a captured run config
    (``trainer._model_config`` flattens the module's nested ``config``
    dataclass into ``model_config.config``). Unknown keys are ignored so
    an older server binary can read a newer run's snapshot."""
    model_cfg = (config.get("model_config") or {}).get("config")
    if not isinstance(model_cfg, dict):
        raise ValueError(
            "config.json carries no model_config.config — was this run's "
            "model a GPT? (serving currently supports the GPT family)")
    fields = {f.name for f in dataclasses.fields(GPTConfig)}
    return GPTConfig(**{k: v for k, v in model_cfg.items() if k in fields})


def load_for_serving(run_dir: str, step: Optional[int] = None,
                     config: Optional[GPTConfig] = None,
                     config_path: Optional[str] = None,
                     weights_dtype: Optional[str] = None,
                     kv_dtype: Optional[str] = None,
                     quant_embed: bool = False
                     ) -> Tuple[PyTree, GPTConfig, Dict[str, Any]]:
    """Restore a ``fit()`` run dir for inference.

    Returns ``(params, config, info)``: the node-AVERAGED f32 param tree
    (device arrays), the run's ``GPTConfig`` (training sharding intact —
    the engine sanitizes via ``decode_config``), and an info dict
    (``step``, ``num_nodes``, the raw run config). ``config=`` skips the
    ``config.json`` lookup entirely (e.g. serving hand-built params).

    ``weights_dtype`` ('int8'/'int4') runs the quantize-at-load step —
    the returned params are the per-tile-quantized tree and the returned
    config carries the dtype (with ``quant_embed`` optionally extending
    quantization to the tied embedding/lm_head); ``kv_dtype`` ('int8')
    just stamps the config — the KV pools quantize online at decode.
    """
    if not os.path.isdir(run_dir):
        raise CheckpointNotFoundError(
            f"checkpoint run dir {run_dir} does not exist")
    raw: Dict[str, Any] = {}
    if config is None:
        raw = read_run_config(run_dir, config_path)
        config = gpt_config_from_run(raw)
    at_step, node_params, _extra = restore_params(run_dir, step=step)
    leaves = jax.tree.leaves(node_params)
    if not leaves:
        raise CheckpointNotFoundError(
            f"checkpoint step {at_step} under {run_dir} restored an "
            f"empty params tree")
    k = int(leaves[0].shape[0])
    want_k = raw.get("num_nodes")
    if want_k is not None and int(want_k) != k:
        raise ValueError(
            f"checkpoint params carry a [{k}]-node axis but config.json "
            f"says num_nodes={want_k} — wrong run dir / config pairing?")
    # node-average on device (the FitResult.params convention); params
    # are float, so a plain mean is exact in intent and f32 in practice
    avg = jax.jit(
        lambda t: jax.tree.map(lambda x: jnp.mean(x, axis=0), t)
    )(node_params)
    if weights_dtype or kv_dtype or quant_embed:
        config = dataclasses.replace(
            config,
            weights_dtype=weights_dtype or config.weights_dtype,
            kv_dtype=kv_dtype or config.kv_dtype,
            quant_embed=bool(quant_embed) or config.quant_embed)
        avg = quantize_params(avg, config)
    info = {"step": at_step, "num_nodes": k, "run_config": raw}
    return avg, config, info


# -- checkpoint-dir watching (fleet weight hot-swap) ----------------------


def latest_checkpoint_step(run_dir: str) -> Optional[int]:
    """Newest COMMITTED checkpoint step in a run dir, from directory
    names alone — cheap enough to poll. Orbax writes into a
    tmp-suffixed dir and renames on commit, and quarantined dirs carry
    a ``.corrupt-k`` suffix, so "committed" is exactly "the name is a
    bare integer". None when the dir is missing/empty (a trainer that
    has not checkpointed yet is not an error for a watcher)."""
    try:
        names = os.listdir(run_dir)
    except OSError:
        return None
    steps = [int(n) for n in names if n.isdigit()]
    return max(steps) if steps else None


class CheckpointWatcher:
    """Poll a trainer's run dir and fire ``on_new_step(step)`` whenever
    a NEWER committed checkpoint appears — the push half of the fleet's
    zero-downtime weight hot-swap (``python -m gym_tpu.serve
    --reload-watch S`` wires the callback to a rolling
    ``Router.reload``). Callback failures are logged, not fatal: a
    single unreadable checkpoint must not kill the watcher — the
    trainer's NEXT checkpoint gets its own attempt."""

    def __init__(self, run_dir: str,
                 on_new_step: Callable[[int], None],
                 poll_s: float = 10.0,
                 initial_step: Optional[int] = None):
        """``initial_step``: the step already being served — only
        strictly newer checkpoints fire (None = the first committed
        checkpoint seen fires)."""
        self.run_dir = run_dir
        self.on_new_step = on_new_step
        self.poll_s = float(poll_s)
        self.last_step = initial_step
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="gym-tpu-serve-ckpt-watcher",
            daemon=True)

    def start(self) -> "CheckpointWatcher":
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout=join_timeout_s)

    def poll_once(self) -> Optional[int]:
        """One poll (also the testable unit): fire the callback iff a
        newer step committed; returns the step fired, else None."""
        step = latest_checkpoint_step(self.run_dir)
        if step is None or (self.last_step is not None
                            and step <= self.last_step):
            return None
        self.last_step = step
        try:
            self.on_new_step(step)
        except Exception:  # noqa: BLE001 — a failed reload must not
            # kill the watcher; the next checkpoint retries
            sys.stderr.write(
                f"gym_tpu.serve: checkpoint watcher — on_new_step"
                f"({step}) raised:\n{traceback.format_exc()}")
        return step

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.poll_once()
