"""Self-healing driver loop: watchdog + engine rebuild on crash/wedge.

The scheduler's ``run`` loop (PR 4) dies with its engine: an exception
in a dispatch unwinds the driver thread and every in-flight future waits
forever; a WEDGED dispatch (hung XLA call, injected ``serve.decode:hang``)
is worse — nothing unwinds at all. The ``Supervisor`` wraps the loop
with the PR-2 resilience primitives so the HTTP server stays up through
an engine failure:

- every ``scheduler.step()`` runs inside a ``Watchdog.watch`` region
  (``serve.dispatch``); a dispatch that outlives ``dispatch_timeout_s``
  is declared wedged and the watchdog's callback triggers failover from
  its monitor thread — the stuck driver thread is ABANDONED, not joined
  (a thread hung inside a C call cannot be interrupted);
- failover: dump every thread's stack (the wedge evidence), fail all
  in-flight requests with a typed ``EngineFailedError`` (their KV-cache
  rows died with the engine), rebuild the engine via ``engine_factory``
  (the global prefill/decode program LRUs make this warm — same config,
  no recompiles), swap it into the scheduler, and start a fresh driver
  generation. Queued requests survive and resume on the new engine.
- the scheduler EPOCH (bumped by ``fail_inflight``) makes the abandoned
  thread harmless: when it finally wakes it finds the epoch advanced and
  discards its admissions and events instead of cross-talking with the
  new generation's slots.

``max_restarts`` bounds the crash loop: past it the supervisor declares
the engine unrecoverable, fails queued requests too, and stops — the
HTTP layer keeps answering (typed 503s), which is still better than a
silent hang.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Optional

from ..utils.resilience import Watchdog, dump_thread_stacks
from .engine import InferenceEngine
from .scheduler import EngineFailedError, Scheduler


class Supervisor:
    """Run the scheduler's driver loop under a watchdog; on an engine
    exception or wedged dispatch, fail in-flight requests typed, rebuild
    the engine, and resume the queue.

    One supervisor per scheduler. ``start()`` spawns the driver thread;
    ``stop()`` is the graceful half of shutdown (the caller then runs
    ``scheduler.shutdown`` for the drain semantics).
    """

    def __init__(self, scheduler: Scheduler,
                 engine_factory: Callable[[], InferenceEngine], *,
                 dispatch_timeout_s: float = 120.0,
                 max_restarts: int = 5,
                 metrics=None,
                 idle_wait_s: float = 0.005,
                 on_dead: Optional[Callable[[BaseException], None]] = None,
                 log=print):
        """``on_dead(error)`` fires once, AFTER the supervisor declares
        the engine unrecoverable (queued requests already failed typed,
        ``failed`` set) — the fleet router hooks it to pull the replica
        out of dispatch the moment it dies instead of on the next
        health poll."""
        self.scheduler = scheduler
        self.engine_factory = engine_factory
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self.max_restarts = int(max_restarts)
        self.metrics = metrics
        self.idle_wait_s = float(idle_wait_s)
        self.on_dead = on_dead
        self._log = log
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._gen = 0
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[Watchdog] = None
        self.restarts = 0
        self.failed: Optional[BaseException] = None  # set past max_restarts

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Supervisor":
        with self._lock:
            self._spawn_locked(self._gen)
        return self

    def _spawn_locked(self, gen: int) -> None:
        """Start the driver thread for generation ``gen`` with a FRESH
        watchdog (a Watchdog fires at most once by design)."""
        wd = Watchdog(self.dispatch_timeout_s,
                      on_timeout=lambda label, msg, g=gen:
                      self._failover(g, EngineFailedError(
                          f"dispatch wedged past "
                          f"{self.dispatch_timeout_s:.0f}s watchdog "
                          f"deadline ({label})"), wedged=True)).start()
        self._watchdog = wd
        t = threading.Thread(target=self._drive, args=(gen, wd),
                             name=f"gym-tpu-serve-driver-{gen}",
                             daemon=True)
        self._thread = t
        t.start()

    def _drive(self, gen: int, wd: Watchdog) -> None:
        sched = self.scheduler
        while not self._stop.is_set():
            with self._lock:
                if self._gen != gen:
                    return           # failed over past this generation
            try:
                with wd.watch("serve.dispatch"):
                    produced = sched.step()
            except Exception as e:  # noqa: BLE001 — ANY engine error
                # means this generation is over; the failover path
                # decides whether a rebuild is still allowed
                sys.stderr.write(
                    f"gym_tpu.serve: engine exception in driver "
                    f"generation {gen}:\n{traceback.format_exc()}")
                self._failover(gen, EngineFailedError(
                    f"engine raised {type(e).__name__}: {e}"),
                    wedged=False)
                return
            with self._lock:
                # re-check AFTER the step: a thread that was failed over
                # past while wedged inside the dispatch must not tick
                # metrics against the new generation's engine
                if self._gen != gen:
                    return
            if self.metrics is not None:
                self.metrics.engine_tick(
                    sched.engine.stats, queue_depth=sched.queue_depth())
            if produced == 0:
                self._stop.wait(self.idle_wait_s)
        wd.close()

    # -- failover ---------------------------------------------------------

    def _failover(self, gen: int, error: BaseException,
                  wedged: bool) -> None:
        """Fail in-flight typed, rebuild the engine, start the next
        generation. Runs on the dying driver thread (exception path) or
        the watchdog monitor thread (wedge path) — never both for one
        generation: the gen check under the lock deduplicates."""
        with self._lock:
            if self._gen != gen or self._stop.is_set():
                return               # stale or already shutting down
            self._gen += 1
            new_gen = self._gen
            self.restarts += 1
            restarts = self.restarts
            old_wd = self._watchdog
        if wedged:
            # the watchdog already dumped stacks when it fired; this line
            # ties the dump to the supervisor's decision in the log
            self._log(f"gym_tpu.serve: supervisor — driver generation "
                      f"{gen} wedged; abandoning its thread", flush=True)
        victims = self.scheduler.fail_inflight(error)
        self._log(f"gym_tpu.serve: supervisor — engine failure "
                  f"({error}); failed {len(victims)} in-flight "
                  f"request(s) typed, restart {restarts}/"
                  f"{self.max_restarts}", flush=True)
        if restarts > self.max_restarts:
            self._declare_dead(error)
            return
        try:
            t0 = time.perf_counter()
            engine = self.engine_factory()
            self._log(f"gym_tpu.serve: supervisor — engine rebuilt in "
                      f"{time.perf_counter() - t0:.2f}s (warm program "
                      f"cache), resuming queue "
                      f"(depth {self.scheduler.queue_depth()})",
                      flush=True)
        except Exception as e:  # noqa: BLE001 — a factory that cannot
            # rebuild (unreadable checkpoint, OOM) is unrecoverable
            sys.stderr.write(
                f"gym_tpu.serve: supervisor — engine rebuild FAILED:\n"
                f"{traceback.format_exc()}")
            self._declare_dead(e)
            return
        self.scheduler.replace_engine(engine)
        if self.metrics is not None:
            # counted HERE, after the swap: a terminal attempt that
            # never rebuilt must not inflate the restart observable
            self.metrics.engine_restarted()
        with self._lock:
            if self._stop.is_set():
                return
            self._spawn_locked(new_gen)
        if not wedged and old_wd is not None:
            old_wd.close()

    def _declare_dead(self, error: BaseException) -> None:
        # fail queued typed too — their futures must not wait forever
        self.scheduler.shutdown(finish_running=False, deadline_s=0.0)
        sys.stderr.write(dump_thread_stacks(
            f"gym_tpu.serve: supervisor — engine unrecoverable after "
            f"{self.restarts} restart(s) ({error}); failing queued "
            f"requests and stopping the driver:"))
        sys.stderr.flush()
        # set LAST: anyone who observes `failed` may rely on the
        # scheduler already refusing new work
        self.failed = error
        if self.on_dead is not None:
            try:
                self.on_dead(error)
            except Exception:  # noqa: BLE001 — a broken death observer
                # must not mask the death itself
                sys.stderr.write(
                    f"gym_tpu.serve: supervisor on_dead callback "
                    f"raised:\n{traceback.format_exc()}")

    # -- shutdown ---------------------------------------------------------

    def stop(self, join_timeout_s: float = 300.0) -> bool:
        """Signal the driver loop to exit and join it. Returns True when
        the driver exited (safe to run ``scheduler.shutdown`` from the
        caller); False means the driver is wedged mid-dispatch — do NOT
        touch the engine from another thread in that case."""
        self._stop.set()
        with self._lock:
            t, wd = self._thread, self._watchdog
        if t is not None:
            t.join(timeout=join_timeout_s)
        if wd is not None:
            wd.close()
        return t is None or not t.is_alive()

    @property
    def generation(self) -> int:
        with self._lock:
            return self._gen

    def status(self) -> dict:
        # engine_restarts (actual rebuilds) deliberately lives in
        # ServeMetrics — ONE source of truth for /stats; `restarts` here
        # counts failover ATTEMPTS (incl. a terminal one)
        return {"engine_generation": self.generation,
                "engine_dead": self.failed is not None}
