"""FCFS request scheduler over the slot engine.

The engine (``engine.py``) knows slots; this layer knows REQUESTS:

- ``submit``: thread-safe, backpressure-bounded — when the FCFS queue is
  full it blocks up to ``timeout`` for a drain (or raises
  ``QueueFullError`` immediately with ``block=False``). Requests that
  can never fit the KV cache are rejected at submit time with the same
  typed ``ValueError`` ``generate_fast`` raises.
- ``step``: one scheduling round, run by the single driver thread:
  admit queued requests into free slots (prefill), advance every active
  slot one token (the shared decode step), and complete/evict finished
  requests BETWEEN steps — continuous batching.
- ``Request``: the poll/wait surface — status, accumulated tokens, and a
  ``result(timeout)`` future; per-request TTFT/latency timestamps feed
  ``metrics.ServeMetrics``.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from .engine import InferenceEngine, SamplingParams


class QueueFullError(RuntimeError):
    """Backpressure signal: the FCFS queue is at capacity and the caller
    declined (or timed out) waiting for it to drain."""


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"


@dataclasses.dataclass
class Request:
    """A submitted generation request. ``tokens`` accumulates NEW tokens
    (the prompt is not echoed); timestamps are ``time.perf_counter()``."""

    id: int
    prompt: np.ndarray
    sampling: SamplingParams
    status: RequestStatus = RequestStatus.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    _event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request completes; returns the new tokens or
        raises ``RuntimeError`` (failed) / ``TimeoutError``."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} still "
                               f"{self.status.value} after {timeout}s")
        if self.status is RequestStatus.FAILED:
            raise RuntimeError(f"request {self.id} failed: {self.error}")
        return list(self.tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def avg_token_latency_s(self) -> Optional[float]:
        """Mean inter-token latency AFTER the first token (TTFT is its
        own observable)."""
        if (self.done_t is None or self.first_token_t is None
                or len(self.tokens) < 2):
            return None
        return (self.done_t - self.first_token_t) / (len(self.tokens) - 1)


class Scheduler:
    """FCFS queue + slot assignment. One driver thread calls ``step``
    (or ``run``); any number of threads call ``submit``."""

    def __init__(self, engine: InferenceEngine, max_queue: int = 64,
                 metrics=None):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._by_slot: Dict[int, Request] = {}
        self._ids = itertools.count()
        self._accepting = True

    # -- submit side ------------------------------------------------------

    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               block: bool = True,
               timeout: Optional[float] = 30.0) -> Request:
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.engine.validate(prompt, sampling)   # typed ValueError, early
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._drained:
            if not self._accepting:
                raise RuntimeError("scheduler is shutting down")
            while len(self._queue) >= self.max_queue:
                if not block:
                    raise QueueFullError(
                        f"request queue at capacity ({self.max_queue})")
                rem = None if deadline is None \
                    else deadline - time.perf_counter()
                if rem is not None and rem <= 0:
                    raise QueueFullError(
                        f"request queue still at capacity "
                        f"({self.max_queue}) after {timeout}s")
                self._drained.wait(rem)
                if not self._accepting:
                    raise RuntimeError("scheduler is shutting down")
            req = Request(id=next(self._ids), prompt=prompt,
                          sampling=sampling, submit_t=time.perf_counter())
            self._queue.append(req)
        return req

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def active_requests(self) -> int:
        with self._lock:
            return len(self._by_slot)

    # -- driver side ------------------------------------------------------

    def _admit_from_queue(self) -> int:
        admitted = 0
        while self.engine.free_slots():
            with self._drained:
                if not self._queue:
                    break
                req = self._queue.popleft()
                self._drained.notify_all()
            try:
                slot, ev = self.engine.admit(req.prompt, req.sampling)
            except Exception as e:  # noqa: BLE001 — a bad request must
                # fail ITSELF, not tear the serving loop down
                self._fail(req, f"{type(e).__name__}: {e}")
                continue
            req.status = RequestStatus.RUNNING
            req.first_token_t = time.perf_counter()
            req.tokens.append(ev.token)
            admitted += 1
            if ev.finished:
                self._complete(req)
            else:
                self._by_slot[slot] = req
        return admitted

    def step(self) -> int:
        """One scheduling round; returns the number of tokens produced
        (0 = idle). Admission happens BEFORE the decode step so a freed
        slot turns around within one round."""
        produced = self._admit_from_queue()
        events = self.engine.step()
        now = time.perf_counter()
        for ev in events:
            req = self._by_slot.get(ev.slot)
            if req is None:      # slot freed by a cancel between steps
                continue
            req.tokens.append(ev.token)
            produced += 1
            if ev.finished:
                del self._by_slot[ev.slot]
                self._complete(req, now)
        return produced

    def _complete(self, req: Request,
                  now: Optional[float] = None) -> None:
        req.done_t = now if now is not None else time.perf_counter()
        req.status = RequestStatus.DONE
        req._event.set()
        if self.metrics is not None:
            self.metrics.request_done(
                req, queue_depth=self.queue_depth(),
                active_slots=self.engine.stats.active_slots)

    def _fail(self, req: Request, error: str) -> None:
        req.error = error
        req.status = RequestStatus.FAILED
        req.done_t = time.perf_counter()
        req._event.set()
        if self.metrics is not None:
            self.metrics.request_done(
                req, queue_depth=self.queue_depth(),
                active_slots=self.engine.stats.active_slots)

    def run(self, stop: threading.Event, idle_wait_s: float = 0.005):
        """Drive ``step`` until ``stop`` is set; sleeps briefly when idle
        (no busy spin — submissions are picked up at the next round)."""
        while not stop.is_set():
            produced = self.step()
            if self.metrics is not None:
                self.metrics.engine_tick(
                    self.engine.stats, queue_depth=self.queue_depth())
            if produced == 0:
                stop.wait(idle_wait_s)

    def shutdown(self, finish_running: bool = True,
                 deadline_s: float = 300.0) -> None:
        """Graceful drain (the SIGTERM path): stop accepting, FAIL queued
        requests ("shutting down" — reported, not dropped), and either
        answer every in-flight request (``finish_running=True``, bounded
        by ``deadline_s``) or fail those too. Call from the driver thread
        or after the driver loop has stopped."""
        with self._drained:
            self._accepting = False
            queued = list(self._queue)
            self._queue.clear()
            self._drained.notify_all()
        for req in queued:
            self._fail(req, "server shutting down before this request "
                            "was scheduled")
        if finish_running:
            deadline = time.perf_counter() + deadline_s
            while self._by_slot and time.perf_counter() < deadline:
                self.step()
        for slot, req in list(self._by_slot.items()):
            self.engine.release(slot)
            del self._by_slot[slot]
            self._fail(req, "server shut down mid-generation")
