"""FCFS request scheduler over the slot engine.

The engine (``engine.py``) knows slots; this layer knows REQUESTS:

- ``submit``: thread-safe, backpressure-bounded — when the FCFS queue is
  full it blocks up to ``timeout`` for a drain (or raises
  ``QueueFullError`` immediately with ``block=False``). Requests that
  can never fit the KV cache are rejected at submit time with the same
  typed ``ValueError`` ``generate_fast`` raises. Requests that carry a
  ``deadline_s`` the engine provably cannot meet — estimated from the
  live tokens/s EWMA and the current backlog — are rejected typed
  (``AdmissionRejectedError``, with a ``retry_after_s`` hint) instead of
  being enqueued to time out: admission control / load shedding.
- ``step``: one scheduling round, run by the single driver thread:
  shed queued requests past their deadline (before prefill), admit
  queued requests into free slots (prefill; PREFIX-AWARE within a
  bounded lookahead window — a request whose prompt prefix is resident
  in the paged engine's prefix cache is admitted ahead of its FCFS turn
  so shared-prefix bursts hit the cache before eviction churn loses
  them), advance every active slot one token (the shared decode step),
  and complete/evict finished requests BETWEEN steps — continuous
  batching. Running requests past
  their deadline are cancelled at the chunk boundary and their slot
  freed; a slot the engine quarantined (NaN/Inf logits) fails only its
  own request.
- ``Request``: the poll/wait surface — status, accumulated tokens, and a
  ``result(timeout)`` future; per-request TTFT/latency stamps feed
  ``metrics.ServeMetrics``. Failures carry their TYPED exception
  (``Request.exception``), which ``result`` re-raises — callers branch
  on class, not on string matching.

Engine failover (``supervisor.Supervisor``) uses two hooks:
``fail_inflight`` (fail every running request typed, bump the scheduler
EPOCH) and ``replace_engine``. The epoch makes failover safe against a
WEDGED driver thread: a stale ``step`` that finally wakes from a hung
dispatch finds the epoch advanced and discards its admissions and
events instead of corrupting the rebuilt engine's slot bookkeeping.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..utils.resilience import fault_point
from .engine import InferenceEngine, NoFreeBlocksError, SamplingParams


class QueueFullError(RuntimeError):
    """Backpressure signal: the FCFS queue is at capacity and the caller
    declined (or timed out) waiting for it to drain."""


class SchedulerClosedError(RuntimeError):
    """Typed "scheduler is shutting down": raised by ``submit`` after
    ``shutdown()`` and stored on requests failed by the drain. Subclasses
    ``RuntimeError`` so pre-existing callers that caught the bare
    ``RuntimeError`` keep working."""


class DeadlineExceededError(RuntimeError):
    """The request's ``deadline_s`` elapsed: shed from the queue before
    prefill, or cancelled at a decode-chunk boundary while running."""


class AdmissionRejectedError(RuntimeError):
    """Load shedding at ``submit``: the live tokens/s EWMA says this
    request cannot finish inside its ``deadline_s``, so it is rejected
    up front instead of queued to die. ``retry_after_s`` estimates when
    the current backlog will have drained."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class QuotaExceededError(AdmissionRejectedError):
    """Per-class token-rate quota exhausted at ``submit``: the request's
    ``slo_class`` refill bucket cannot cover its committed tokens right
    now. Subclasses ``AdmissionRejectedError`` so every existing
    429 + ``Retry-After`` surface — the HTTP handler, the router's
    cheapest-reject ladder, the wire frames — applies unchanged."""


class EngineFailedError(RuntimeError):
    """The engine crashed or wedged under this request: its in-flight
    generation cannot be recovered (the KV cache died with the engine).
    The supervisor rebuilds the engine; RETRYING the request is safe."""


class SlotQuarantinedError(RuntimeError):
    """The engine detected non-finite (NaN/Inf) logits in this request's
    slot and quarantined it — only this request fails; neighbor slots
    are row-isolated by the model's per-row cache math."""


class RequestFailedError(RuntimeError):
    """Fallback for a request failed with only a string reason (no typed
    exception was stored) — ``Request.result`` re-raises the stored
    TYPED exception whenever one exists."""


class RequestCancelledError(RuntimeError):
    """The request was cancelled by its caller — in practice: the HTTP
    client disconnected mid-stream. The generation stops at the next
    decode-chunk boundary and the slot is freed; ``serve.csv`` records
    ``status=disconnected`` (not a failure, not a traceback)."""


#: Known SLO classes in priority order (most urgent first). Requests
#: that name an unknown class are rejected at submit with a typed
#: ValueError (HTTP 400) — a typo'd class silently mapping to a default
#: priority would be an isolation hole.
SLO_CLASSES = ("interactive", "standard", "batch")
DEFAULT_SLO_CLASS = "standard"
DEFAULT_TENANT = "default"
#: Admission priority (lower = more urgent). Preemption only ever runs
#: in favor of a STRICTLY more urgent class, so same-class traffic can
#: never thrash slots back and forth.
CLASS_PRIORITY = {"interactive": 0, "standard": 1, "batch": 2}
#: Weighted-fair-queuing weights: a tenant's virtual finish time
#: advances at cost/weight, so at equal sustained demand an interactive
#: tenant receives 8x a batch tenant's token share.
CLASS_WEIGHT = {"interactive": 8.0, "standard": 4.0, "batch": 1.0}


@dataclasses.dataclass(frozen=True)
class ClassQuota:
    """Refill-bucket token quota for one SLO class. Exactly one of
    ``tokens_per_s`` (absolute refill rate) or ``share`` (fraction of
    the live ``tokens_per_s_ewma`` — the bucket refills at a slice of
    whatever the engine is actually delivering) must be set.
    ``burst_s`` sizes the bucket: a class may burst up to ``burst_s``
    seconds of its refill rate before the rate limit bites."""

    tokens_per_s: Optional[float] = None
    share: Optional[float] = None
    burst_s: float = 2.0

    def __post_init__(self):
        if (self.tokens_per_s is None) == (self.share is None):
            raise ValueError(
                "ClassQuota: set exactly one of tokens_per_s / share")
        if self.tokens_per_s is not None and not self.tokens_per_s > 0:
            raise ValueError(
                f"tokens_per_s must be > 0, got {self.tokens_per_s}")
        if self.share is not None and not 0 < self.share <= 1:
            raise ValueError(
                f"share must be in (0, 1], got {self.share}")
        if not self.burst_s > 0:
            raise ValueError(f"burst_s must be > 0, got {self.burst_s}")


class _TokenBucket:
    """Lazy-refill token bucket with an injectable clock (tests pin
    refill determinism by stepping a fake clock; production uses
    ``time.monotonic``). Called under the scheduler lock — no locking
    of its own."""

    def __init__(self, quota: ClassQuota, clock=time.monotonic):
        self.quota = quota
        self._clock = clock
        self._level: Optional[float] = None   # None = start full
        self._last = 0.0

    def _rate(self, ewma: Optional[float]) -> Optional[float]:
        """Resolve the refill rate in tokens/s; None = unenforceable
        right now (share-based quota on a cold engine with no EWMA —
        optimistic, the same stance the deadline admission takes)."""
        if self.quota.tokens_per_s is not None:
            return float(self.quota.tokens_per_s)
        if ewma is None or ewma <= 0:
            return None
        return float(self.quota.share) * float(ewma)

    def _refill(self, rate: float) -> float:
        cap = rate * self.quota.burst_s
        now = self._clock()
        if self._level is None:
            self._level = cap
        else:
            self._level = min(cap, self._level
                              + (now - self._last) * rate)
        self._last = now
        return cap

    def try_take(self, n: int,
                 ewma: Optional[float]) -> Tuple[bool, float]:
        """``(admitted, retry_after_s)``. A request larger than the
        whole bucket is admitted whenever the bucket is FULL (its level
        goes negative, which enforces the long-run rate) — otherwise a
        single big request could never pass and would starve forever
        instead of being rate-limited."""
        rate = self._rate(ewma)
        if rate is None:
            return True, 0.0
        cap = self._refill(rate)
        need = float(n)
        if self._level >= min(need, cap):
            self._level -= need
            return True, 0.0
        return False, max(0.05, (min(need, cap) - self._level) / rate)

    def fill_fraction(self, ewma: Optional[float]) -> Optional[float]:
        """Live bucket fill in [0, 1] for ``/stats`` (None when the
        rate is unresolvable). Refills as a side effect — harmless: the
        level is a function of elapsed time either way."""
        rate = self._rate(ewma)
        if rate is None:
            return None
        cap = self._refill(rate)
        return max(0.0, min(1.0, self._level / cap)) if cap else None


class RequestStatus(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"


@dataclasses.dataclass
class Request:
    """A submitted generation request. ``tokens`` accumulates NEW tokens
    (the prompt is not echoed); timestamps are ``time.perf_counter()``."""

    id: int
    prompt: np.ndarray
    sampling: SamplingParams
    deadline_s: Optional[float] = None
    tenant: str = DEFAULT_TENANT
    slo_class: str = DEFAULT_SLO_CLASS
    status: RequestStatus = RequestStatus.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    exception: Optional[BaseException] = None
    submit_t: float = 0.0
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    preemptions: int = 0                  # times parked mid-decode
    _wfq_start: float = dataclasses.field(default=0.0, repr=False)
    _wfq_finish: float = dataclasses.field(default=0.0, repr=False)
    _event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)
    _progress: threading.Condition = dataclasses.field(
        default_factory=threading.Condition, repr=False)

    def _notify_progress(self) -> None:
        """Wake streamers: new tokens appended or the request resolved.
        Called by the scheduler after every mutation a streaming reader
        cares about (its own Condition — never the scheduler lock)."""
        with self._progress:
            self._progress.notify_all()

    def wait_progress(self, seen: int,
                      timeout: Optional[float] = None
                      ) -> Tuple[List[int], bool]:
        """Block until the request holds MORE than ``seen`` tokens or
        reaches a terminal state (or ``timeout`` elapses — not an
        error: streaming pollers re-arm). Returns ``(tokens snapshot,
        terminal)``. The streaming read surface: a streamer keeps its
        own cursor, calls with it, and ships ``snapshot[seen:]`` —
        token chunks arrive at decode-chunk granularity because that is
        when the driver appends. Terminal FAILED is NOT raised here;
        the caller branches on ``status``/``exception`` so a streaming
        failover can splice instead of unwinding."""
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        with self._progress:
            while (len(self.tokens) <= seen
                   and not self._event.is_set()):
                rem = (None if deadline is None
                       else deadline - time.perf_counter())
                if rem is not None and rem <= 0:
                    break
                self._progress.wait(rem)
        return list(self.tokens), self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request completes; returns the new tokens or
        raises the TYPED failure (``DeadlineExceededError``,
        ``EngineFailedError``, ``SlotQuarantinedError``,
        ``SchedulerClosedError`` — all ``RuntimeError`` subclasses) /
        ``TimeoutError``."""
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} still "
                               f"{self.status.value} after {timeout}s")
        if self.status is RequestStatus.FAILED:
            if self.exception is not None:
                raise self.exception
            raise RequestFailedError(
                f"request {self.id} failed: {self.error}")
        return list(self.tokens)

    @property
    def deadline_t(self) -> Optional[float]:
        """Absolute ``perf_counter`` deadline (None = no deadline)."""
        if self.deadline_s is None:
            return None
        return self.submit_t + self.deadline_s

    @property
    def priority(self) -> int:
        """Admission priority from the SLO class (lower = more
        urgent); unknown classes rank as the default class."""
        return CLASS_PRIORITY.get(self.slo_class,
                                  CLASS_PRIORITY[DEFAULT_SLO_CLASS])

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def avg_token_latency_s(self) -> Optional[float]:
        """Mean inter-token latency AFTER the first token (TTFT is its
        own observable)."""
        if (self.done_t is None or self.first_token_t is None
                or len(self.tokens) < 2):
            return None
        return (self.done_t - self.first_token_t) / (len(self.tokens) - 1)


class Scheduler:
    """FCFS queue + slot assignment. One driver thread calls ``step``
    (or ``run``); any number of threads call ``submit``."""

    def __init__(self, engine: InferenceEngine, max_queue: int = 64,
                 metrics=None, prefix_window: int = 8,
                 starvation_rounds: int = 128,
                 quotas: Optional[Dict[str, ClassQuota]] = None,
                 preempt: bool = False, max_preemptions: int = 4,
                 quota_clock=time.monotonic):
        """``prefix_window``: how many queued requests the admit step may
        look ahead to prefer one whose prompt prefix is RESIDENT in the
        paged engine's prefix cache (most resident blocks win, FCFS
        breaks ties — so an unpaged engine, where every score is 0,
        keeps exact FCFS order). 1 = strict FCFS.

        ``starvation_rounds``: anti-starvation bound for the paged
        block pool — once the HEAD request has been passed over this
        many scheduling rounds for lack of blocks (while smaller
        requests kept admitting and re-pinning them), admission stops
        entirely until running slots drain and the head fits. Without
        it a large-block-need request could wait unboundedly under a
        sustained stream of small ones.

        ``quotas``: per-``slo_class`` refill-bucket token quotas
        (``ClassQuota``); a submit whose class bucket is dry fails
        typed ``QuotaExceededError`` (→ HTTP 429 + Retry-After). None
        (the default) disables quota enforcement entirely.

        ``preempt``: allow a STRICTLY more urgent queued request to
        park the least urgent running slot at a chunk boundary (paged
        engines only — parking is a host-side snapshot over pinned
        pages). The parked request keeps its ``Request`` object and
        stream; it resumes byte-identical once pressure clears, bounded
        by ``max_preemptions`` parks per request and the same
        ``starvation_rounds`` anti-starvation contract as the queue
        head. ``quota_clock`` injects the bucket clock for
        deterministic tests."""
        self.engine = engine
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self.prefix_window = max(1, int(prefix_window))
        self.starvation_rounds = max(1, int(starvation_rounds))
        self._head_skip_id: Optional[int] = None
        self._head_skips = 0
        self.quotas: Dict[str, ClassQuota] = dict(quotas or {})
        self._buckets = {cls: _TokenBucket(q, quota_clock)
                         for cls, q in self.quotas.items()}
        self.preempt = bool(preempt)
        self.max_preemptions = max(0, int(max_preemptions))
        # parked (preempted) requests, oldest first: (Request, the
        # engine's ParkedSlot snapshot). Parked requests stay RUNNING —
        # their stream simply pauses and later resumes byte-identical.
        self._parked: List[Tuple[Request, Any]] = []
        self._parked_skip_id: Optional[int] = None
        self._parked_skips = 0
        self.preemptions = 0               # slots parked (cumulative)
        self.resumes = 0                   # parked snapshots resumed
        self.quota_rejections: Dict[str, int] = {}
        # start-time-fair-queuing state: the system virtual time
        # advances to the start tag of each admitted request; a
        # tenant's next request starts at max(vtime, its last finish)
        self._vtime = 0.0
        self._tenant_finish: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._by_slot: Dict[int, Request] = {}
        self._ids = itertools.count()
        self._accepting = True
        self._shutdown_done = False
        self._epoch = 0
        # weight hot-swap support (serve/router.py): while paused, step()
        # keeps decoding the running slots but admits nothing new, so a
        # draining replica quiesces under sustained queued traffic
        self._admission_paused = False
        # queued requests carrying a deadline — lets the per-step shed
        # sweep early-out to one integer check in the (common)
        # no-deadline deployment instead of an O(queue) scan
        self._queued_deadlines = 0
        # the request popped from the queue but not yet placed in
        # _by_slot (the driver is inside engine.admit): failover and
        # shutdown must be able to fail it too — it is in NEITHER
        # collection while the prefill runs
        self._admitting: Optional[Request] = None
        # request ids cancelled by their caller (client disconnect):
        # swept at the next decode-chunk boundary alongside the
        # deadline cancellations — the single-driver contract means a
        # cancel can NEVER touch the engine from the caller's thread
        self._cancelled: set = set()

    # -- submit side ------------------------------------------------------

    def _estimate_service_s(self, max_new: int) -> Optional[float]:
        """Seconds until a request submitted NOW would finish, from the
        live tokens/s EWMA (``metrics``) and the tokens already committed
        ahead of it (queued max_new + remaining of running). Aggregate
        rate over total pending tokens is the right model for a slot
        batch: the engine serves the whole backlog concurrently at the
        EWMA rate. ``None`` when no rate is established yet (cold
        engine) — admission is then optimistic and the deadline is
        enforced downstream by shedding/cancellation."""
        if self.metrics is None:
            return None
        rate = self.metrics.tokens_per_s_ewma()
        if rate is None or rate <= 0:
            return None
        backlog = sum(r.sampling.max_new_tokens for r in self._queue)
        backlog += sum(
            max(0, r.sampling.max_new_tokens - len(r.tokens))
            for r in self._by_slot.values())
        backlog += sum(
            max(0, r.sampling.max_new_tokens - len(r.tokens))
            for r, _parked in self._parked)
        return (backlog + max_new) / rate

    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               block: bool = True, timeout: Optional[float] = 30.0,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               slo_class: Optional[str] = None) -> Request:
        fault_point("serve.admit")
        t_entry = time.perf_counter()
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.engine.validate(prompt, sampling)   # typed ValueError, early
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        slo_class = (DEFAULT_SLO_CLASS if slo_class is None
                     else str(slo_class))
        if slo_class not in CLASS_PRIORITY:
            raise ValueError(
                f"unknown slo_class {slo_class!r} (known: "
                f"{', '.join(SLO_CLASSES)})")
        if deadline_s is not None and not deadline_s > 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}); omit it for "
                f"no deadline")
        # the deadline clock starts at submit ENTRY and also caps the
        # queue-full blocking wait — "bounds the request end to end"
        # must include time spent waiting for queue space
        cap = timeout
        if deadline_s is not None:
            cap = deadline_s if cap is None else min(cap, deadline_s)
        wait_deadline = None if cap is None else t_entry + cap
        with self._drained:
            if not self._accepting:
                raise SchedulerClosedError("scheduler is shutting down")
            bucket = self._buckets.get(slo_class)
            if bucket is not None:
                ewma = (self.metrics.tokens_per_s_ewma()
                        if self.metrics is not None else None)
                ok, retry = bucket.try_take(
                    sampling.max_new_tokens, ewma)
                if not ok:
                    self.quota_rejections[slo_class] = \
                        self.quota_rejections.get(slo_class, 0) + 1
                    if self.metrics is not None:
                        self.metrics.request_rejected(
                            queue_depth=len(self._queue),
                            active_slots=self.engine.stats.active_slots,
                            tenant=tenant, slo_class=slo_class)
                    raise QuotaExceededError(
                        f"slo_class={slo_class} token quota exhausted: "
                        f"{sampling.max_new_tokens} committed tokens "
                        f"exceed the class refill bucket — retry after "
                        f"{retry:.2g}s", retry_after_s=retry)
            if deadline_s is not None:
                est = self._estimate_service_s(sampling.max_new_tokens)
                if est is not None and est > deadline_s:
                    if self.metrics is not None:
                        self.metrics.request_rejected(
                            queue_depth=len(self._queue),
                            active_slots=self.engine.stats.active_slots,
                            tenant=tenant, slo_class=slo_class)
                    raise AdmissionRejectedError(
                        f"deadline_s={deadline_s:.3g} infeasible: estimated "
                        f"service time {est:.3g}s at the current "
                        f"{self.metrics.tokens_per_s_ewma() or 0.0:.1f} "
                        f"tok/s — shed at admission",
                        retry_after_s=max(0.1, est - deadline_s))
            while len(self._queue) >= self.max_queue:
                if not block:
                    raise QueueFullError(
                        f"request queue at capacity ({self.max_queue})")
                rem = None if wait_deadline is None \
                    else wait_deadline - time.perf_counter()
                if rem is not None and rem <= 0:
                    raise QueueFullError(
                        f"request queue still at capacity "
                        f"({self.max_queue}) after {cap}s")
                self._drained.wait(rem)
                if not self._accepting:
                    raise SchedulerClosedError("scheduler is shutting down")
            req = Request(id=next(self._ids), prompt=prompt,
                          sampling=sampling, deadline_s=deadline_s,
                          tenant=tenant, slo_class=slo_class,
                          submit_t=t_entry)
            # start-time fair queuing tags (arrival-stamped): start at
            # max(system virtual time, this tenant's last finish);
            # finish advances by cost/weight — the weighted-fair share
            w = CLASS_WEIGHT.get(slo_class, 1.0)
            cost = float(prompt.size + sampling.max_new_tokens)
            req._wfq_start = max(self._vtime,
                                 self._tenant_finish.get(tenant, 0.0))
            req._wfq_finish = req._wfq_start + cost / w
            self._tenant_finish[tenant] = req._wfq_finish
            self._queue.append(req)
            if deadline_s is not None:
                self._queued_deadlines += 1
        return req

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def active_requests(self) -> int:
        with self._lock:
            return len(self._by_slot)

    def inflight(self) -> int:
        """Requests the engine currently holds state for: running slots,
        parked (preempted — their pages stay pinned) and one
        mid-``admit``. Queued requests do NOT count — they carry no
        engine state and survive an engine swap untouched. The router's
        rolling weight reload waits for this to reach 0."""
        with self._lock:
            return (len(self._by_slot) + len(self._parked)
                    + (1 if self._admitting is not None else 0))

    def backlog_tokens(self) -> int:
        """Committed future work in tokens (queued max_new + remaining of
        running/parked + mid-admission) — the router's least-loaded
        dispatch score. Same accounting as ``_estimate_service_s``'s
        backlog."""
        with self._lock:
            t = sum(r.sampling.max_new_tokens for r in self._queue)
            t += sum(max(0, r.sampling.max_new_tokens - len(r.tokens))
                     for r in self._by_slot.values())
            t += sum(max(0, r.sampling.max_new_tokens - len(r.tokens))
                     for r, _parked in self._parked)
            if self._admitting is not None:
                t += self._admitting.sampling.max_new_tokens
            return t

    def backlog_tokens_by_class(self) -> Dict[str, int]:
        """``backlog_tokens`` split by ``slo_class`` — the router's
        class-aware dispatch input: a replica drowning in preemptible
        batch backlog is still a fine home for interactive traffic."""
        with self._lock:
            return self._backlog_by_class_locked()

    def _backlog_by_class_locked(self) -> Dict[str, int]:
        out: Dict[str, int] = {}

        def add(req: Request, tokens: int) -> None:
            out[req.slo_class] = out.get(req.slo_class, 0) + tokens

        for r in self._queue:
            add(r, r.sampling.max_new_tokens)
        for r in self._by_slot.values():
            add(r, max(0, r.sampling.max_new_tokens - len(r.tokens)))
        for r, _parked in self._parked:
            add(r, max(0, r.sampling.max_new_tokens - len(r.tokens)))
        if self._admitting is not None:
            add(self._admitting,
                self._admitting.sampling.max_new_tokens)
        return out

    def tenant_snapshot(self) -> Dict[str, Union[int, Dict]]:
        """Live multi-tenant observables for ``/stats``: per-class
        quota fill (None = unresolvable/cold), preempt/resume/rejection
        counters, parked depth and the per-class backlog."""
        ewma = (self.metrics.tokens_per_s_ewma()
                if self.metrics is not None else None)
        with self._lock:
            fills = {cls: b.fill_fraction(ewma)
                     for cls, b in self._buckets.items()}
            return {
                "preemptions": self.preemptions,
                "resumes": self.resumes,
                "parked": len(self._parked),
                "quota_rejections": dict(self.quota_rejections),
                "quota_fill": fills,
                "backlog_by_class": self._backlog_by_class_locked(),
            }

    # -- caller-side cancellation (client disconnect) ---------------------

    def cancel(self, req: Request,
               reason: str = "client disconnected") -> bool:
        """Cancel ``req`` on behalf of its caller (the HTTP handler saw
        EPIPE mid-stream). A QUEUED request is failed immediately (it
        holds no engine state); a RUNNING one is flagged and the driver
        cancels it at the NEXT decode-chunk boundary — same mechanics,
        same granularity as deadline cancellation — freeing the slot.
        Returns True if the cancel took (False: already resolved). The
        stored failure is ``RequestCancelledError``, which metrics maps
        to ``status=disconnected``."""
        queued = False
        with self._drained:
            if req.status in (RequestStatus.DONE, RequestStatus.FAILED):
                return False
            if req in self._queue:
                self._queue.remove(req)
                if req.deadline_s is not None:
                    self._queued_deadlines -= 1
                self._drained.notify_all()
                queued = True
            else:
                # running, or mid-admission (it will be RUNNING by the
                # time the driver's next sweep sees the flag)
                self._cancelled.add(req.id)
        if queued:
            self._fail(req, RequestCancelledError(
                f"request {req.id} cancelled while queued — {reason}"))
        return True

    # -- admission pause (rolling weight hot-swap) ------------------------

    def pause_admission(self) -> None:
        """Stop admitting queued requests into slots (running slots keep
        decoding to completion; submits still enqueue). The router pauses
        a replica, waits for ``inflight() == 0``, swaps the engine, then
        ``resume_admission`` — queued requests admit onto the NEW
        engine, which is what makes the weight swap zero-downtime."""
        with self._lock:
            self._admission_paused = True

    def resume_admission(self) -> None:
        with self._lock:
            self._admission_paused = False

    # -- driver side ------------------------------------------------------

    def _shed_expired_queued(self, now: float) -> List[Request]:
        """Remove queued requests whose deadline already passed — shed
        BEFORE prefill, even when every slot is busy (an expired request
        must not wait for a free slot just to be told it is late)."""
        shed: List[Request] = []
        with self._drained:
            if not self._queued_deadlines:
                return shed
            keep = deque()
            for req in self._queue:
                dl = req.deadline_t
                if dl is not None and now > dl:
                    shed.append(req)
                else:
                    keep.append(req)
            if shed:
                self._queue = keep
                self._queued_deadlines -= len(shed)
                self._drained.notify_all()
        return shed

    def _pick_admit_index(self, engine: InferenceEngine) -> Optional[int]:
        """Index of the next queued request to admit (caller holds the
        lock).

        SINGLE tenant queued (the default deployment): FCFS, except
        that within the first ``prefix_window`` queued requests the one
        with the most prompt-prefix blocks RESIDENT in the paged
        engine's prefix cache wins (FCFS breaks ties) — admit ordering
        is the cheapest way to turn shared-prefix bursts into cache
        hits before eviction churn loses them.

        MULTIPLE tenants queued: weighted-fair queuing — the candidates
        are each tenant's OLDEST queued request (per-tenant FIFO, so a
        flooding tenant cannot push a quiet tenant's head out of any
        bounded window) and the earliest virtual finish tag wins, with
        the resident-prefix score as a bounded tie-break and FCFS after
        that. At one tenant the candidate set and scoring degrade to
        exactly the single-tenant path above.

        Requests the block pool cannot serve right now are passed over
        (running slots will free their blocks; ``engine.validate``
        guarantees every queued request fits an idle pool) — bounded by
        the starvation guard: once the HEAD request has been passed
        over ``starvation_rounds`` times — whether for lack of blocks
        OR because hotter-prefix/fairer requests kept outscoring it —
        it is the only admissible choice: admit it, or (if the pool
        still can't serve it) admit nothing until the pool drains.
        None = admit nothing this round."""
        head = self._queue[0]
        if self._head_skip_id != head.id:
            self._head_skip_id, self._head_skips = head.id, 0
        starved = self._head_skips > self.starvation_rounds
        # candidate set: each tenant's first queued request; one tenant
        # present → the first prefix_window requests (the PR-7 window)
        tenant_heads: Dict[str, Tuple[int, Request]] = {}
        for i, req in enumerate(self._queue):
            if req.tenant not in tenant_heads:
                tenant_heads[req.tenant] = (i, req)
        wfq = len(tenant_heads) > 1
        if wfq:
            candidates = sorted(tenant_heads.values())
        else:
            candidates = list(enumerate(
                itertools.islice(self._queue, self.prefix_window)))
        best, best_key, head_ok = None, None, False
        for i, req in candidates:
            ok, score = engine.admit_probe(req.prompt, req.sampling)
            if i == 0:
                head_ok = ok
                if starved:
                    break        # the head's turn: it or nothing
            if not ok:
                continue
            # min() keys: WFQ ranks by virtual finish first; the
            # single-tenant key is (-score, i) — most resident blocks,
            # FCFS ties — the exact pre-tenant ordering
            key = ((req._wfq_finish, -score, i) if wfq
                   else (-score, i))
            if best_key is None or key < best_key:
                best, best_key = i, key
        if starved:
            best = 0 if head_ok else None
        if best == 0:
            self._head_skips = 0
        else:
            self._head_skips += 1
        return best

    def _admit_from_queue(self, epoch: int,
                          engine: InferenceEngine) -> int:
        admitted = 0
        while engine.free_slots():
            with self._drained:
                # _admission_paused re-checked HERE, not just in step()'s
                # snapshot: it shares this lock with pause_admission, so
                # once the router has paused and observed inflight()==0,
                # no driver iteration — however stale its snapshot — can
                # still pop a request into the about-to-be-swapped engine
                if (self._epoch != epoch or self._admission_paused
                        or not self._queue):
                    break
                idx = self._pick_admit_index(engine)
                if idx is None:
                    break          # block pool busy: admit next round
                req = self._queue[idx]
                del self._queue[idx]
                # SFQ virtual time: advance to the admitted request's
                # start tag so idle tenants re-enter at the live edge
                self._vtime = max(self._vtime, req._wfq_start)
                if req.deadline_s is not None:
                    self._queued_deadlines -= 1
                self._admitting = req
                self._drained.notify_all()
            dl = req.deadline_t
            if dl is not None and time.perf_counter() > dl:
                # expired between the shed sweep and this pop
                with self._lock:
                    if self._admitting is req:
                        self._admitting = None
                self._fail(req, DeadlineExceededError(
                    f"deadline_s={req.deadline_s:.3g} elapsed in queue — "
                    f"shed before prefill"))
                continue
            try:
                slot, ev = engine.admit(req.prompt, req.sampling)
            except NoFreeBlocksError:
                # transient paged-pool shortage that appeared between the
                # capacity probe and admit — reinsert at the ORIGINAL
                # queue position (the request is fine; the blocks aren't
                # there yet; jumping older requests would also perturb
                # the starvation guard's head tracking). Positions ahead
                # of idx only ever shrink via this driver thread, so the
                # clamp preserves relative order. Skipped when a
                # failover raced us: fail_inflight already owns the
                # in-admission request's resolution.
                with self._drained:
                    mine = self._admitting is req
                    if mine:
                        self._admitting = None
                    if mine and self._epoch == epoch:
                        self._queue.insert(min(idx, len(self._queue)),
                                           req)
                        if req.deadline_s is not None:
                            self._queued_deadlines += 1
                break
            except Exception as e:  # noqa: BLE001 — a bad request must
                # fail ITSELF, not tear the serving loop down
                with self._lock:
                    if self._admitting is req:
                        self._admitting = None
                self._fail(req, e)
                continue
            with self._lock:
                # clear only OUR marker: a stale waking driver must not
                # wipe the live generation's in-admission request
                if self._admitting is req:
                    self._admitting = None
                stale = self._epoch != epoch
                # a failover/shutdown may have failed this request while
                # we were inside admit — never resurrect a resolved one
                resolved = req.status in (RequestStatus.DONE,
                                          RequestStatus.FAILED)
                if not stale and not resolved:
                    req.status = RequestStatus.RUNNING
                    req.first_token_t = time.perf_counter()
                    req.tokens.append(ev.token)
                    admitted += 1
                    if not ev.finished:
                        self._by_slot[slot] = req
            if not stale and not resolved:
                req._notify_progress()     # first token: wake streamers
            if resolved and not stale:
                engine.release(slot)   # same engine; free the row
                continue
            if stale:
                # the engine was replaced mid-admit (supervisor failover):
                # this prefill went into the DEAD engine
                self._fail(req, EngineFailedError(
                    "engine replaced during admission (supervisor "
                    "failover) — retry"))
                break
            if ev.finished:
                self._complete(req)
        return admitted

    # -- preemptible decode (driver side) ---------------------------------

    def _preempt_for_queued(self, epoch: int,
                            engine: InferenceEngine) -> None:
        """Park the least urgent running slot when a STRICTLY more
        urgent request is queued and no slot is free — at most one park
        per scheduling round (the driver loop converges within a few
        chunks under a flood; one-at-a-time keeps each round bounded).
        Chunk-boundary semantics for free: this runs between engine
        dispatches. The victim keeps its ``Request`` (stream pauses),
        is bounded by ``max_preemptions`` parks, and its pages stay
        pinned for the byte-identical resume."""
        if not engine.paged or engine.free_slots():
            return
        victim = None
        with self._lock:
            if self._epoch != epoch or not self._queue:
                return
            urgent = min(r.priority for r in self._queue)
            cands = [(slot, req) for slot, req in self._by_slot.items()
                     if req.priority > urgent
                     and req.preemptions < self.max_preemptions]
            if not cands:
                return
            # least urgent class first; most remaining work second (the
            # slot that would hold its pages/slot hostage the longest)
            slot, victim = max(cands, key=lambda it: (
                it[1].priority,
                it[1].sampling.max_new_tokens - len(it[1].tokens)))
            parked = engine.park(slot)
            del self._by_slot[slot]
            victim.preemptions += 1
            self._parked.append((victim, parked))
            self.preemptions += 1
        if self.metrics is not None:
            self.metrics.request_preempted(
                victim, queue_depth=self.queue_depth(),
                active_slots=engine.stats.active_slots)

    def _resume_parked(self, epoch: int,
                       engine: InferenceEngine) -> None:
        """Resume parked requests (oldest first) into free slots. A
        parked request YIELDS to strictly more urgent queued work — the
        admit pass gets the slot — but only up to ``starvation_rounds``
        passes, the same anti-starvation contract as the queue head:
        a batch request always eventually progresses."""
        resumed: List[Request] = []
        while True:
            with self._lock:
                if (self._epoch != epoch or not self._parked
                        or not engine.free_slots()):
                    break
                req, parked = self._parked[0]
                if self._parked_skip_id != req.id:
                    self._parked_skip_id, self._parked_skips = req.id, 0
                if req.status in (RequestStatus.DONE,
                                  RequestStatus.FAILED):
                    # resolved while parked (failover/shutdown race):
                    # drop the snapshot, never resurrect
                    self._parked.pop(0)
                    engine.release_parked(parked)
                    continue
                starved = self._parked_skips > self.starvation_rounds
                urgent_queued = any(r.priority < req.priority
                                    for r in self._queue)
                if urgent_queued and not starved:
                    self._parked_skips += 1
                    break        # the admit pass takes the free slot
                slot = engine.resume(parked)
                self._parked.pop(0)
                self._parked_skips = 0
                self._by_slot[slot] = req
                self.resumes += 1
                resumed.append(req)
        for req in resumed:
            if self.metrics is not None:
                self.metrics.request_resumed(
                    req, queue_depth=self.queue_depth(),
                    active_slots=engine.stats.active_slots)

    def step(self) -> int:
        """One scheduling round; returns the number of tokens produced
        (0 = idle). Admission happens BEFORE the decode step so a freed
        slot turns around within one round. Epoch-guarded: a stale driver
        (one that wedged, was failed over past, and finally woke) discards
        its events instead of touching the rebuilt engine's requests."""
        now0 = time.perf_counter()
        for req in self._shed_expired_queued(now0):
            self._fail(req, DeadlineExceededError(
                f"deadline_s={req.deadline_s:.3g} elapsed in queue after "
                f"{now0 - req.submit_t:.3g}s — shed before prefill"))
        with self._lock:
            epoch = self._epoch
            engine = self.engine
            paused = self._admission_paused
        if not paused:
            if self._parked:
                self._resume_parked(epoch, engine)
            if self.preempt:
                self._preempt_for_queued(epoch, engine)
        produced = 0 if paused else self._admit_from_queue(epoch, engine)
        events = engine.step()
        now = time.perf_counter()
        completed: List[Request] = []
        failed: List[Tuple[Request, BaseException]] = []
        progressed: List[Request] = []
        with self._lock:
            if self._epoch != epoch:
                return produced        # stale driver: discard the chunk
            for ev in events:
                req = self._by_slot.get(ev.slot)
                if req is None:      # slot freed by a cancel between steps
                    continue
                if ev.poisoned:
                    # NaN/Inf quarantine: the engine already deactivated
                    # the slot; this chunk's tokens are garbage — fail
                    # ONLY this request, drop its events
                    del self._by_slot[ev.slot]
                    failed.append((req, SlotQuarantinedError(
                        f"non-finite logits in slot {ev.slot} — request "
                        f"quarantined after {len(req.tokens)} tokens")))
                    continue
                req.tokens.append(ev.token)
                produced += 1
                if req not in progressed:
                    progressed.append(req)
                if ev.finished:
                    del self._by_slot[ev.slot]
                    completed.append(req)
            # deadline + caller cancellation at the chunk boundary: the
            # slot is freed for the next admit, the partial generation
            # reported (or, for a disconnect, silently dropped — the
            # client is gone)
            for slot, req in list(self._by_slot.items()):
                dl = req.deadline_t
                if req.id in self._cancelled:
                    self._cancelled.discard(req.id)
                    engine.release(slot)
                    del self._by_slot[slot]
                    failed.append((req, RequestCancelledError(
                        f"request {req.id} cancelled mid-generation "
                        f"({len(req.tokens)} tokens in) — slot freed at "
                        f"chunk boundary")))
                elif dl is not None and now > dl:
                    engine.release(slot)
                    del self._by_slot[slot]
                    failed.append((req, DeadlineExceededError(
                        f"deadline_s={req.deadline_s:.3g} exceeded "
                        f"mid-generation ({len(req.tokens)} tokens in) — "
                        f"cancelled at chunk boundary")))
            # the same sweep over PARKED requests: a preempted request
            # whose caller disconnected or deadline passed must release
            # its pinned pages and fail typed, never linger parked
            if self._parked:
                keep_parked = []
                for req, parked in self._parked:
                    dl = req.deadline_t
                    if req.id in self._cancelled:
                        self._cancelled.discard(req.id)
                        engine.release_parked(parked)
                        failed.append((req, RequestCancelledError(
                            f"request {req.id} cancelled while parked "
                            f"({len(req.tokens)} tokens in)")))
                    elif dl is not None and now > dl:
                        engine.release_parked(parked)
                        failed.append((req, DeadlineExceededError(
                            f"deadline_s={req.deadline_s:.3g} exceeded "
                            f"while parked ({len(req.tokens)} tokens "
                            f"in) — preempted and never resumed in "
                            f"time")))
                    else:
                        keep_parked.append((req, parked))
                self._parked = keep_parked
        for req in completed:
            self._complete(req, now)
        for req, exc in failed:
            self._fail(req, exc)
        if progressed:
            for req in progressed:
                req._notify_progress()
        return produced

    def _complete(self, req: Request,
                  now: Optional[float] = None) -> None:
        with self._lock:   # idempotent: failover/shutdown may race us
            if req.status in (RequestStatus.DONE, RequestStatus.FAILED):
                return
            req.done_t = now if now is not None else time.perf_counter()
            req.status = RequestStatus.DONE
            self._cancelled.discard(req.id)
        req._event.set()
        req._notify_progress()
        if self.metrics is not None:
            self.metrics.request_done(
                req, queue_depth=self.queue_depth(),
                active_slots=self.engine.stats.active_slots)

    def _fail(self, req: Request,
              error: Union[str, BaseException]) -> None:
        with self._lock:   # idempotent: only the FIRST resolution wins
            if req.status in (RequestStatus.DONE, RequestStatus.FAILED):
                return
            if isinstance(error, BaseException):
                req.exception = error
                req.error = f"{type(error).__name__}: {error}"
            else:
                req.error = error
            req.status = RequestStatus.FAILED
            req.done_t = time.perf_counter()
            self._cancelled.discard(req.id)
        req._event.set()
        req._notify_progress()
        if self.metrics is not None:
            self.metrics.request_done(
                req, queue_depth=self.queue_depth(),
                active_slots=self.engine.stats.active_slots)

    # -- failover hooks (supervisor) --------------------------------------

    def fail_inflight(self, error: BaseException) -> List[Request]:
        """Fail every RUNNING request typed and advance the epoch so a
        stale (wedged) driver that eventually wakes cannot apply its
        events or admissions. Called by the supervisor on an engine crash
        or watchdog-detected wedge; queued requests stay queued — they
        resume on the rebuilt engine."""
        with self._drained:
            self._epoch += 1
            victims = list(self._by_slot.values())
            self._by_slot.clear()
            # parked requests die with the engine too: their pinned
            # pages lived in the DEAD engine's pool — no release needed
            # (the rebuilt engine starts with a fresh allocator), but
            # their futures must resolve typed, never silently drop
            victims.extend(req for req, _parked in self._parked)
            self._parked.clear()
            if self._admitting is not None:
                # popped from the queue but wedged inside engine.admit —
                # in NEITHER collection; its future must not wait for
                # the abandoned thread to wake (maybe never)
                victims.append(self._admitting)
        for req in victims:
            self._fail(req, error)
        return victims

    def replace_engine(self, engine: InferenceEngine) -> None:
        """Swap in a rebuilt engine (after ``fail_inflight``, or a
        drained hot-swap). The global program LRUs make the swap warm:
        same config → no recompiles. The epoch bump invalidates any
        driver iteration that snapshotted the OLD engine before the
        swap: without it, a preempted driver could still admit a queued
        request into the detached engine (old weights, slots the new
        engine never steps)."""
        with self._lock:
            self.engine = engine
            self._epoch += 1

    def run(self, stop: threading.Event, idle_wait_s: float = 0.005):
        """Drive ``step`` until ``stop`` is set; sleeps briefly when idle
        (no busy spin — submissions are picked up at the next round)."""
        while not stop.is_set():
            produced = self.step()
            if self.metrics is not None:
                self.metrics.engine_tick(
                    self.engine.stats, queue_depth=self.queue_depth())
            if produced == 0:
                stop.wait(idle_wait_s)

    def shutdown(self, finish_running: bool = True,
                 deadline_s: float = 300.0) -> None:
        """Graceful drain (the SIGTERM path): stop accepting, FAIL queued
        requests (typed ``SchedulerClosedError`` — reported, not
        dropped), and either answer every in-flight request
        (``finish_running=True``, bounded by ``deadline_s``) or fail
        those too. Call from the driver thread or after the driver loop
        has stopped. Idempotent: a second call returns immediately
        instead of re-draining."""
        with self._drained:
            if self._shutdown_done:
                return
            self._shutdown_done = True
            self._accepting = False
            queued = list(self._queue)
            self._queue.clear()
            self._queued_deadlines = 0
            self._drained.notify_all()
        for req in queued:
            self._fail(req, SchedulerClosedError(
                "server shutting down before this request was scheduled"))
        if finish_running:
            deadline = time.perf_counter() + deadline_s
            while ((self._by_slot or self._parked)
                   and time.perf_counter() < deadline):
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — a broken engine
                    # cannot drain (e.g. a persistent fault raced the
                    # stop); fall through and fail the remainder typed
                    # instead of killing the drain thread mid-shutdown
                    sys.stderr.write(
                        f"gym_tpu.serve: drain step raised "
                        f"{type(e).__name__}: {e} — failing remaining "
                        f"in-flight requests\n")
                    break
        for slot, req in list(self._by_slot.items()):
            self.engine.release(slot)
            del self._by_slot[slot]
            self._fail(req, SchedulerClosedError(
                "server shut down mid-generation"))
        for req, parked in self._parked:
            self.engine.release_parked(parked)
            self._fail(req, SchedulerClosedError(
                "server shut down while this request was parked "
                "(preempted)"))
        self._parked = []
        with self._lock:
            admitting = self._admitting
        if admitting is not None:
            # mid-admission under a wedged driver: resolve its future
            # (idempotent _fail — a no-op if the driver got there first)
            self._fail(admitting, SchedulerClosedError(
                "server shut down during admission"))
