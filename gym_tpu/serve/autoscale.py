"""Load-adaptive autoscaling for the out-of-process fleet (ISSUE 13).

Three layers, so the POLICY is unit-testable on synthetic traces with
no subprocesses anywhere near it:

- ``AutoscalePolicy`` — the knobs: replica bounds, the drain-time
  watermarks, patience/hysteresis/cooldown tick counts.
- ``AutoscaleController`` — a pure state machine: feed it one
  ``tick(healthy, starting, backlog_tokens, tokens_per_s)`` per
  interval and it answers ``+1`` (spawn), ``-1`` (retire) or ``0``
  (hold). Decisions are priced exactly the way fleet admission control
  prices deadlines: estimated drain seconds = total backlog tokens /
  the aggregate live tokens/s EWMA. Scale-up needs ``up_patience``
  consecutive over-watermark ticks (a one-tick burst is noise);
  scale-down needs ``down_patience`` consecutive under-watermark ticks
  (hysteresis — retiring is expensive to undo) and never goes below
  ``min_replicas``. Both respect a post-action ``cooldown`` so a
  spawning worker's cold window cannot trigger a second spawn.
- ``Autoscaler`` — the thread that drives a ``ProcessRouter`` with the
  controller's decisions, and RESPAWNS dead replicas (a ``kill -9``'d
  worker leaves the healthy count under ``min_replicas``; the next
  tick spawns a replacement — with a warm ``--program-cache-dir`` the
  newcomer deserializes its whole program family and reports
  ``programs_compiled=0``, which is what makes spawning cheap enough
  to be load-adaptive).
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import traceback
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Autoscaling knobs. The defaults suit the 2-core CI box: patient
    up (2 ticks), much more patient down (8 ticks), bounded 1..4."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: scale up when the backlog would take longer than this to drain
    #: at the current aggregate rate
    up_drain_s: float = 4.0
    #: scale down when it would drain faster than this (must be well
    #: under ``up_drain_s`` — the hysteresis band lives between them)
    down_drain_s: float = 0.5
    #: with no rate established (cold fleet), fall back to a per-replica
    #: backlog-token watermark for the up decision
    up_backlog_tokens_per_replica: float = 256.0
    up_patience: int = 2
    down_patience: int = 8
    #: ticks to hold after ANY action (spawn or retire)
    cooldown: int = 4

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} < min_replicas "
                f"{self.min_replicas}")
        if self.down_drain_s >= self.up_drain_s:
            raise ValueError(
                f"down_drain_s {self.down_drain_s} must sit below "
                f"up_drain_s {self.up_drain_s} (the hysteresis band)")


class AutoscaleController:
    """Pure decision state machine (no threads, no processes, no
    clocks — ticks ARE the clock). See module docstring."""

    def __init__(self, policy: Optional[AutoscalePolicy] = None):
        self.policy = policy or AutoscalePolicy()
        self._over = 0          # consecutive ticks above the up mark
        self._under = 0         # consecutive ticks below the down mark
        self._cooldown = 0
        self.decisions = 0      # non-hold decisions issued (observable)
        #: why the LAST tick decided what it decided — the audit-trail
        #: string ``ServeMetrics.autoscale_tick`` persists per tick
        #: (ISSUE 15); greppable prefixes: floor/cooldown/up/down/hold
        self.last_reason = "init"

    def tick(self, healthy: int, starting: int, backlog_tokens: float,
             tokens_per_s: Optional[float]) -> int:
        """One autoscale interval. Returns +1 spawn / -1 retire / 0
        hold. ``starting`` (spawned, not yet serving) counts toward
        capacity for the up decision — never spawn a third replica
        because the second is still importing jax."""
        p = self.policy
        total = healthy + starting
        # replica-count floor dominates EVERYTHING: a dead fleet (or a
        # kill -9 below min) respawns immediately, cooldown or not —
        # availability is not a load decision
        if total < p.min_replicas:
            self._over = self._under = 0
            self._cooldown = p.cooldown
            self.decisions += 1
            self.last_reason = (f"floor: {total} < min_replicas "
                                f"{p.min_replicas}")
            return +1
        if self._cooldown > 0:
            self._cooldown -= 1
            self.last_reason = f"cooldown: {self._cooldown + 1} to go"
            return 0
        # price the backlog in seconds at the live aggregate rate; with
        # no rate yet (cold fleet), use the per-replica token watermark
        if tokens_per_s and tokens_per_s > 0:
            drain_s = backlog_tokens / tokens_per_s
            over = drain_s > p.up_drain_s
            under = drain_s < p.down_drain_s
            gauge = f"drain_s={drain_s:.2f}"
        else:
            over = (healthy > 0
                    and backlog_tokens / max(1, healthy)
                    > p.up_backlog_tokens_per_replica)
            under = backlog_tokens == 0
            gauge = (f"cold_backlog/replica="
                     f"{backlog_tokens / max(1, healthy):.0f}")
        self._over = self._over + 1 if over else 0
        self._under = self._under + 1 if under else 0
        if (self._over >= p.up_patience and total < p.max_replicas):
            self._over = self._under = 0
            self._cooldown = p.cooldown
            self.decisions += 1
            self.last_reason = (f"up: {gauge} over for "
                                f"{p.up_patience} tick(s)")
            return +1
        if (self._under >= p.down_patience
                and total > p.min_replicas and starting == 0):
            self._over = self._under = 0
            self._cooldown = p.cooldown
            self.decisions += 1
            self.last_reason = (f"down: {gauge} under for "
                                f"{p.down_patience} tick(s)")
            return -1
        self.last_reason = (f"hold: {gauge} over={self._over}/"
                            f"{p.up_patience} under={self._under}/"
                            f"{p.down_patience}")
        return 0


class Autoscaler:
    """Drive a ``ProcessRouter`` from an ``AutoscaleController``: every
    ``interval_s`` take the router's ``autoscale_snapshot()``, tick the
    controller, act. Spawn failures are logged and retried next tick —
    an autoscaler must never die of one bad spawn."""

    def __init__(self, router: Any,
                 policy: Optional[AutoscalePolicy] = None,
                 interval_s: float = 1.0, metrics: Any = None,
                 log=print):
        """``metrics``: a ``ServeMetrics`` — every tick is persisted as
        an ``autoscale`` audit row (snapshot, decision, reason) so
        decisions are reconstructible from ``serve.csv`` alone."""
        self.router = router
        self.controller = AutoscaleController(policy)
        self.interval_s = float(interval_s)
        self.metrics = metrics
        self._log = log
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="gym-tpu-autoscaler", daemon=True)
        self.ticks = 0
        self.spawns = 0
        self.retires = 0

    @property
    def policy(self) -> AutoscalePolicy:
        return self.controller.policy

    def start(self) -> "Autoscaler":
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout=join_timeout_s)

    def tick_once(self) -> int:
        """One autoscale step (also the testable unit): snapshot →
        decide → act. Returns the decision."""
        snap: Dict[str, Any] = self.router.autoscale_snapshot()
        decision = self.controller.tick(
            int(snap.get("healthy", 0)), int(snap.get("starting", 0)),
            float(snap.get("backlog_tokens", 0.0)),
            snap.get("tokens_per_s"))
        if self.metrics is not None:
            try:
                self.metrics.autoscale_tick(
                    healthy=int(snap.get("healthy", 0)),
                    starting=int(snap.get("starting", 0)),
                    backlog_tokens=float(snap.get("backlog_tokens",
                                                  0.0)),
                    tokens_per_s=snap.get("tokens_per_s"),
                    decision=decision,
                    reason=self.controller.last_reason)
            except Exception:  # noqa: BLE001 — observability must not
                pass           # kill the control loop
        if decision > 0:
            rep = self.router.scale_up()
            self.spawns += 1
            self._log(
                f"gym_tpu.serve: autoscaler — scale UP -> replica "
                f"{rep.id} (healthy {snap['healthy']}, backlog "
                f"{snap['backlog_tokens']:.0f} tok, rate "
                f"{snap.get('tokens_per_s') or 0.0:.1f} tok/s)",
                flush=True)
        elif decision < 0:
            rep = self.router.scale_down()
            if rep is not None:
                self.retires += 1
                self._log(
                    f"gym_tpu.serve: autoscaler — scale DOWN -> "
                    f"retired replica {rep.id} (healthy "
                    f"{snap['healthy']}, backlog "
                    f"{snap['backlog_tokens']:.0f} tok)", flush=True)
        self.ticks += 1
        return decision

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick_once()
            except Exception:  # noqa: BLE001 — one bad tick (a spawn
                # raced shutdown, a snapshot raced a close) must not
                # kill the control loop; the next tick retries
                sys.stderr.write(
                    "gym_tpu.serve: autoscaler tick failed:\n"
                    + traceback.format_exc())

    def status(self) -> Dict[str, Any]:
        return {"ticks": self.ticks, "spawns": self.spawns,
                "retires": self.retires,
                "min_replicas": self.policy.min_replicas,
                "max_replicas": self.policy.max_replicas}
