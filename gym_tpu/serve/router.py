"""Fleet serving: N engine replicas behind one health-aware router.

One engine+scheduler+supervisor stack (PRs 4–7) caps out at one chip's
throughput, and a wedged or killed engine takes the whole service down
with it until its supervisor rebuilds. The router is the layer that
survives the loss of a *replica*:

- **Replicas** — in-process engine+scheduler+supervisor stacks
  (``build_fleet`` constructs them over one shared params tree and one
  shared ``ServeMetrics``; each replica writes through a
  ``replica_view`` so ``serve.csv`` rows and EWMAs stay per-replica).
  Health is DERIVED, not polled: a replica is out of dispatch exactly
  when its supervisor declared the engine dead (``failed`` set, hooked
  live via ``Supervisor.on_dead``) or while a rolling reload drains it.
- **Dispatch** — least-loaded by committed backlog tokens
  (``Scheduler.backlog_tokens``) with a prefix-cache-aware bonus: on
  paged engines ``admit_probe``'s resident-prefix score (× page_size
  tokens of elided prefill work) is subtracted from the load, so
  shared-prefix traffic sticks to the replica that already holds the
  pages instead of re-prefilling them on a cold sibling. Ties break to
  the lowest replica id (deterministic; a single replica degrades to
  the PR-5 path exactly).
- **Failover** — a replica that dies or wedges mid-request fails its
  in-flight requests typed (``EngineFailedError`` via the supervisor,
  ``SchedulerClosedError`` for its queued requests when it is declared
  dead). ``FleetRequest.result`` catches those and transparently
  re-dispatches to a sibling under the request's REMAINING deadline
  (original ``deadline_s`` minus elapsed since the fleet submit entry —
  the PR-5 submit-entry anchor, so a retried request can never wait two
  full deadlines), bounded by ``max_failovers``. The engine is
  deterministic (same params, same seed ⇒ the exact ``generate_fast``
  stream), so the winning attempt's stream IS the uncontended stream —
  no duplicate tokens, no gaps; partial tokens from the dead attempt
  are discarded, never concatenated.
- **Degradation** — when every live replica rejects a deadline at
  admission the router re-raises the cheapest ``AdmissionRejectedError``
  (HTTP 429 + Retry-After); when every queue is full it waits bounded by
  the submit timeout/deadline then raises ``QueueFullError``; when every
  replica is dead it raises ``NoHealthyReplicaError`` (HTTP 503). The
  PR-5 admission machinery becomes fleet-level load shedding.
- **Zero-downtime weight hot-swap** (``reload``) — roll new params
  through the replicas ONE AT A TIME: pause the replica's admission and
  stop dispatching to it, wait for its in-flight requests to finish
  (queued requests keep their place), rebuild the engine from the
  updated params box via the replica's factory — warm through the
  global program LRUs: same config ⇒ ZERO recompiles — swap it into
  the scheduler, resume. Siblings keep serving throughout, so a
  trainer's newest checkpoint enters the fleet without dropping a
  single in-flight request. The rebuild (not an in-place param write)
  is deliberate: a fresh engine gets a fresh paged allocator/prefix
  cache, so prefix blocks computed under the OLD weights can never be
  served against the new ones.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import json
import os
import pickle
import queue
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.resilience import dump_thread_stacks
from . import wire
from .engine import InferenceEngine, SamplingParams
from .scheduler import (CLASS_PRIORITY, AdmissionRejectedError,
                        DeadlineExceededError, EngineFailedError,
                        QueueFullError, Request, RequestCancelledError,
                        RequestStatus, Scheduler, SchedulerClosedError)
from .supervisor import Supervisor

PyTree = Any


class NoHealthyReplicaError(RuntimeError):
    """Every replica in the fleet is dead (or the fleet is empty): the
    request cannot be dispatched anywhere. HTTP maps this to 503 —
    fleet-level degradation, not a traceback."""

    def __init__(self, msg: str, retry_after_s: float = 10.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class FleetReloadError(RuntimeError):
    """A rolling weight reload could not proceed: one is already in
    flight (``retry_after_s`` is None → HTTP 409), or a replica failed
    to drain inside the bound (``retry_after_s`` set → HTTP 503, the
    condition is transient; the partial state is reported —
    already-swapped replicas STAY swapped)."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class Replica:
    """One fleet member: its scheduler/supervisor stack plus the engine
    factory the supervisor rebuilds from (reading the router's params
    box, so a post-reload failover rebuilds with the NEW weights)."""

    id: int
    scheduler: Scheduler
    supervisor: Supervisor
    engine_factory: Callable[[], InferenceEngine]
    metrics: Any = None
    draining: bool = False

    @property
    def dead(self) -> bool:
        return self.supervisor.failed is not None

    @property
    def healthy(self) -> bool:
        return not self.dead and not self.draining


class FleetRequest:
    """Router-level request handle, mirroring ``scheduler.Request``'s
    wait surface (``result`` / ``tokens`` / ``ttft_s`` / ``done_t``) so
    the HTTP handler treats both alike. ``result`` performs the bounded
    failover retries; ``replica_id`` names the replica currently (or
    finally) serving the request and ``failovers`` how many times it was
    re-dispatched. TTFT is anchored at the FLEET submit entry, so a
    failed-over request's reported latency honestly includes the
    failover."""

    def __init__(self, router: "Router", prompt: np.ndarray,
                 sampling: SamplingParams, deadline_s: Optional[float],
                 submit_t: float, tenant: Optional[str] = None,
                 slo_class: Optional[str] = None):
        self._router = router
        self.prompt = prompt
        self.sampling = sampling
        self.deadline_s = deadline_s
        self.submit_t = submit_t
        self.tenant = tenant
        self.slo_class = slo_class
        self.failovers = 0
        self.replica_id: int = -1
        self._inner: Optional[Request] = None

    # -- Request-compatible surface --------------------------------------

    @property
    def id(self) -> int:
        return self._inner.id

    @property
    def status(self) -> RequestStatus:
        return self._inner.status

    @property
    def tokens(self) -> List[int]:
        return list(self._inner.tokens)

    @property
    def error(self) -> Optional[str]:
        return self._inner.error

    @property
    def exception(self) -> Optional[BaseException]:
        return self._inner.exception

    @property
    def done_t(self) -> Optional[float]:
        return self._inner.done_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self._inner.first_token_t is None:
            return None
        return self._inner.first_token_t - self.submit_t

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block for the tokens, transparently failing over to a sibling
        replica (bounded retries, remaining-deadline forwarded) when the
        serving replica dies mid-request. Raises the TYPED terminal
        failure otherwise — exactly ``Request.result``'s contract."""
        return self._router._await(self, timeout)

    def cancel(self, reason: str = "client disconnected") -> bool:
        """Caller-side cancellation (HTTP client went away): stop the
        generation at the next decode-chunk boundary on whichever
        replica currently serves it, free the slot."""
        inner = self._inner
        rep = next((r for r in self._router.replicas
                    if r.id == self.replica_id), None)
        if rep is None or inner is None:
            return False
        return rep.scheduler.cancel(inner, reason=reason)

    def stream(self, timeout: Optional[float] = None,
               poll_s: float = 0.25):
        """Yield lists of NEW tokens as the request produces them, at
        decode-chunk granularity — the streaming read surface. A replica
        that dies mid-stream is failed over exactly like ``result``,
        and the retry's replayed prefix (the deterministic engine
        re-derives the already-yielded tokens) is SUPPRESSED, so the
        concatenation of everything yielded is byte-identical to an
        uncontended run: the failover splice. Terminal failures raise
        TYPED, after whatever prefix was already delivered."""
        wait_deadline = (None if timeout is None
                         else time.perf_counter() + timeout)
        yielded: List[int] = []
        while True:
            inner = self._inner
            rem = (None if wait_deadline is None
                   else wait_deadline - time.perf_counter())
            if rem is not None and rem <= 0:
                # the reader gave up: stop the generation at the next
                # chunk boundary — a timed-out stream must not keep a
                # slot busy for nobody (the process router's
                # _stream_timeout twin)
                self.cancel(reason="stream wait timed out")
                raise TimeoutError(
                    f"request {inner.id} still {inner.status.value} "
                    f"after {timeout}s ({len(yielded)} tokens streamed)")
            step = poll_s if rem is None else min(poll_s, rem)
            snapshot, terminal = inner.wait_progress(len(yielded), step)
            if len(snapshot) > len(yielded):
                if snapshot[:len(yielded)] != yielded:
                    raise EngineFailedError(
                        f"stream splice mismatch after failover: "
                        f"replayed prefix diverged at request "
                        f"{inner.id} — non-deterministic replica?")
                chunk = snapshot[len(yielded):]
                yielded.extend(chunk)
                yield chunk
            if terminal:
                if inner.status is RequestStatus.DONE:
                    return
                exc = inner.exception or RuntimeError(
                    inner.error or "request failed")
                if not isinstance(exc, (EngineFailedError,
                                        SchedulerClosedError)):
                    raise exc
                # replica died mid-stream: re-dispatch under the
                # remaining deadline; the new attempt replays the
                # yielded prefix, which the loop above suppresses
                self._router._failover_redispatch(self, exc,
                                                  wait_deadline)


class Router:
    """Health-aware dispatch + failover + rolling weight reload over a
    list of ``Replica``s. Thread-safe: any number of handler threads
    call ``submit``/``result``; the internal lock guards only counters
    and flags (never held across a blocking call)."""

    def __init__(self, replicas: Sequence[Replica], *,
                 metrics=None, max_failovers: Optional[int] = None,
                 params_box: Optional[Dict[str, Any]] = None,
                 prefix_bonus_weight: float = 1.0, log=print):
        """``max_failovers`` bounds per-request re-dispatches; the
        default ``min(2, N-1)`` keeps a single-replica fleet EXACTLY on
        the PR-5 path (a typed failure surfaces to the client, no silent
        same-replica retry) while a real fleet retries on siblings.
        ``params_box`` is the mutable weights container every replica's
        engine factory reads (``reload`` updates it first, so failover
        rebuilds during a rolling swap already use the new params)."""
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        self.metrics = metrics
        self.params_box = params_box if params_box is not None else {}
        self.max_failovers = (min(2, len(self.replicas) - 1)
                              if max_failovers is None
                              else max(0, int(max_failovers)))
        self.prefix_bonus_weight = float(prefix_bonus_weight)
        self._log = log
        self._lock = threading.Lock()
        self._closing = False
        self._reloading = False
        self.failovers = 0
        self.retries_exhausted = 0
        self.reloads = 0
        for rep in self.replicas:
            rep.supervisor.on_dead = (
                lambda error, rid=rep.id: self._on_replica_dead(rid, error))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Router":
        for rep in self.replicas:
            rep.supervisor.start()
        return self

    def close(self, drain_deadline_s: float = 300.0) -> bool:
        """Stop every replica's driver and drain it (answer in-flight,
        fail queued typed). A replica whose driver is WEDGED past the
        deadline gets its thread stacks dumped (per-replica evidence)
        and its requests failed typed without touching its engine.
        Returns True when every replica drained cleanly."""
        with self._lock:
            self._closing = True
        clean = True
        for rep in self.replicas:
            if rep.supervisor.stop(join_timeout_s=drain_deadline_s):
                rep.scheduler.shutdown(finish_running=True,
                                       deadline_s=drain_deadline_s)
            else:
                clean = False
                sys.stderr.write(dump_thread_stacks(
                    f"gym_tpu.serve: router — replica {rep.id} driver "
                    f"wedged past the {drain_deadline_s:.0f}s drain "
                    f"deadline:"))
                sys.stderr.flush()
                # flag writes only — never step a wedged engine from
                # another thread; blocked handlers still get answers
                rep.scheduler.shutdown(finish_running=False,
                                       deadline_s=0.0)
        return clean

    def _on_replica_dead(self, rid: int, error: BaseException) -> None:
        # health is derived from supervisor.failed (already set when
        # this fires); the hook exists for the log line and so tests can
        # observe the exact moment a replica left dispatch
        self._log(f"gym_tpu.serve: router — replica {rid} declared dead "
                  f"({type(error).__name__}: {error}); excluded from "
                  f"dispatch", flush=True)

    # -- dispatch ---------------------------------------------------------

    def _score(self, rep: Replica, prompt: np.ndarray,
               sp: SamplingParams,
               slo_class: Optional[str] = None) -> float:
        """Lower = better: committed backlog tokens minus the resident
        shared-prefix bonus (tokens of prefill work the replica's paged
        cache would elide). The probe reads allocator state owned by the
        replica's driver thread — it is ADVISORY, so a racing mutation
        degrades to bonus 0, never to a failed dispatch.

        Class-aware (ISSUE 17): when the replica can PREEMPT, backlog
        belonging to strictly lower-priority classes barely counts
        against a more urgent request — a batch flood parked on one
        replica must not strand interactive traffic fleet-wide when
        that replica would simply park the batch decode. Without
        preemption the full backlog is the honest wait, so no discount.
        """
        sched = rep.scheduler
        load = float(sched.backlog_tokens())
        pri = CLASS_PRIORITY.get(slo_class) if slo_class else None
        if pri is not None and getattr(sched, "preempt", False):
            try:
                lower = sum(
                    tok for cls, tok in
                    sched.backlog_tokens_by_class().items()
                    if CLASS_PRIORITY.get(cls, 1) > pri)
                load -= 0.75 * lower
            except Exception:  # noqa: BLE001 — advisory, like the probe
                pass
        bonus = 0.0
        try:
            eng = sched.engine
            if getattr(eng, "paged", False):
                bonus = (eng.admit_probe(prompt, sp)[1] * eng.page_size
                         * self.prefix_bonus_weight)
        except Exception:  # noqa: BLE001 — cross-thread probe race:
            bonus = 0.0    # stickiness lost for one pick, nothing else
        return load - bonus

    def _candidates(self, prompt: np.ndarray, sp: SamplingParams,
                    exclude: Tuple[int, ...] = (),
                    slo_class: Optional[str] = None) -> List[Replica]:
        alive = [r for r in self.replicas
                 if not r.dead and r.id not in exclude]
        ready = [r for r in alive if not r.draining]
        # a fully-draining fleet (rolling reload on N=1) still ACCEPTS:
        # requests queue on the paused scheduler and admit onto the new
        # engine — that is what makes the swap zero-downtime at N=1
        pool = ready or alive
        return sorted(pool,
                      key=lambda r: (self._score(r, prompt, sp,
                                                 slo_class), r.id))

    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               block: bool = True, timeout: Optional[float] = 30.0,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               slo_class: Optional[str] = None) -> FleetRequest:
        """Dispatch to the best healthy replica. Same contract as
        ``Scheduler.submit`` (typed ``ValueError`` for bad requests,
        ``AdmissionRejectedError``/``QueueFullError`` backpressure,
        deadline caps the queue-full wait) plus
        ``NoHealthyReplicaError`` when the whole fleet is dead.
        ``tenant``/``slo_class`` ride through to the replica scheduler
        (quotas, weighted-fair queuing, preemption priority)."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t_entry = time.perf_counter()
        if deadline_s is not None and not deadline_s > 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}); omit it for "
                f"no deadline")
        cap = timeout
        if deadline_s is not None:
            cap = deadline_s if cap is None else min(cap, deadline_s)
        wait_deadline = None if cap is None else t_entry + cap
        fr = FleetRequest(self, prompt, sampling, deadline_s, t_entry,
                          tenant=tenant, slo_class=slo_class)
        fr._inner, fr.replica_id = self._dispatch(
            prompt, sampling, deadline_s, exclude=(), block=block,
            wait_deadline=wait_deadline, tenant=tenant,
            slo_class=slo_class)
        return fr

    def _dispatch(self, prompt: np.ndarray, sampling: SamplingParams,
                  deadline_s: Optional[float],
                  exclude: Tuple[int, ...], block: bool,
                  wait_deadline: Optional[float],
                  tenant: Optional[str] = None,
                  slo_class: Optional[str] = None
                  ) -> Tuple[Request, int]:
        """Try candidates best-first; degrade typed. ``exclude`` is a
        PREFERENCE (a failover avoids the replica that just failed it)
        — when exclusion empties the pool it is lifted rather than
        refusing a dispatch a live replica could serve."""
        while True:
            with self._lock:
                if self._closing:
                    raise SchedulerClosedError(
                        "router shutting down — request not dispatched")
            cands = self._candidates(prompt, sampling, exclude,
                                     slo_class)
            if not cands and exclude:
                cands = self._candidates(prompt, sampling, (),
                                         slo_class)
            if not cands:
                raise NoHealthyReplicaError(
                    f"all {len(self.replicas)} replica(s) are dead — "
                    f"fleet unrecoverable without a restart")
            rejects: List[AdmissionRejectedError] = []
            full = closing = 0
            for rep in cands:
                try:
                    req = rep.scheduler.submit(
                        prompt, sampling, block=False,
                        deadline_s=deadline_s, tenant=tenant,
                        slo_class=slo_class)
                    return req, rep.id
                except AdmissionRejectedError as e:
                    rejects.append(e)
                except QueueFullError:
                    full += 1
                except SchedulerClosedError:
                    closing += 1    # replica died between the pick and
                    #                 the submit (its scheduler refuses
                    #                 before `failed` is set); the next
                    #                 loop re-derives health
                # ValueError (bad request) propagates: every replica
                # runs the same config, no sibling would accept it
            if rejects and not full:
                # every live replica's admission control says the
                # deadline is infeasible: fleet-level shed, cheapest
                # retry hint wins
                raise min(rejects, key=lambda e: e.retry_after_s)
            if not block and full:
                raise QueueFullError(
                    f"every replica's queue is at capacity")
            if not block:
                # nothing was full — every candidate was mid-death: a
                # health signal (503 + retry), not a backpressure one
                raise NoHealthyReplicaError(
                    f"every dispatchable replica is shutting down or "
                    f"being declared dead", retry_after_s=1.0)
            rem = (None if wait_deadline is None
                   else wait_deadline - time.perf_counter())
            if rem is not None and rem <= 0:
                if full:
                    raise QueueFullError(
                        f"every replica's queue still at capacity after "
                        f"the submit wait")
                raise NoHealthyReplicaError(
                    f"every dispatchable replica still shutting down or "
                    f"being declared dead after the submit wait",
                    retry_after_s=1.0)
            time.sleep(min(0.02, rem) if rem is not None else 0.02)

    # -- result wait + failover -------------------------------------------

    def _await(self, fr: FleetRequest,
               timeout: Optional[float]) -> List[int]:
        wait_deadline = (None if timeout is None
                         else time.perf_counter() + timeout)
        while True:
            rem = (None if wait_deadline is None
                   else max(0.0, wait_deadline - time.perf_counter()))
            try:
                return fr._inner.result(rem)
            except (EngineFailedError, SchedulerClosedError) as e:
                self._failover_redispatch(fr, e, wait_deadline)

    def _failover_redispatch(self, fr: FleetRequest, e: BaseException,
                             wait_deadline: Optional[float]) -> None:
        """The shared failover step (``result`` and ``stream`` both land
        here when the serving replica dies): re-dispatch to a sibling
        under the request's REMAINING deadline, bounded by the retry
        budget — or re-raise the triggering failure typed."""
        with self._lock:
            closing = self._closing
        if closing:
            raise e
        if fr.failovers >= self.max_failovers:
            if self.max_failovers:
                with self._lock:
                    self.retries_exhausted += 1
                self._log(
                    f"gym_tpu.serve: router — request {fr.id} "
                    f"exhausted its {self.max_failovers} "
                    f"failover retr"
                    f"{'y' if self.max_failovers == 1 else 'ies'}"
                    f"; surfacing {type(e).__name__}", flush=True)
            raise e
        # satellite: forward the REMAINING deadline, anchored at
        # the fleet submit entry — a retried request can never
        # wait two full deadlines
        rem_dl = None
        if fr.deadline_s is not None:
            rem_dl = (fr.deadline_s
                      - (time.perf_counter() - fr.submit_t))
            if rem_dl <= 0:
                raise DeadlineExceededError(
                    f"deadline_s={fr.deadline_s:.3g} exhausted "
                    f"during replica failover — not retried"
                ) from e
        failed_rid = fr.replica_id
        # a failed dispatch here degrades typed (all dead → 503,
        # sibling sheds the remaining deadline → 429, …): the
        # client gets the fleet's honest answer, chained to the
        # failure that triggered the retry
        inner, rid = self._dispatch(
            fr.prompt, fr.sampling, rem_dl,
            exclude=(failed_rid,), block=True,
            wait_deadline=wait_deadline, tenant=fr.tenant,
            slo_class=fr.slo_class)
        fr.failovers += 1
        with self._lock:
            self.failovers += 1
        fr._inner, fr.replica_id = inner, rid
        self._log(
            f"gym_tpu.serve: router — failover: request retried "
            f"on replica {rid} (replica {failed_rid} failed it: "
            f"{type(e).__name__}; retry {fr.failovers}/"
            f"{self.max_failovers}"
            + (f", {rem_dl:.3g}s of deadline left)"
               if rem_dl is not None else ")"), flush=True)

    # -- zero-downtime weight hot-swap -------------------------------------

    def reload(self, params: PyTree, *, weights_tag: Optional[str] = None,
               drain_timeout_s: float = 300.0) -> Dict[str, Any]:
        """Roll ``params`` through the fleet one replica at a time with
        ZERO dropped requests and (same config) ZERO recompiles: pause
        the replica's admission + stop dispatching to it, wait for its
        in-flight requests to finish, rebuild its engine from the
        updated params box (warm via the global program LRUs), swap,
        resume. Dead replicas are skipped (a later supervisor rebuild
        would use the new params anyway — the box is already updated).
        Serialized: a second concurrent reload raises
        ``FleetReloadError`` instead of interleaving two rollouts."""
        with self._lock:
            if self._closing:
                raise SchedulerClosedError(
                    "router shutting down — reload refused")
            if self._reloading:
                raise FleetReloadError(
                    "a weight reload is already in progress")
            self._reloading = True
        t0 = time.perf_counter()
        swapped: List[int] = []
        skipped: List[int] = []
        try:
            # box first: any failover rebuild from here on — including
            # on replicas not yet reached — already serves the new
            # weights (its in-flight died with the old engine regardless)
            self.params_box["params"] = params
            if weights_tag is not None:
                self.params_box["tag"] = weights_tag
            for rep in self.replicas:
                if rep.dead:
                    skipped.append(rep.id)
                    continue
                rep.draining = True
                rep.scheduler.pause_admission()
                try:
                    deadline = time.perf_counter() + drain_timeout_s
                    while rep.scheduler.inflight() and not rep.dead:
                        if time.perf_counter() > deadline:
                            raise FleetReloadError(
                                f"replica {rep.id} did not drain within "
                                f"{drain_timeout_s:.0f}s — rolling "
                                f"reload aborted (replicas {swapped} "
                                f"already swapped, {skipped} skipped)",
                                retry_after_s=max(5.0, drain_timeout_s))
                        time.sleep(0.002)
                    if rep.dead:
                        skipped.append(rep.id)
                        continue
                    engine = rep.engine_factory()
                    rep.scheduler.replace_engine(engine)
                    if rep.metrics is not None:
                        rep.metrics.engine_reloaded()
                    swapped.append(rep.id)
                finally:
                    rep.scheduler.resume_admission()
                    rep.draining = False
            with self._lock:
                self.reloads += 1
            wall = time.perf_counter() - t0
            self._log(
                f"gym_tpu.serve: router — weight reload "
                f"{'(' + str(self.params_box.get('tag')) + ') ' if self.params_box.get('tag') else ''}"
                f"rolled through replicas {swapped} in {wall:.2f}s"
                + (f" (skipped dead: {skipped})" if skipped else ""),
                flush=True)
            return {"swapped": swapped, "skipped": skipped,
                    "weights_tag": self.params_box.get("tag"),
                    "wall_s": round(wall, 3)}
        finally:
            with self._lock:
                self._reloading = False

    # -- observability -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        reps = []
        for rep in self.replicas:
            eng = rep.scheduler.engine
            entry = {
                "id": rep.id,
                "healthy": rep.healthy,
                "dead": rep.dead,
                "draining": rep.draining,
                "restarts": rep.supervisor.restarts,
                "engine_generation": rep.supervisor.generation,
                "queue_depth": rep.scheduler.queue_depth(),
                "active_requests": rep.scheduler.active_requests(),
                "backlog_tokens": rep.scheduler.backlog_tokens(),
                "weights_tag": getattr(eng, "weights_tag", None),
            }
            if rep.metrics is not None:
                entry["tokens_per_s_ewma"] = rep.metrics.tokens_per_s_ewma()
            reps.append(entry)
        with self._lock:
            return {
                "replicas": reps,
                "healthy_replicas": sum(1 for r in reps if r["healthy"]),
                "failovers": self.failovers,
                "retries_exhausted": self.retries_exhausted,
                "weight_reloads": self.reloads,
                "weights_tag": self.params_box.get("tag"),
            }


def build_fleet(params: PyTree, config, *, replicas: int = 1,
                num_slots: int = 4, decode_chunk: int = 1,
                paged: bool = False, page_size: int = 16,
                kv_pages: Optional[int] = None, spec_tokens: int = 0,
                max_queue: int = 64, metrics=None,
                dispatch_timeout_s: float = 120.0, max_restarts: int = 5,
                max_failovers: Optional[int] = None,
                weights_tag: Optional[str] = None,
                prefix_bonus_weight: float = 1.0,
                quotas: Optional[Dict[str, Any]] = None,
                preempt: bool = False, log=print) -> Router:
    """Construct a ``Router`` over N identical in-process replica
    stacks sharing one params tree and one metrics collector (each
    replica writes through its ``replica_view``). Supervisors are NOT
    started — call ``router.start()``. With ``replicas=1`` and the
    default retry budget (0), the stack behaves exactly like the PR-5
    single-engine server."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    box: Dict[str, Any] = {"params": params, "tag": weights_tag}
    reps: List[Replica] = []
    for rid in range(int(replicas)):
        view = (metrics.replica_view(rid)
                if metrics is not None else None)

        def factory(rid=rid):
            return InferenceEngine(
                box["params"], config, num_slots=num_slots,
                decode_chunk=decode_chunk, paged=paged,
                page_size=page_size, kv_pages=kv_pages,
                spec_tokens=spec_tokens, weights_tag=box.get("tag"))

        sched = Scheduler(factory(), max_queue=max_queue, metrics=view,
                          quotas=quotas, preempt=preempt)
        sup = Supervisor(sched, factory,
                         dispatch_timeout_s=dispatch_timeout_s,
                         max_restarts=max_restarts, metrics=view, log=log)
        reps.append(Replica(id=rid, scheduler=sched, supervisor=sup,
                            engine_factory=factory, metrics=view))
    return Router(reps, metrics=metrics, max_failovers=max_failovers,
                  params_box=box, prefix_bonus_weight=prefix_bonus_weight,
                  log=log)


# ==========================================================================
# Out-of-process fleet: subprocess replicas behind the same dispatch
# semantics, spoken over local sockets (ISSUE 13, ROADMAP item 2)
# ==========================================================================
#
# The in-process ``Router`` above proved the fleet semantics but shares
# one GIL and one failure domain across N replicas. The classes below
# move each replica into a real subprocess (``serve/worker.py``) behind
# a THIN dispatcher: one asyncio event loop (a single background
# thread) multiplexes every worker connection — health ticks, submits,
# token-chunk streams — while synchronous callers (the HTTP handler
# threads) interact through per-request queues. Same health/failover
# protocol as the in-process router: least-loaded dispatch from
# worker-reported backlog, dead replicas out of dispatch the moment
# their connection drops, bounded failover under the REMAINING
# deadline — upgraded to STREAMING: a replica killed mid-stream has its
# request re-dispatched with the already-delivered tokens as a
# ``prefix`` the sibling re-derives (deterministic engine), verifies,
# and suppresses, so the concatenated client stream is byte-identical
# to an uncontended run.


class WorkerSpawner:
    """Launches ``python -m gym_tpu.serve.worker`` subprocesses sharing
    one params/config snapshot. The snapshot is materialized ONCE into
    ``base_dir`` (pickled numpy tree + config JSON — one checkpoint
    restore in the parent, N cheap loads in the workers); alternatively
    ``ckpt`` makes each worker restore the run dir itself. Worker
    stdout/stderr land in ``base_dir/worker-<rid>.log``."""

    def __init__(self, base_dir: str, *, params: Any = None,
                 config: Any = None, ckpt: Optional[str] = None,
                 step: Optional[int] = None,
                 config_path: Optional[str] = None,
                 num_slots: int = 4, decode_chunk: int = 1,
                 page_size: int = 16, kv_pages: Optional[int] = None,
                 spec_tokens: int = 0, max_queue: int = 64,
                 dispatch_timeout_s: float = 120.0,
                 max_restarts: int = 5,
                 program_cache_dir: Optional[str] = None,
                 weights_tag: Optional[str] = None,
                 no_warmup: bool = False, device: Optional[str] = "cpu",
                 env: Optional[Dict[str, str]] = None,
                 quotas_json: Optional[str] = None,
                 preempt: bool = False):
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.params_file: Optional[str] = None
        self.config_file: Optional[str] = None
        self.ckpt, self.step, self.config_path = ckpt, step, config_path
        if params is not None:
            if config is None:
                raise ValueError("params without config — the worker "
                                 "needs both")
            self.params_file = os.path.join(self.base_dir, "params.pkl")
            self.dump_params(params, self.params_file)
            self.config_file = os.path.join(self.base_dir, "config.json")
            tmp = self.config_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump(dataclasses.asdict(config), f)
            os.replace(tmp, self.config_file)
        elif ckpt is None:
            raise ValueError(
                "WorkerSpawner needs params+config or a ckpt run dir")
        self.num_slots = int(num_slots)
        self.decode_chunk = int(decode_chunk)
        self.page_size = int(page_size)
        self.kv_pages = kv_pages
        self.spec_tokens = int(spec_tokens)
        self.max_queue = int(max_queue)
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self.max_restarts = int(max_restarts)
        self.program_cache_dir = program_cache_dir
        self.weights_tag = weights_tag
        self.no_warmup = bool(no_warmup)
        self.device = device
        self.env = dict(env or {})
        self.quotas_json = quotas_json
        self.preempt = bool(preempt)
        self._reload_seq = itertools.count()

    @staticmethod
    def dump_params(params: Any, path: str) -> str:
        """Materialize a params tree as host numpy, atomically (a
        worker must never read a torn pickle)."""
        import jax
        host = jax.device_get(params)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(host, f, protocol=4)
        os.replace(tmp, path)
        return path

    def reload_file(self, params: Any,
                    tag: Optional[str] = None) -> str:
        """A fresh params snapshot for a rolling reload (sequence-
        numbered: an in-flight worker read of the PREVIOUS snapshot
        must never race an overwrite)."""
        name = f"reload-{next(self._reload_seq)}"
        if tag:
            name += f"-{str(tag).replace(os.sep, '_')[:40]}"
        return self.dump_params(params,
                                os.path.join(self.base_dir,
                                             name + ".pkl"))

    def sock_path(self, rid: int) -> str:
        return os.path.join(self.base_dir, f"w{rid}.sock")

    def spawn(self, rid: int) -> Tuple[subprocess.Popen, str, str]:
        """Start worker ``rid``; returns ``(proc, socket_path,
        log_path)``. The caller owns the connect-and-wait."""
        sock = self.sock_path(rid)
        try:
            os.unlink(sock)
        except FileNotFoundError:
            pass
        log_path = os.path.join(self.base_dir, f"worker-{rid}.log")
        cmd = [sys.executable, "-m", "gym_tpu.serve.worker",
               "--socket", sock, "--replica-id", str(rid),
               "--num_slots", str(self.num_slots),
               "--decode_chunk", str(self.decode_chunk),
               "--page_size", str(self.page_size),
               "--spec_tokens", str(self.spec_tokens),
               "--max_queue", str(self.max_queue),
               "--dispatch-timeout", str(self.dispatch_timeout_s),
               "--max-restarts", str(self.max_restarts)]
        if self.kv_pages is not None:
            cmd += ["--kv_pages", str(self.kv_pages)]
        if self.params_file:
            cmd += ["--params-file", self.params_file,
                    "--config-json", self.config_file]
        else:
            cmd += ["--ckpt", self.ckpt]
            if self.step is not None:
                cmd += ["--step", str(self.step)]
            if self.config_path:
                cmd += ["--config", self.config_path]
        if self.program_cache_dir:
            cmd += ["--program-cache-dir", self.program_cache_dir]
        if self.weights_tag:
            cmd += ["--weights-tag", str(self.weights_tag)]
        if self.no_warmup:
            cmd += ["--no-warmup"]
        if self.quotas_json:
            cmd += ["--quotas-json", self.quotas_json]
        if self.preempt:
            cmd += ["--preempt"]
        if self.device:
            cmd += ["--device", str(self.device)]
        env = dict(os.environ)
        env.update(self.env)
        if self.device == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        # the worker must import gym_tpu exactly as this process does
        import gym_tpu as _pkg
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(
                                 os.pathsep)
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(cmd, stdout=logf, stderr=logf,
                                    env=env)
        return proc, sock, log_path


class ProcessReplica:
    """Router-side handle on one worker subprocess: the Popen, the
    socket, the last health report, and the router's own committed-
    token accounting (health reports lag; the local add keeps
    least-loaded dispatch responsive between ticks)."""

    def __init__(self, rid: int, proc: Optional[subprocess.Popen],
                 sock_path: str, log_path: str):
        self.id = int(rid)
        self.proc = proc
        self.sock_path = sock_path
        self.log_path = log_path
        self.pid: Optional[int] = proc.pid if proc is not None else None
        self.connected = False
        self.dead = False
        self.draining = False
        self.retired = False
        self.death_reason: Optional[str] = None
        self.last_health: Dict[str, Any] = {}
        self.inflight_tokens = 0
        # (accept time, committed tokens) of requests the worker has
        # ACCEPTED but whose tokens may predate the last health report:
        # expired against health ticks so a request is never counted
        # both locally and in the worker-reported backlog
        self._accepts: List[Tuple[float, int]] = []
        self.writer: Any = None

    @property
    def healthy(self) -> bool:
        return (self.connected and not self.dead
                and not self.draining and not self.retired)

    def load(self) -> float:
        return (float(self.last_health.get("backlog_tokens", 0) or 0)
                + self.inflight_tokens)

    def load_for(self, slo_class: Optional[str]) -> float:
        """Class-aware dispatch load (ISSUE 17): when the worker can
        PREEMPT, backlog belonging to strictly lower-priority classes
        barely counts against a more urgent request — the in-process
        ``Router._score`` discount, read off the health report."""
        load = self.load()
        pri = CLASS_PRIORITY.get(slo_class) if slo_class else None
        if pri is None or not self.last_health.get("preempt"):
            return load
        by_cls = self.last_health.get("backlog_by_class") or {}
        lower = sum(float(tok or 0) for cls, tok in by_cls.items()
                    if CLASS_PRIORITY.get(cls, 1) > pri)
        return load - 0.75 * lower


class ProcRequest:
    """Process-fleet request handle — the same wait surface as
    ``FleetRequest`` (``result``/``stream``/``tokens``/``ttft_s``/
    ``done_t``/``replica_id``/``failovers``) fed by wire frames instead
    of a shared-memory ``Request``. ``tokens`` holds exactly what was
    delivered to the caller, across failovers — the splice invariant's
    source of truth."""

    def __init__(self, router: "ProcessRouter", prompt: np.ndarray,
                 sampling: SamplingParams, deadline_s: Optional[float],
                 submit_t: float, tenant: Optional[str] = None,
                 slo_class: Optional[str] = None):
        self._router = router
        self.prompt = prompt
        self.sampling = sampling
        self.deadline_s = deadline_s
        self.submit_t = submit_t
        self.tenant = tenant
        self.slo_class = slo_class
        self.tokens: List[int] = []
        self.failovers = 0
        self.replica_id = -1
        self.pid: Optional[int] = None
        self.id: Optional[int] = None        # wire id, current attempt
        self._rep: Optional[ProcessReplica] = None
        self._q: "queue.Queue" = queue.Queue()
        self.first_chunk_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.done_frame: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        self.exception: Optional[BaseException] = None
        self.streaming = True
        self.coalesce_s: Optional[float] = None
        self._finished = False

    @property
    def ttft_s(self) -> Optional[float]:
        """Client-observable time to first token (= first streamed
        chunk), anchored at the fleet submit entry — for a spliced
        request this is the FIRST attempt's first chunk, honestly.
        Result-only requests (no chunk frames) fall back to the
        worker-reported first-token time."""
        if self.first_chunk_t is not None:
            return self.first_chunk_t - self.submit_t
        if self.done_frame is not None:
            return self.done_frame.get("ttft_s")
        return None

    @property
    def avg_token_latency_s(self) -> Optional[float]:
        if (self.done_t is None or self.first_chunk_t is None
                or len(self.tokens) < 2):
            return None
        return ((self.done_t - self.first_chunk_t)
                / (len(self.tokens) - 1))

    def stream(self, timeout: Optional[float] = None):
        """Yield lists of NEW tokens as chunk frames arrive; failover
        splices transparently (see ``ProcessRouter._stream``)."""
        return self._router._stream(self, timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        for _ in self._router._stream(self, timeout):
            pass
        return list(self.tokens)

    def cancel(self, reason: str = "client disconnected") -> bool:
        return self._router._cancel(self, reason)


class ProcessRouter:
    """Dispatcher over N worker subprocesses. One asyncio loop thread
    owns every worker connection (connects, reads frames, health
    ticks); synchronous callers submit and consume through thread-safe
    queues — the ``Router`` dispatch/failover/degradation semantics,
    spoken over sockets, with token streaming end to end."""

    kind = "process"

    def __init__(self, spawner: WorkerSpawner, *, replicas: int = 2,
                 metrics=None, max_failovers: Optional[int] = None,
                 health_interval_s: float = 0.5,
                 connect_timeout_s: float = 240.0,
                 submit_ack_timeout_s: float = 30.0, log=print):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.spawner = spawner
        self.metrics = metrics
        self._want = int(replicas)
        self.max_failovers = (min(2, self._want - 1)
                              if max_failovers is None
                              else max(0, int(max_failovers)))
        self.health_interval_s = float(health_interval_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.submit_ack_timeout_s = float(submit_ack_timeout_s)
        self._log = log
        self._lock = threading.Lock()
        self._closing = False
        self._reloading = False
        self.failovers = 0
        self.retries_exhausted = 0
        self.reloads = 0
        self.replicas_spawned = 0
        self.replicas_retired = 0
        self.replicas: List[ProcessReplica] = []
        self._rids = itertools.count()
        self._ids = itertools.count(1)
        self._pending: Dict[int, Tuple["queue.Queue",
                                       ProcessReplica]] = {}
        self._weights_tag = spawner.weights_tag
        self._loop = asyncio.new_event_loop()
        self._loop_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ProcessRouter":
        self._loop_thread = threading.Thread(
            target=self._run_loop, name="gym-tpu-proc-router",
            daemon=True)
        self._loop_thread.start()
        for _ in range(self._want):
            self.scale_up()
        return self

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()
        # drain cancelled callbacks so close() leaves nothing running
        pending = asyncio.all_tasks(self._loop)
        for t in pending:
            t.cancel()
        if pending:
            self._loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True))
        self._loop.close()

    def wait_ready(self, n: Optional[int] = None,
                   timeout_s: float = 240.0) -> bool:
        """Block until ``n`` (default: all requested) replicas are
        connected and healthy. Raises ``NoHealthyReplicaError`` when
        every spawned worker died instead (startup crash — the worker
        logs carry the traceback)."""
        want = self._want if n is None else int(n)
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            with self._lock:
                live = [r for r in self.replicas if not r.retired]
                up = sum(1 for r in live if r.healthy)
                all_dead = bool(live) and all(r.dead for r in live)
            if up >= want:
                return True
            if all_dead:
                raise NoHealthyReplicaError(
                    f"every spawned worker died during startup — see "
                    f"worker logs under {self.spawner.base_dir}")
            time.sleep(0.1)
        raise NoHealthyReplicaError(
            f"fleet not ready ({want} replicas) after {timeout_s:.0f}s "
            f"— see worker logs under {self.spawner.base_dir}")

    def scale_up(self) -> ProcessReplica:
        """Spawn one more worker process and connect to it (async; use
        ``wait_ready`` to block on health). The autoscaler's up-arrow
        AND the respawn path for killed workers."""
        with self._lock:
            if self._closing:
                raise SchedulerClosedError(
                    "router shutting down — not spawning")
            rid = next(self._rids)
        proc, sock, log_path = self.spawner.spawn(rid)
        rep = ProcessReplica(rid, proc, sock, log_path)
        with self._lock:
            self.replicas.append(rep)
            self.replicas_spawned += 1
        if self.metrics is not None:
            self.metrics.replica_spawned(replica_id=rid, pid=rep.pid)
        asyncio.run_coroutine_threadsafe(self._connect(rep), self._loop)
        self._log(f"gym_tpu.serve: proc-router — spawned replica {rid} "
                  f"(pid {rep.pid}, {os.path.basename(sock)})",
                  flush=True)
        return rep

    def scale_down(self, drain_timeout_s: float = 60.0
                   ) -> Optional[ProcessReplica]:
        """Retire the newest healthy replica (drain, stop, reap) — the
        autoscaler's down-arrow. Refuses to go below one healthy
        replica. Returns the retired replica, or None."""
        with self._lock:
            cands = [r for r in self.replicas if r.healthy]
            if len(cands) <= 1:
                return None
            rep = max(cands, key=lambda r: r.id)
            rep.draining = True
        deadline = time.perf_counter() + drain_timeout_s
        while time.perf_counter() < deadline:
            with self._lock:
                busy = any(r is rep for _, r in self._pending.values())
            if not busy:
                break
            time.sleep(0.05)
        self._stop_worker(rep, graceful=True,
                          timeout_s=max(5.0, drain_timeout_s))
        with self._lock:
            rep.retired = True
            rep.connected = False
            self.replicas_retired += 1
        if self.metrics is not None:
            self.metrics.replica_retired(replica_id=rep.id, pid=rep.pid)
        self._log(f"gym_tpu.serve: proc-router — retired replica "
                  f"{rep.id} (pid {rep.pid})", flush=True)
        return rep

    def _stop_worker(self, rep: ProcessReplica, graceful: bool,
                     timeout_s: float = 15.0) -> bool:
        """Stop one worker and REAP it (no zombies): stop frame →
        wait → SIGTERM → wait → SIGKILL → wait."""
        proc = rep.proc
        if graceful and rep.connected:
            try:
                self._send(rep, {"type": "stop",
                                 "id": next(self._ids)}, timeout=5.0)
            except Exception:  # noqa: BLE001 — fall through to signals
                pass
        if proc is None:
            return True
        try:
            proc.wait(timeout=timeout_s)
            return True
        except subprocess.TimeoutExpired:
            pass
        proc.terminate()
        try:
            proc.wait(timeout=10.0)
            return True
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10.0)
            return False

    def close(self, drain_deadline_s: float = 300.0) -> bool:
        """Stop every worker (graceful drain where the worker is still
        healthy), fail still-pending requests typed, reap every child,
        stop the event loop."""
        with self._lock:
            if self._closing:
                return True
            self._closing = True
        clean = True
        live = [r for r in self.replicas if not r.retired]
        # broadcast the stop frames FIRST so every worker drains
        # CONCURRENTLY — then reap under one shared deadline; a serial
        # stop-and-wait would multiply the drain bound by the fleet size
        for rep in live:
            if not rep.dead and rep.connected:
                try:
                    self._send(rep, {"type": "stop",
                                     "id": next(self._ids)},
                               timeout=5.0)
                except Exception:  # noqa: BLE001 — signals below
                    pass
        overall = time.perf_counter() + drain_deadline_s
        for rep in live:
            rem = max(5.0, overall - time.perf_counter())
            try:
                ok = self._stop_worker(
                    rep, graceful=False,   # stop already broadcast
                    timeout_s=(rem if not rep.dead else 5.0))
                clean = clean and ok
            except Exception:  # noqa: BLE001 — keep reaping siblings
                clean = False
        with self._lock:
            pend = list(self._pending.items())
            self._pending.clear()
        for wid, (q, _rep) in pend:
            q.put({"type": "error", "id": wid,
                   "error_type": "SchedulerClosedError",
                   "message": "router shutting down"})
        try:
            self._loop.call_soon_threadsafe(self._loop.stop)
        except RuntimeError:
            pass
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
        return clean

    # -- async plumbing (loop thread) --------------------------------------

    async def _read_one(self, reader) -> Dict[str, Any]:
        return await wire.read_frame_async(reader)

    async def _connect(self, rep: ProcessReplica) -> None:
        deadline = self._loop.time() + self.connect_timeout_s
        reader = writer = None
        while True:
            if rep.proc is not None and rep.proc.poll() is not None:
                self._mark_dead(
                    rep, f"worker exited rc={rep.proc.returncode} "
                         f"during startup (log: {rep.log_path})")
                return
            try:
                reader, writer = await asyncio.open_unix_connection(
                    rep.sock_path)
                break
            except (FileNotFoundError, ConnectionRefusedError,
                    OSError):
                if self._loop.time() > deadline:
                    self._mark_dead(
                        rep, f"no socket after "
                             f"{self.connect_timeout_s:.0f}s")
                    return
                await asyncio.sleep(0.2)
        try:
            hello = await asyncio.wait_for(self._read_one(reader),
                                           timeout=60.0)
        except Exception as e:  # noqa: BLE001 — handshake failed
            self._mark_dead(rep, f"handshake failed: {e}")
            writer.close()
            return
        rep.writer = writer
        rep.pid = int(hello.get("pid", rep.pid or -1))
        rep.last_health = hello
        rep.connected = True
        self._log(f"gym_tpu.serve: proc-router — replica {rep.id} "
                  f"connected (pid {rep.pid})", flush=True)
        self._loop.create_task(self._reader_loop(rep, reader))
        self._loop.create_task(self._health_loop(rep))

    async def _reader_loop(self, rep: ProcessReplica, reader) -> None:
        try:
            while True:
                frame = await self._read_one(reader)
                ftype = frame.get("type")
                if ftype in ("health_ok", "hello", "stats_ok"):
                    rep.last_health = frame
                    # accepted requests older than one health interval
                    # are reflected in this report's backlog_tokens —
                    # drop their local add (no double count)
                    now = time.perf_counter()
                    with self._lock:
                        keep = []
                        for t, committed in rep._accepts:
                            if now - t > self.health_interval_s:
                                rep.inflight_tokens = max(
                                    0, rep.inflight_tokens - committed)
                            else:
                                keep.append((t, committed))
                        rep._accepts = keep
                    if frame.get("dead"):
                        self._mark_dead(
                            rep, "worker engine unrecoverable "
                                 "(supervisor gave up)")
                if "id" in frame and frame.get("id") is not None:
                    with self._lock:
                        entry = self._pending.get(frame["id"])
                    if entry is not None:
                        entry[0].put(frame)
        except (asyncio.IncompleteReadError, wire.WireError,
                ConnectionError, OSError) as e:
            self._mark_dead(rep, f"connection lost: "
                                 f"{type(e).__name__}: {e}")
        except asyncio.CancelledError:
            raise

    async def _health_loop(self, rep: ProcessReplica) -> None:
        while rep.connected and not rep.dead:
            try:
                await self._send_async(rep, {"type": "health"})
            except Exception:  # noqa: BLE001 — connection died
                self._mark_dead(rep, "health send failed")
                return
            await asyncio.sleep(self.health_interval_s)
            if rep.proc is not None and rep.proc.poll() is not None:
                self._mark_dead(
                    rep, f"worker process exited "
                         f"rc={rep.proc.returncode}")
                return

    async def _send_async(self, rep: ProcessReplica,
                          frame: Dict[str, Any]) -> None:
        if rep.writer is None:
            raise ConnectionError(f"replica {rep.id} not connected")
        rep.writer.write(wire.encode_frame(frame))
        await rep.writer.drain()

    def _send(self, rep: ProcessReplica, frame: Dict[str, Any],
              timeout: float = 10.0) -> None:
        fut = asyncio.run_coroutine_threadsafe(
            self._send_async(rep, frame), self._loop)
        fut.result(timeout)

    def _mark_dead(self, rep: ProcessReplica, why: str) -> None:
        """Declare one replica dead (idempotent; any thread): out of
        dispatch immediately, every pending request on it gets a typed
        engine-failure frame (the failover trigger), and the corpse is
        reaped in the background so ``kill -9`` never leaves a
        zombie."""
        with self._lock:
            if rep.dead or rep.retired:
                return
            closing = self._closing
            rep.dead = True
            rep.connected = False
            rep.death_reason = why
            victims = [(wid, q) for wid, (q, r)
                       in self._pending.items() if r is rep]
        w = rep.writer
        if w is not None:
            try:
                self._loop.call_soon_threadsafe(w.close)
            except RuntimeError:
                pass
        for wid, q in victims:
            q.put({"type": "error", "id": wid,
                   "error_type": "EngineFailedError",
                   "message": f"replica {rep.id} (pid {rep.pid}) "
                              f"lost: {why}"})
        if not closing:
            # a worker leaving DURING close() is the stop we asked for,
            # not a death worth alerting on
            self._log(f"gym_tpu.serve: proc-router — replica {rep.id} "
                      f"(pid {rep.pid}) declared dead ({why}); excluded "
                      f"from dispatch", flush=True)
        if rep.proc is not None and rep.proc.poll() is None:
            threading.Thread(
                target=self._stop_worker, args=(rep, False, 5.0),
                name=f"reap-worker-{rep.id}", daemon=True).start()

    # -- dispatch ----------------------------------------------------------

    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               block: bool = True, timeout: Optional[float] = 30.0,
               deadline_s: Optional[float] = None,
               stream: bool = True,
               coalesce_s: Optional[float] = None,
               tenant: Optional[str] = None,
               slo_class: Optional[str] = None) -> ProcRequest:
        """Same contract as ``Router.submit``: typed backpressure and
        health degradation, deadline caps the dispatch wait.
        ``stream=False`` marks a result-only request: the worker skips
        per-chunk frames entirely and ships the tokens on the ``done``
        frame — per-token wire overhead drops to zero for callers that
        never wanted a stream. ``coalesce_s`` overrides the worker's
        post-first-chunk batching window (None = worker default; 0 =
        one frame per decode chunk — chaos drills use this to pin the
        kill inside the stream)."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t_entry = time.perf_counter()
        if deadline_s is not None and not deadline_s > 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}); omit it "
                f"for no deadline")
        cap = timeout
        if deadline_s is not None:
            cap = deadline_s if cap is None else min(cap, deadline_s)
        wait_deadline = None if cap is None else t_entry + cap
        pr = ProcRequest(self, prompt, sampling, deadline_s, t_entry,
                         tenant=tenant, slo_class=slo_class)
        pr.streaming = bool(stream)
        pr.coalesce_s = coalesce_s
        self._dispatch_proc(pr, deadline_s, prefix=[], exclude=(),
                            block=block, wait_deadline=wait_deadline)
        return pr

    def _dispatch_proc(self, pr: ProcRequest,
                       deadline_s: Optional[float], prefix: List[int],
                       exclude: Tuple[int, ...], block: bool,
                       wait_deadline: Optional[float]) -> None:
        sp_dict = wire.sampling_to_dict(pr.sampling)
        committed = int(pr.sampling.max_new_tokens)
        prompt_list = [int(t) for t in pr.prompt]
        while True:
            with self._lock:
                if self._closing:
                    raise SchedulerClosedError(
                        "router shutting down — request not dispatched")
                live = [r for r in self.replicas if not r.retired]
                cands = [r for r in live
                         if r.healthy and r.id not in exclude]
                if not cands and exclude:
                    cands = [r for r in live if r.healthy]
                cands.sort(key=lambda r: (r.load_for(pr.slo_class),
                                          r.id))
                n_live = len(live)
            if not cands:
                starting = any(not r.connected and not r.dead
                               and not r.retired for r in live)
                if not starting:
                    raise NoHealthyReplicaError(
                        f"all {n_live} replica(s) are dead — fleet "
                        f"unrecoverable without a respawn")
            rejects: List[AdmissionRejectedError] = []
            full = 0
            for rep in cands:
                wid = next(self._ids)
                with self._lock:
                    if not rep.healthy:
                        continue   # died/started draining since the
                        #            candidate snapshot (scale_down
                        #            race) — a stop-frame'd worker
                        #            would never ack this submit
                    self._pending[wid] = (pr._q, rep)
                    rep.inflight_tokens += committed
                frame = {"type": "submit", "id": wid,
                         "prompt": prompt_list, "sampling": sp_dict,
                         "deadline_s": deadline_s, "prefix": prefix,
                         "stream": pr.streaming,
                         "submit_timeout": max(
                             1.0, self.submit_ack_timeout_s - 5.0)}
                if pr.coalesce_s is not None:
                    frame["coalesce_s"] = float(pr.coalesce_s)
                # only when tagged: a default (single-tenant) frame
                # stays byte-identical to the pre-tenant protocol, and
                # an old worker never sees fields it would note about
                if pr.tenant is not None:
                    frame["tenant"] = str(pr.tenant)
                if pr.slo_class is not None:
                    frame["slo_class"] = str(pr.slo_class)
                try:
                    self._send(rep, frame, timeout=10.0)
                    first = self._next_frame(
                        pr, wid, self.submit_ack_timeout_s)
                except queue.Empty:
                    self._unpend(wid, rep, committed)
                    self._mark_dead(
                        rep, f"no submit ack within "
                             f"{self.submit_ack_timeout_s:.0f}s")
                    continue
                except Exception as e:  # noqa: BLE001 — send failed:
                    # the connection is gone; health will confirm
                    self._unpend(wid, rep, committed)
                    self._mark_dead(rep, f"submit send failed: {e}")
                    continue
                if first.get("type") == "accepted":
                    pr.id, pr.replica_id = wid, rep.id
                    pr.pid, pr._rep = rep.pid, rep
                    with self._lock:
                        # from here the WORKER owns the load accounting
                        # (its next health report includes this
                        # request); the local add expires against that
                        # report instead of at completion
                        rep._accepts.append(
                            (time.perf_counter(), committed))
                    return
                self._unpend(wid, rep, committed)
                exc = wire.frame_to_exception(first)
                if isinstance(exc, AdmissionRejectedError):
                    rejects.append(exc)
                elif isinstance(exc, QueueFullError):
                    full += 1
                elif isinstance(exc, ValueError):
                    raise exc        # every replica runs one config
                # engine-failure/closing: candidate mid-death — the
                # next loop re-derives health
            if rejects and not full:
                raise min(rejects, key=lambda e: e.retry_after_s)
            if not block and full:
                raise QueueFullError(
                    "every replica's queue is at capacity")
            if not block:
                # empty candidate set (fleet still starting) or every
                # candidate mid-death: the non-blocking contract is
                # fast-fail, not a silent spin until the deadline
                raise NoHealthyReplicaError(
                    "no replica is dispatchable right now (starting, "
                    "draining or being declared dead)",
                    retry_after_s=1.0)
            rem = (None if wait_deadline is None
                   else wait_deadline - time.perf_counter())
            if rem is not None and rem <= 0:
                if full:
                    raise QueueFullError(
                        "every replica's queue still at capacity "
                        "after the submit wait")
                raise NoHealthyReplicaError(
                    "no replica became dispatchable within the submit "
                    "wait", retry_after_s=1.0)
            time.sleep(min(0.05, rem) if rem is not None else 0.05)

    @staticmethod
    def _next_frame(pr: ProcRequest, wid: int,
                    timeout: float) -> Dict[str, Any]:
        """Next frame belonging to attempt ``wid``. The request's queue
        can hold STALE frames from a previous failover attempt (the
        worker's own error AND ``_mark_dead``'s synthetic one can both
        land for the same dead attempt) — consuming one of those as the
        new attempt's ack or as a fresh failure would burn the failover
        budget on a ghost. Raises ``queue.Empty`` on timeout."""
        deadline = time.perf_counter() + timeout
        while True:
            rem = deadline - time.perf_counter()
            if rem <= 0:
                raise queue.Empty
            frame = pr._q.get(timeout=rem)
            if frame.get("id") == wid:
                return frame
            # stale attempt's frame: drop it

    def _unpend(self, wid: Optional[int],
                rep: Optional[ProcessReplica], committed: int) -> None:
        with self._lock:
            if wid is not None:
                self._pending.pop(wid, None)
            if rep is not None:
                rep.inflight_tokens = max(
                    0, rep.inflight_tokens - committed)

    # -- streaming consume + failover splice -------------------------------

    def _stream(self, pr: ProcRequest, timeout: Optional[float]):
        if pr._finished:
            if pr.exception is not None:
                raise pr.exception
            return
        wait_deadline = (None if timeout is None
                         else time.perf_counter() + timeout)
        while True:
            rem = (None if wait_deadline is None
                   else wait_deadline - time.perf_counter())
            if rem is not None and rem <= 0:
                raise self._stream_timeout(pr, timeout)
            try:
                frame = pr._q.get(timeout=rem)
            except queue.Empty:
                raise self._stream_timeout(pr, timeout) from None
            if frame.get("id") != pr.id:
                continue      # stale frame from a failed-over attempt
            ftype = frame.get("type")
            if ftype == "chunk":
                toks = [int(t) for t in frame.get("tokens", [])]
                if toks:
                    if pr.first_chunk_t is None:
                        pr.first_chunk_t = time.perf_counter()
                    pr.tokens.extend(toks)
                    yield toks
            elif ftype == "done":
                pr.done_frame = frame
                pr.done_t = time.perf_counter()
                final = [int(t) for t in frame.get("tokens", [])]
                if final:        # result-only path: tokens ride done
                    pr.tokens.extend(final)
                self._finish(pr, None)   # AFTER tokens: the metrics
                #                          row reads len(pr.tokens)
                if final:
                    yield final
                return
            elif ftype == "error":
                exc = wire.frame_to_exception(frame)
                with self._lock:
                    closing = self._closing
                if (isinstance(exc, (EngineFailedError,
                                     SchedulerClosedError))
                        and not closing):
                    try:
                        self._proc_failover(pr, exc, wait_deadline)
                    except BaseException as e2:
                        self._finish(pr, e2)
                        raise
                    continue
                self._finish(pr, exc)
                raise exc
            # accepted/stray frames: ignore

    def _stream_timeout(self, pr: ProcRequest,
                        timeout: Optional[float]) -> TimeoutError:
        """The caller's wait elapsed: tell the worker to stop generating
        for a reader that gave up, and FINISH the request so its pending
        entry and load accounting are released — a timed-out stream
        must never leak dispatch weight or a queue entry."""
        exc = TimeoutError(
            f"request still streaming after {timeout}s "
            f"({len(pr.tokens)} tokens delivered)")
        rep = pr._rep
        if rep is not None and rep.connected:
            try:
                self._send(rep, {"type": "cancel", "id": pr.id},
                           timeout=5.0)
            except Exception:  # noqa: BLE001 — best effort
                pass
        # recorded as a DISCONNECT (the reader gave up), exactly like
        # the in-process fleet's timeout-cancel path — never inflating
        # requests_failed for a client decision
        self._finish(pr, RequestCancelledError(
            f"stream reader gave up after {timeout}s"))
        return exc

    def _proc_failover(self, pr: ProcRequest, e: BaseException,
                       wait_deadline: Optional[float]) -> None:
        """Mid-stream failover: re-dispatch with the already-delivered
        tokens as the splice ``prefix`` (the sibling re-derives,
        verifies and suppresses them), under the REMAINING deadline and
        the retry budget — the PR-8 failover semantics upgraded to
        streaming across a process boundary."""
        if pr.failovers >= self.max_failovers:
            if self.max_failovers:
                with self._lock:
                    self.retries_exhausted += 1
                self._log(
                    f"gym_tpu.serve: proc-router — request exhausted "
                    f"its {self.max_failovers} failover budget; "
                    f"surfacing {type(e).__name__}", flush=True)
            raise e
        rem_dl = None
        if pr.deadline_s is not None:
            rem_dl = (pr.deadline_s
                      - (time.perf_counter() - pr.submit_t))
            if rem_dl <= 0:
                raise DeadlineExceededError(
                    f"deadline_s={pr.deadline_s:.3g} exhausted during "
                    f"replica failover — not retried") from e
        failed_rid = pr.replica_id
        # pop the pending entry only: load accounting was handed to the
        # worker at accept (the _accepts expiry), and the dead worker's
        # counters are out of dispatch anyway
        self._unpend(pr.id, pr._rep, 0)
        self._dispatch_proc(pr, rem_dl, prefix=list(pr.tokens),
                            exclude=(failed_rid,), block=True,
                            wait_deadline=wait_deadline)
        pr.failovers += 1
        with self._lock:
            self.failovers += 1
        self._log(
            f"gym_tpu.serve: proc-router — failover: request retried "
            f"on replica {pr.replica_id} with a "
            f"{len(pr.tokens)}-token splice prefix (replica "
            f"{failed_rid} failed it: {type(e).__name__}; retry "
            f"{pr.failovers}/{self.max_failovers}"
            + (f", {rem_dl:.3g}s of deadline left)"
               if rem_dl is not None else ")"), flush=True)

    def _finish(self, pr: ProcRequest,
                exc: Optional[BaseException]) -> None:
        if pr._finished:
            return
        pr._finished = True
        if exc is not None:
            pr.exception = exc
            pr.error = f"{type(exc).__name__}: {exc}"
            if pr.done_t is None:
                pr.done_t = time.perf_counter()
        # pending entry only — post-accept load accounting lives in the
        # worker's health reports (see the _accepts expiry)
        self._unpend(pr.id, pr._rep, 0)
        if self.metrics is not None:
            try:
                self.metrics.request_done(
                    pr, queue_depth=0, active_slots=0,
                    replica_id=pr.replica_id, pid=pr.pid)
            except Exception:  # noqa: BLE001 — observability only
                pass

    def _cancel(self, pr: ProcRequest, reason: str) -> bool:
        if pr._finished:
            return False
        rep = pr._rep
        if rep is not None and rep.connected:
            try:
                self._send(rep, {"type": "cancel", "id": pr.id},
                           timeout=5.0)
            except Exception:  # noqa: BLE001 — best effort: the
                pass           # worker reaps via router-disconnect too
        self._finish(pr, RequestCancelledError(
            f"request cancelled — {reason}"))
        return True

    # -- rolling weight hot-swap -------------------------------------------

    def reload(self, params: Any, *, weights_tag: Optional[str] = None,
               drain_timeout_s: float = 300.0) -> Dict[str, Any]:
        """Roll new params through the worker fleet one process at a
        time: snapshot the tree once, then each worker drains, rebuilds
        warm and resumes — zero dropped requests, same contract as the
        in-process ``Router.reload``."""
        with self._lock:
            if self._closing:
                raise SchedulerClosedError(
                    "router shutting down — reload refused")
            if self._reloading:
                raise FleetReloadError(
                    "a weight reload is already in progress")
            self._reloading = True
        t0 = time.perf_counter()
        swapped: List[int] = []
        skipped: List[int] = []
        try:
            path = self.spawner.reload_file(params, weights_tag)
            for rep in list(self.replicas):
                if not rep.healthy:
                    skipped.append(rep.id)
                    continue
                rep.draining = True
                wid = next(self._ids)
                q: "queue.Queue" = queue.Queue()
                with self._lock:
                    self._pending[wid] = (q, rep)
                try:
                    self._send(rep, {
                        "type": "reload", "id": wid,
                        "params_file": path, "tag": weights_tag,
                        "drain_timeout_s": drain_timeout_s})
                    frame = q.get(timeout=drain_timeout_s + 30.0)
                except queue.Empty:
                    raise FleetReloadError(
                        f"replica {rep.id} did not confirm the reload "
                        f"within {drain_timeout_s:.0f}s — rolling "
                        f"reload aborted ({swapped} already swapped)",
                        retry_after_s=max(5.0, drain_timeout_s))
                except Exception as e:  # noqa: BLE001 — send failure
                    raise FleetReloadError(
                        f"replica {rep.id} unreachable during reload: "
                        f"{e}", retry_after_s=5.0)
                finally:
                    with self._lock:
                        self._pending.pop(wid, None)
                    rep.draining = False
                if frame.get("type") != "reload_ok":
                    raise FleetReloadError(
                        f"replica {rep.id} reload failed: "
                        f"{frame.get('message')}", retry_after_s=5.0)
                swapped.append(rep.id)
            with self._lock:
                self.reloads += 1
                self._weights_tag = weights_tag
            wall = time.perf_counter() - t0
            self._log(
                f"gym_tpu.serve: proc-router — weight reload "
                f"{'(' + str(weights_tag) + ') ' if weights_tag else ''}"
                f"rolled through replicas {swapped} in {wall:.2f}s"
                + (f" (skipped: {skipped})" if skipped else ""),
                flush=True)
            return {"swapped": swapped, "skipped": skipped,
                    "weights_tag": weights_tag,
                    "wall_s": round(wall, 3)}
        finally:
            with self._lock:
                self._reloading = False

    # -- observability -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            reps_l = list(self.replicas)
        reps = []
        for rep in reps_l:
            h = rep.last_health
            reps.append({
                "id": rep.id,
                "pid": rep.pid,
                "healthy": rep.healthy,
                "dead": rep.dead,
                "death_reason": rep.death_reason,
                "draining": rep.draining,
                "retired": rep.retired,
                "connected": rep.connected,
                "backlog_tokens": h.get("backlog_tokens", 0),
                "queue_depth": h.get("queue_depth", 0),
                "active_requests": h.get("active_requests", 0),
                "active_slots": h.get("active_slots", 0),
                "num_slots": h.get("num_slots", 0),
                "tokens_generated": h.get("tokens_generated", 0),
                "tokens_per_s_ewma": h.get("tokens_per_s_ewma"),
                "programs_compiled": h.get("programs_compiled"),
                "engine_generation": h.get("engine_generation", 0),
                "restarts": h.get("engine_restarts", 0),
                "weights_tag": h.get("weights_tag"),
                "warmup": h.get("warmup"),
                # multi-tenant observables off the health frame (ISSUE
                # 17; absent from pre-tenant workers — a mixed fleet
                # reports what each worker knows)
                "backlog_by_class": h.get("backlog_by_class"),
                "tenants": h.get("tenants"),
            })
        with self._lock:
            live = [r for r in reps if not r["retired"]]
            return {
                "fleet": "process",
                "replicas": reps,
                "healthy_replicas": sum(1 for r in live
                                        if r["healthy"]),
                "failovers": self.failovers,
                "retries_exhausted": self.retries_exhausted,
                "weight_reloads": self.reloads,
                "replicas_spawned": self.replicas_spawned,
                "replicas_retired": self.replicas_retired,
                "weights_tag": self._weights_tag,
            }

    def autoscale_snapshot(self) -> Dict[str, Any]:
        """The autoscaler's tick input: healthy/starting counts, total
        backlog (worker-reported + router-committed) and the aggregate
        live tokens/s EWMA — exactly the per-replica observables the
        in-process fleet prices admission with."""
        with self._lock:
            live = [r for r in self.replicas if not r.retired]
            healthy = [r for r in live if r.healthy]
            # spawned-but-connecting AND draining (rolling reload)
            # replicas are TEMPORARY capacity, not missing capacity:
            # without counting them the floor rule would spawn a
            # spurious worker during every reload on a min-sized fleet
            starting = [r for r in live if not r.dead
                        and (not r.connected or r.draining)]
            backlog = sum(r.load() for r in healthy)
            ewmas = [r.last_health.get("tokens_per_s_ewma")
                     for r in healthy]
            live_rates = [e for e in ewmas if e]
            return {
                "healthy": len(healthy),
                "starting": len(starting),
                "dead": sum(1 for r in live if r.dead),
                "backlog_tokens": float(backlog),
                "tokens_per_s": (sum(live_rates)
                                 if live_rates else None),
            }


def build_process_fleet(params: Any, config: Any, base_dir: str, *,
                        replicas: int = 2, num_slots: int = 4,
                        decode_chunk: int = 1, page_size: int = 16,
                        kv_pages: Optional[int] = None,
                        spec_tokens: int = 0, max_queue: int = 64,
                        metrics=None,
                        dispatch_timeout_s: float = 120.0,
                        max_restarts: int = 5,
                        max_failovers: Optional[int] = None,
                        weights_tag: Optional[str] = None,
                        program_cache_dir: Optional[str] = None,
                        no_warmup: bool = False,
                        device: Optional[str] = "cpu",
                        env: Optional[Dict[str, str]] = None,
                        quotas: Optional[Dict[str, Any]] = None,
                        preempt: bool = False,
                        log=print) -> ProcessRouter:
    """``build_fleet``'s out-of-process twin: materialize the params
    snapshot under ``base_dir`` and stand up a ``ProcessRouter`` over
    N worker subprocesses. Not started — call ``.start()`` (and
    ``wait_ready()`` to block on worker health)."""
    spawner = WorkerSpawner(
        base_dir, params=params, config=config, num_slots=num_slots,
        decode_chunk=decode_chunk, page_size=page_size,
        kv_pages=kv_pages, spec_tokens=spec_tokens,
        max_queue=max_queue, dispatch_timeout_s=dispatch_timeout_s,
        max_restarts=max_restarts, program_cache_dir=program_cache_dir,
        weights_tag=weights_tag, no_warmup=no_warmup, device=device,
        env=env,
        quotas_json=(None if not quotas else json.dumps(
            {cls: (dataclasses.asdict(q)
                   if dataclasses.is_dataclass(q) else dict(q))
             for cls, q in quotas.items()})),
        preempt=preempt)
    return ProcessRouter(spawner, replicas=replicas, metrics=metrics,
                         max_failovers=max_failovers, log=log)
