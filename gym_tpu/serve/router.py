"""Fleet serving: N engine replicas behind one health-aware router.

One engine+scheduler+supervisor stack (PRs 4–7) caps out at one chip's
throughput, and a wedged or killed engine takes the whole service down
with it until its supervisor rebuilds. The router is the layer that
survives the loss of a *replica*:

- **Replicas** — in-process engine+scheduler+supervisor stacks
  (``build_fleet`` constructs them over one shared params tree and one
  shared ``ServeMetrics``; each replica writes through a
  ``replica_view`` so ``serve.csv`` rows and EWMAs stay per-replica).
  Health is DERIVED, not polled: a replica is out of dispatch exactly
  when its supervisor declared the engine dead (``failed`` set, hooked
  live via ``Supervisor.on_dead``) or while a rolling reload drains it.
- **Dispatch** — least-loaded by committed backlog tokens
  (``Scheduler.backlog_tokens``) with a prefix-cache-aware bonus: on
  paged engines ``admit_probe``'s resident-prefix score (× page_size
  tokens of elided prefill work) is subtracted from the load, so
  shared-prefix traffic sticks to the replica that already holds the
  pages instead of re-prefilling them on a cold sibling. Ties break to
  the lowest replica id (deterministic; a single replica degrades to
  the PR-5 path exactly).
- **Failover** — a replica that dies or wedges mid-request fails its
  in-flight requests typed (``EngineFailedError`` via the supervisor,
  ``SchedulerClosedError`` for its queued requests when it is declared
  dead). ``FleetRequest.result`` catches those and transparently
  re-dispatches to a sibling under the request's REMAINING deadline
  (original ``deadline_s`` minus elapsed since the fleet submit entry —
  the PR-5 submit-entry anchor, so a retried request can never wait two
  full deadlines), bounded by ``max_failovers``. The engine is
  deterministic (same params, same seed ⇒ the exact ``generate_fast``
  stream), so the winning attempt's stream IS the uncontended stream —
  no duplicate tokens, no gaps; partial tokens from the dead attempt
  are discarded, never concatenated.
- **Degradation** — when every live replica rejects a deadline at
  admission the router re-raises the cheapest ``AdmissionRejectedError``
  (HTTP 429 + Retry-After); when every queue is full it waits bounded by
  the submit timeout/deadline then raises ``QueueFullError``; when every
  replica is dead it raises ``NoHealthyReplicaError`` (HTTP 503). The
  PR-5 admission machinery becomes fleet-level load shedding.
- **Zero-downtime weight hot-swap** (``reload``) — roll new params
  through the replicas ONE AT A TIME: pause the replica's admission and
  stop dispatching to it, wait for its in-flight requests to finish
  (queued requests keep their place), rebuild the engine from the
  updated params box via the replica's factory — warm through the
  global program LRUs: same config ⇒ ZERO recompiles — swap it into
  the scheduler, resume. Siblings keep serving throughout, so a
  trainer's newest checkpoint enters the fleet without dropping a
  single in-flight request. The rebuild (not an in-place param write)
  is deliberate: a fresh engine gets a fresh paged allocator/prefix
  cache, so prefix blocks computed under the OLD weights can never be
  served against the new ones.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.resilience import dump_thread_stacks
from .engine import InferenceEngine, SamplingParams
from .scheduler import (AdmissionRejectedError, DeadlineExceededError,
                        EngineFailedError, QueueFullError, Request,
                        RequestStatus, Scheduler, SchedulerClosedError)
from .supervisor import Supervisor

PyTree = Any


class NoHealthyReplicaError(RuntimeError):
    """Every replica in the fleet is dead (or the fleet is empty): the
    request cannot be dispatched anywhere. HTTP maps this to 503 —
    fleet-level degradation, not a traceback."""

    def __init__(self, msg: str, retry_after_s: float = 10.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class FleetReloadError(RuntimeError):
    """A rolling weight reload could not proceed: one is already in
    flight (``retry_after_s`` is None → HTTP 409), or a replica failed
    to drain inside the bound (``retry_after_s`` set → HTTP 503, the
    condition is transient; the partial state is reported —
    already-swapped replicas STAY swapped)."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class Replica:
    """One fleet member: its scheduler/supervisor stack plus the engine
    factory the supervisor rebuilds from (reading the router's params
    box, so a post-reload failover rebuilds with the NEW weights)."""

    id: int
    scheduler: Scheduler
    supervisor: Supervisor
    engine_factory: Callable[[], InferenceEngine]
    metrics: Any = None
    draining: bool = False

    @property
    def dead(self) -> bool:
        return self.supervisor.failed is not None

    @property
    def healthy(self) -> bool:
        return not self.dead and not self.draining


class FleetRequest:
    """Router-level request handle, mirroring ``scheduler.Request``'s
    wait surface (``result`` / ``tokens`` / ``ttft_s`` / ``done_t``) so
    the HTTP handler treats both alike. ``result`` performs the bounded
    failover retries; ``replica_id`` names the replica currently (or
    finally) serving the request and ``failovers`` how many times it was
    re-dispatched. TTFT is anchored at the FLEET submit entry, so a
    failed-over request's reported latency honestly includes the
    failover."""

    def __init__(self, router: "Router", prompt: np.ndarray,
                 sampling: SamplingParams, deadline_s: Optional[float],
                 submit_t: float):
        self._router = router
        self.prompt = prompt
        self.sampling = sampling
        self.deadline_s = deadline_s
        self.submit_t = submit_t
        self.failovers = 0
        self.replica_id: int = -1
        self._inner: Optional[Request] = None

    # -- Request-compatible surface --------------------------------------

    @property
    def id(self) -> int:
        return self._inner.id

    @property
    def status(self) -> RequestStatus:
        return self._inner.status

    @property
    def tokens(self) -> List[int]:
        return list(self._inner.tokens)

    @property
    def error(self) -> Optional[str]:
        return self._inner.error

    @property
    def exception(self) -> Optional[BaseException]:
        return self._inner.exception

    @property
    def done_t(self) -> Optional[float]:
        return self._inner.done_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self._inner.first_token_t is None:
            return None
        return self._inner.first_token_t - self.submit_t

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block for the tokens, transparently failing over to a sibling
        replica (bounded retries, remaining-deadline forwarded) when the
        serving replica dies mid-request. Raises the TYPED terminal
        failure otherwise — exactly ``Request.result``'s contract."""
        return self._router._await(self, timeout)


class Router:
    """Health-aware dispatch + failover + rolling weight reload over a
    list of ``Replica``s. Thread-safe: any number of handler threads
    call ``submit``/``result``; the internal lock guards only counters
    and flags (never held across a blocking call)."""

    def __init__(self, replicas: Sequence[Replica], *,
                 metrics=None, max_failovers: Optional[int] = None,
                 params_box: Optional[Dict[str, Any]] = None,
                 prefix_bonus_weight: float = 1.0, log=print):
        """``max_failovers`` bounds per-request re-dispatches; the
        default ``min(2, N-1)`` keeps a single-replica fleet EXACTLY on
        the PR-5 path (a typed failure surfaces to the client, no silent
        same-replica retry) while a real fleet retries on siblings.
        ``params_box`` is the mutable weights container every replica's
        engine factory reads (``reload`` updates it first, so failover
        rebuilds during a rolling swap already use the new params)."""
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        self.metrics = metrics
        self.params_box = params_box if params_box is not None else {}
        self.max_failovers = (min(2, len(self.replicas) - 1)
                              if max_failovers is None
                              else max(0, int(max_failovers)))
        self.prefix_bonus_weight = float(prefix_bonus_weight)
        self._log = log
        self._lock = threading.Lock()
        self._closing = False
        self._reloading = False
        self.failovers = 0
        self.retries_exhausted = 0
        self.reloads = 0
        for rep in self.replicas:
            rep.supervisor.on_dead = (
                lambda error, rid=rep.id: self._on_replica_dead(rid, error))

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Router":
        for rep in self.replicas:
            rep.supervisor.start()
        return self

    def close(self, drain_deadline_s: float = 300.0) -> bool:
        """Stop every replica's driver and drain it (answer in-flight,
        fail queued typed). A replica whose driver is WEDGED past the
        deadline gets its thread stacks dumped (per-replica evidence)
        and its requests failed typed without touching its engine.
        Returns True when every replica drained cleanly."""
        with self._lock:
            self._closing = True
        clean = True
        for rep in self.replicas:
            if rep.supervisor.stop(join_timeout_s=drain_deadline_s):
                rep.scheduler.shutdown(finish_running=True,
                                       deadline_s=drain_deadline_s)
            else:
                clean = False
                sys.stderr.write(dump_thread_stacks(
                    f"gym_tpu.serve: router — replica {rep.id} driver "
                    f"wedged past the {drain_deadline_s:.0f}s drain "
                    f"deadline:"))
                sys.stderr.flush()
                # flag writes only — never step a wedged engine from
                # another thread; blocked handlers still get answers
                rep.scheduler.shutdown(finish_running=False,
                                       deadline_s=0.0)
        return clean

    def _on_replica_dead(self, rid: int, error: BaseException) -> None:
        # health is derived from supervisor.failed (already set when
        # this fires); the hook exists for the log line and so tests can
        # observe the exact moment a replica left dispatch
        self._log(f"gym_tpu.serve: router — replica {rid} declared dead "
                  f"({type(error).__name__}: {error}); excluded from "
                  f"dispatch", flush=True)

    # -- dispatch ---------------------------------------------------------

    def _score(self, rep: Replica, prompt: np.ndarray,
               sp: SamplingParams) -> float:
        """Lower = better: committed backlog tokens minus the resident
        shared-prefix bonus (tokens of prefill work the replica's paged
        cache would elide). The probe reads allocator state owned by the
        replica's driver thread — it is ADVISORY, so a racing mutation
        degrades to bonus 0, never to a failed dispatch."""
        load = float(rep.scheduler.backlog_tokens())
        bonus = 0.0
        try:
            eng = rep.scheduler.engine
            if getattr(eng, "paged", False):
                bonus = (eng.admit_probe(prompt, sp)[1] * eng.page_size
                         * self.prefix_bonus_weight)
        except Exception:  # noqa: BLE001 — cross-thread probe race:
            bonus = 0.0    # stickiness lost for one pick, nothing else
        return load - bonus

    def _candidates(self, prompt: np.ndarray, sp: SamplingParams,
                    exclude: Tuple[int, ...] = ()) -> List[Replica]:
        alive = [r for r in self.replicas
                 if not r.dead and r.id not in exclude]
        ready = [r for r in alive if not r.draining]
        # a fully-draining fleet (rolling reload on N=1) still ACCEPTS:
        # requests queue on the paused scheduler and admit onto the new
        # engine — that is what makes the swap zero-downtime at N=1
        pool = ready or alive
        return sorted(pool,
                      key=lambda r: (self._score(r, prompt, sp), r.id))

    def submit(self, prompt, sampling: Optional[SamplingParams] = None,
               block: bool = True, timeout: Optional[float] = 30.0,
               deadline_s: Optional[float] = None) -> FleetRequest:
        """Dispatch to the best healthy replica. Same contract as
        ``Scheduler.submit`` (typed ``ValueError`` for bad requests,
        ``AdmissionRejectedError``/``QueueFullError`` backpressure,
        deadline caps the queue-full wait) plus
        ``NoHealthyReplicaError`` when the whole fleet is dead."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        t_entry = time.perf_counter()
        if deadline_s is not None and not deadline_s > 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}); omit it for "
                f"no deadline")
        cap = timeout
        if deadline_s is not None:
            cap = deadline_s if cap is None else min(cap, deadline_s)
        wait_deadline = None if cap is None else t_entry + cap
        fr = FleetRequest(self, prompt, sampling, deadline_s, t_entry)
        fr._inner, fr.replica_id = self._dispatch(
            prompt, sampling, deadline_s, exclude=(), block=block,
            wait_deadline=wait_deadline)
        return fr

    def _dispatch(self, prompt: np.ndarray, sampling: SamplingParams,
                  deadline_s: Optional[float],
                  exclude: Tuple[int, ...], block: bool,
                  wait_deadline: Optional[float]
                  ) -> Tuple[Request, int]:
        """Try candidates best-first; degrade typed. ``exclude`` is a
        PREFERENCE (a failover avoids the replica that just failed it)
        — when exclusion empties the pool it is lifted rather than
        refusing a dispatch a live replica could serve."""
        while True:
            with self._lock:
                if self._closing:
                    raise SchedulerClosedError(
                        "router shutting down — request not dispatched")
            cands = self._candidates(prompt, sampling, exclude)
            if not cands and exclude:
                cands = self._candidates(prompt, sampling, ())
            if not cands:
                raise NoHealthyReplicaError(
                    f"all {len(self.replicas)} replica(s) are dead — "
                    f"fleet unrecoverable without a restart")
            rejects: List[AdmissionRejectedError] = []
            full = closing = 0
            for rep in cands:
                try:
                    req = rep.scheduler.submit(
                        prompt, sampling, block=False,
                        deadline_s=deadline_s)
                    return req, rep.id
                except AdmissionRejectedError as e:
                    rejects.append(e)
                except QueueFullError:
                    full += 1
                except SchedulerClosedError:
                    closing += 1    # replica died between the pick and
                    #                 the submit (its scheduler refuses
                    #                 before `failed` is set); the next
                    #                 loop re-derives health
                # ValueError (bad request) propagates: every replica
                # runs the same config, no sibling would accept it
            if rejects and not full:
                # every live replica's admission control says the
                # deadline is infeasible: fleet-level shed, cheapest
                # retry hint wins
                raise min(rejects, key=lambda e: e.retry_after_s)
            if not block and full:
                raise QueueFullError(
                    f"every replica's queue is at capacity")
            if not block:
                # nothing was full — every candidate was mid-death: a
                # health signal (503 + retry), not a backpressure one
                raise NoHealthyReplicaError(
                    f"every dispatchable replica is shutting down or "
                    f"being declared dead", retry_after_s=1.0)
            rem = (None if wait_deadline is None
                   else wait_deadline - time.perf_counter())
            if rem is not None and rem <= 0:
                if full:
                    raise QueueFullError(
                        f"every replica's queue still at capacity after "
                        f"the submit wait")
                raise NoHealthyReplicaError(
                    f"every dispatchable replica still shutting down or "
                    f"being declared dead after the submit wait",
                    retry_after_s=1.0)
            time.sleep(min(0.02, rem) if rem is not None else 0.02)

    # -- result wait + failover -------------------------------------------

    def _await(self, fr: FleetRequest,
               timeout: Optional[float]) -> List[int]:
        wait_deadline = (None if timeout is None
                         else time.perf_counter() + timeout)
        while True:
            rem = (None if wait_deadline is None
                   else max(0.0, wait_deadline - time.perf_counter()))
            try:
                return fr._inner.result(rem)
            except (EngineFailedError, SchedulerClosedError) as e:
                with self._lock:
                    closing = self._closing
                if closing:
                    raise
                if fr.failovers >= self.max_failovers:
                    if self.max_failovers:
                        with self._lock:
                            self.retries_exhausted += 1
                        self._log(
                            f"gym_tpu.serve: router — request {fr.id} "
                            f"exhausted its {self.max_failovers} "
                            f"failover retr"
                            f"{'y' if self.max_failovers == 1 else 'ies'}"
                            f"; surfacing {type(e).__name__}", flush=True)
                    raise
                # satellite: forward the REMAINING deadline, anchored at
                # the fleet submit entry — a retried request can never
                # wait two full deadlines
                rem_dl = None
                if fr.deadline_s is not None:
                    rem_dl = (fr.deadline_s
                              - (time.perf_counter() - fr.submit_t))
                    if rem_dl <= 0:
                        raise DeadlineExceededError(
                            f"deadline_s={fr.deadline_s:.3g} exhausted "
                            f"during replica failover — not retried"
                        ) from e
                failed_rid = fr.replica_id
                # a failed dispatch here degrades typed (all dead → 503,
                # sibling sheds the remaining deadline → 429, …): the
                # client gets the fleet's honest answer, chained to the
                # failure that triggered the retry
                inner, rid = self._dispatch(
                    fr.prompt, fr.sampling, rem_dl,
                    exclude=(failed_rid,), block=True,
                    wait_deadline=wait_deadline)
                fr.failovers += 1
                with self._lock:
                    self.failovers += 1
                fr._inner, fr.replica_id = inner, rid
                self._log(
                    f"gym_tpu.serve: router — failover: request retried "
                    f"on replica {rid} (replica {failed_rid} failed it: "
                    f"{type(e).__name__}; retry {fr.failovers}/"
                    f"{self.max_failovers}"
                    + (f", {rem_dl:.3g}s of deadline left)"
                       if rem_dl is not None else ")"), flush=True)

    # -- zero-downtime weight hot-swap -------------------------------------

    def reload(self, params: PyTree, *, weights_tag: Optional[str] = None,
               drain_timeout_s: float = 300.0) -> Dict[str, Any]:
        """Roll ``params`` through the fleet one replica at a time with
        ZERO dropped requests and (same config) ZERO recompiles: pause
        the replica's admission + stop dispatching to it, wait for its
        in-flight requests to finish, rebuild its engine from the
        updated params box (warm via the global program LRUs), swap,
        resume. Dead replicas are skipped (a later supervisor rebuild
        would use the new params anyway — the box is already updated).
        Serialized: a second concurrent reload raises
        ``FleetReloadError`` instead of interleaving two rollouts."""
        with self._lock:
            if self._closing:
                raise SchedulerClosedError(
                    "router shutting down — reload refused")
            if self._reloading:
                raise FleetReloadError(
                    "a weight reload is already in progress")
            self._reloading = True
        t0 = time.perf_counter()
        swapped: List[int] = []
        skipped: List[int] = []
        try:
            # box first: any failover rebuild from here on — including
            # on replicas not yet reached — already serves the new
            # weights (its in-flight died with the old engine regardless)
            self.params_box["params"] = params
            if weights_tag is not None:
                self.params_box["tag"] = weights_tag
            for rep in self.replicas:
                if rep.dead:
                    skipped.append(rep.id)
                    continue
                rep.draining = True
                rep.scheduler.pause_admission()
                try:
                    deadline = time.perf_counter() + drain_timeout_s
                    while rep.scheduler.inflight() and not rep.dead:
                        if time.perf_counter() > deadline:
                            raise FleetReloadError(
                                f"replica {rep.id} did not drain within "
                                f"{drain_timeout_s:.0f}s — rolling "
                                f"reload aborted (replicas {swapped} "
                                f"already swapped, {skipped} skipped)",
                                retry_after_s=max(5.0, drain_timeout_s))
                        time.sleep(0.002)
                    if rep.dead:
                        skipped.append(rep.id)
                        continue
                    engine = rep.engine_factory()
                    rep.scheduler.replace_engine(engine)
                    if rep.metrics is not None:
                        rep.metrics.engine_reloaded()
                    swapped.append(rep.id)
                finally:
                    rep.scheduler.resume_admission()
                    rep.draining = False
            with self._lock:
                self.reloads += 1
            wall = time.perf_counter() - t0
            self._log(
                f"gym_tpu.serve: router — weight reload "
                f"{'(' + str(self.params_box.get('tag')) + ') ' if self.params_box.get('tag') else ''}"
                f"rolled through replicas {swapped} in {wall:.2f}s"
                + (f" (skipped dead: {skipped})" if skipped else ""),
                flush=True)
            return {"swapped": swapped, "skipped": skipped,
                    "weights_tag": self.params_box.get("tag"),
                    "wall_s": round(wall, 3)}
        finally:
            with self._lock:
                self._reloading = False

    # -- observability -----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        reps = []
        for rep in self.replicas:
            eng = rep.scheduler.engine
            entry = {
                "id": rep.id,
                "healthy": rep.healthy,
                "dead": rep.dead,
                "draining": rep.draining,
                "restarts": rep.supervisor.restarts,
                "engine_generation": rep.supervisor.generation,
                "queue_depth": rep.scheduler.queue_depth(),
                "active_requests": rep.scheduler.active_requests(),
                "backlog_tokens": rep.scheduler.backlog_tokens(),
                "weights_tag": getattr(eng, "weights_tag", None),
            }
            if rep.metrics is not None:
                entry["tokens_per_s_ewma"] = rep.metrics.tokens_per_s_ewma()
            reps.append(entry)
        with self._lock:
            return {
                "replicas": reps,
                "healthy_replicas": sum(1 for r in reps if r["healthy"]),
                "failovers": self.failovers,
                "retries_exhausted": self.retries_exhausted,
                "weight_reloads": self.reloads,
                "weights_tag": self.params_box.get("tag"),
            }


def build_fleet(params: PyTree, config, *, replicas: int = 1,
                num_slots: int = 4, decode_chunk: int = 1,
                paged: bool = False, page_size: int = 16,
                kv_pages: Optional[int] = None, spec_tokens: int = 0,
                max_queue: int = 64, metrics=None,
                dispatch_timeout_s: float = 120.0, max_restarts: int = 5,
                max_failovers: Optional[int] = None,
                weights_tag: Optional[str] = None,
                prefix_bonus_weight: float = 1.0, log=print) -> Router:
    """Construct a ``Router`` over N identical in-process replica
    stacks sharing one params tree and one metrics collector (each
    replica writes through its ``replica_view``). Supervisors are NOT
    started — call ``router.start()``. With ``replicas=1`` and the
    default retry budget (0), the stack behaves exactly like the PR-5
    single-engine server."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    box: Dict[str, Any] = {"params": params, "tag": weights_tag}
    reps: List[Replica] = []
    for rid in range(int(replicas)):
        view = (metrics.replica_view(rid)
                if metrics is not None else None)

        def factory(rid=rid):
            return InferenceEngine(
                box["params"], config, num_slots=num_slots,
                decode_chunk=decode_chunk, paged=paged,
                page_size=page_size, kv_pages=kv_pages,
                spec_tokens=spec_tokens, weights_tag=box.get("tag"))

        sched = Scheduler(factory(), max_queue=max_queue, metrics=view)
        sup = Supervisor(sched, factory,
                         dispatch_timeout_s=dispatch_timeout_s,
                         max_restarts=max_restarts, metrics=view, log=log)
        reps.append(Replica(id=rid, scheduler=sched, supervisor=sup,
                            engine_factory=factory, metrics=view))
    return Router(reps, metrics=metrics, max_failovers=max_failovers,
                  params_box=box, prefix_bonus_weight=prefix_bonus_weight,
                  log=log)
