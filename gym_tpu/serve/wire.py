"""Length-prefixed JSON wire protocol for the out-of-process fleet.

One replica worker process (``serve/worker.py``) and the router's
process-fleet dispatcher (``serve/router.py:ProcessRouter``) speak this
protocol over a local stream socket (AF_UNIX). Design constraints, in
order:

- **Typed failure, never a hang.** Every malformed input — truncated
  stream, oversized length prefix, non-JSON payload, unknown frame
  type — raises a ``WireError`` subclass the caller can branch on.
  A reader can never block forever on a half-frame (the transport EOF
  surfaces as ``TruncatedFrameError``) and never allocates an
  attacker-sized buffer (the length prefix is validated BEFORE the
  payload is read).
- **Self-describing frames.** Every frame is a JSON object with a
  ``type`` drawn from ``FRAME_TYPES``; request-scoped frames carry the
  router-assigned ``id`` so one connection multiplexes any number of
  concurrent streams (submit → accepted → chunk* → done | error).
- **stdlib only, jax-free.** The module imports neither jax nor any
  serving internals (``utils.resilience``/``utils.integrity`` are
  themselves stdlib-only), so the frame codec is unit-testable in
  microseconds and the worker can parse a ``stop`` frame even while its
  engine is wedged.
- **Content integrity (ISSUE 20).** Every outgoing frame carries a
  ``crc`` field — zlib crc32 of the frame's canonical JSON encoding
  (sorted keys, ``crc`` excluded; C-speed because this runs per frame
  on the token hot path, unlike the checkpoint sidecars' crc32c).
  ``decode_payload`` verifies against the raw payload bytes and
  raises the typed ``FrameCorruptError`` on mismatch, so a bit flip on
  the wire becomes a failover (the router's reader treats it like any
  ``WireError``: replica marked dead, in-flight requests re-spliced on
  a sibling) and NEVER a silently wrong token. Frames WITHOUT ``crc``
  are accepted unverified — mixed-fleet soft-degrade, the same rule as
  PR 17's unknown-field tolerance. ``encode_frame`` is also the
  ``wire.frame`` corruption fault site (the encoded bytes pass through
  ``corrupt_point``), which is how the chaos campaigns prove the
  detector works.

Frame vocabulary (router → worker unless noted):

====================== ==================================================
``submit``             ``id``, ``prompt`` (token ids), ``sampling``
                       (SamplingParams fields), optional ``deadline_s``,
                       optional ``prefix`` — tokens already delivered to
                       the client by a previous attempt; the worker
                       re-derives them (deterministic engine), VERIFIES
                       them, and streams only what follows: the failover
                       splice.
``accepted``           (worker) ``id`` — the scheduler admitted the
                       request; failures before this are dispatch
                       failures (try a sibling), after it failovers.
``chunk``              (worker) ``id``, ``tokens`` — new tokens, in
                       order, at decode-chunk granularity.
``done``               (worker) ``id``, ``tokens_total``, ``ttft_s``.
``error``              (worker) ``id``, ``error_type``, ``message``,
                       optional ``retry_after_s`` — a typed scheduler
                       failure, reconstructed via ``frame_to_exception``.
``cancel``             ``id`` — client went away: cancel at the next
                       decode-chunk boundary, free the slot.
``health``             (router, periodic) → ``health_ok`` (worker):
                       ``pid``, ``backlog_tokens``, ``queue_depth``,
                       ``active_slots``, ``tokens_per_s_ewma``,
                       ``programs_compiled``, ``dead``, engine samples.
``reload``             ``params_file``, optional ``tag`` → ``reload_ok``
                       — drain + rebuild the engine from the new params
                       (the rolling hot-swap, one worker at a time).
``stop``               graceful drain → ``stop_ok``, then the worker
                       exits 0.
``hello``              (worker, on connect) ``pid``, ``replica_id`` —
                       the readiness handshake.
====================== ==================================================
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, Callable, Dict, Optional

from ..utils.resilience import corrupt_point

#: Hard cap on one frame's JSON payload. Generous for token streams
#: (a 1M-token chunk is ~8 MB of JSON) yet small enough that a corrupt
#: length prefix cannot demand an absurd allocation.
MAX_FRAME_BYTES = 16 << 20

_LEN = struct.Struct(">I")

FRAME_TYPES = frozenset({
    "submit", "accepted", "chunk", "done", "error", "cancel",
    "health", "health_ok", "stats", "stats_ok",
    "reload", "reload_ok", "stop", "stop_ok", "hello",
})


class WireError(RuntimeError):
    """Base class for every protocol violation — callers that just need
    "this peer is speaking garbage" catch this one."""


class FrameTooLargeError(WireError):
    """The length prefix (or an outgoing payload) exceeds
    ``MAX_FRAME_BYTES`` — rejected before any payload is read/sent."""


class TruncatedFrameError(WireError):
    """The stream ended mid-frame (inside the length prefix or the
    payload): the peer died or the transport corrupted. Distinct from a
    CLEAN close, which ``read_frame`` reports as ``None``."""


class MalformedFrameError(WireError):
    """The payload is not a JSON object with a known ``type`` — the
    frame is syntactically present but semantically garbage."""


class FrameCorruptError(WireError):
    """The frame's ``crc`` disagrees with its content — the bytes were
    silently corrupted in transit (or by an injected ``wire.frame``
    fault). A ``WireError`` subclass on purpose: the router's reader
    loop already maps any ``WireError`` to mark-dead + failover, which
    is exactly the right response to a peer whose bytes can't be
    trusted."""


def _frame_crc(frame: Dict[str, Any]) -> int:
    """crc32 over the frame's CANONICAL encoding (sorted keys, compact
    separators, ``crc`` excluded). Canonicalizing makes the checksum
    independent of key order and of the sender's ``json.dumps``
    settings — both ends must agree on the bytes being summed, and a
    decoded dict no longer remembers the wire bytes it came from.

    zlib's C crc32 rather than the sidecars' pure-Python crc32c: this
    runs per frame on the token streaming hot path, where the Python
    table walk (~12 µs/frame, measured) cost the subprocess fleet its
    throughput edge over the thread fleet. Checkpoint sidecars keep
    crc32c — they hash megabytes once per save, not bytes per token."""
    body = {k: v for k, v in frame.items() if k != "crc"}
    return zlib.crc32(json.dumps(
        body, sort_keys=True, separators=(",", ":")).encode())


# Wire layout of a checksummed frame: the canonical body dump with
# ',"crc":"xxxxxxxx"}' spliced over the closing brace. Emitting the
# EXACT bytes the crc was computed over lets decode verify against the
# raw payload (one zlib.crc32 call, no re-serialization); the canonical
# re-encode in _frame_crc is only the fallback for foreign encoders.
_CRC_SUFFIX_LEN = len(',"crc":"00000000"}')


def encode_frame(frame: Dict[str, Any]) -> bytes:
    """``frame`` → ``>I``-length-prefixed UTF-8 JSON bytes. Validates
    the same invariants ``read_frame`` enforces so a bad frame fails on
    the SENDING side, where the stack trace names the bug."""
    if not isinstance(frame, dict):
        raise MalformedFrameError(
            f"frame must be a dict, got {type(frame).__name__}")
    ftype = frame.get("type")
    if ftype not in FRAME_TYPES:
        raise MalformedFrameError(
            f"unknown frame type {ftype!r} (known: "
            f"{sorted(FRAME_TYPES)})")
    try:
        if "crc" in frame:  # never double-stamp a re-encoded frame
            frame = {k: v for k, v in frame.items() if k != "crc"}
        canon = json.dumps(frame, sort_keys=True, separators=(",", ":"))
        payload = (
            f'{canon[:-1]},"crc":"{zlib.crc32(canon.encode()):08x}"}}'
            if canon != "{}" else "{}").encode()
    except (TypeError, ValueError) as e:
        raise MalformedFrameError(
            f"frame is not JSON-serializable: {e}") from e
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameTooLargeError(
            f"frame payload {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap")
    # The wire.frame corruption site operates on the PAYLOAD, before the
    # length prefix is computed: framing stays intact, so an injected
    # bitflip/truncation must be caught by the CONTENT layer (crc or
    # JSON parse) — the detector under test — not by accidental
    # misframing. Misframed/truncated streams have their own typed
    # coverage (TruncatedFrameError / FrameTooLargeError).
    payload = corrupt_point("wire.frame", payload)
    return _LEN.pack(len(payload)) + payload


def decode_payload(payload: bytes) -> Dict[str, Any]:
    """Validate + parse one frame payload (the bytes AFTER the length
    prefix). The single point both the blocking and the async readers
    funnel through."""
    # Fast verify on the RAW bytes: our encoder emits exactly the
    # canonical body with the crc suffix spliced over the closing
    # brace, so checksumming payload-minus-suffix reproduces the
    # stamped value without parsing or re-serializing anything. Any
    # corruption — body, suffix, or the crc digits themselves — makes
    # this miss, and we fall through to the canonical-recompute path
    # (which also verifies frames from foreign encoders that place the
    # field elsewhere).
    fast_verified = False
    if len(payload) > _CRC_SUFFIX_LEN and payload.endswith(b'"}') \
            and payload[-_CRC_SUFFIX_LEN:-10] == b',"crc":"':
        body_bytes = payload[: -_CRC_SUFFIX_LEN] + b"}"
        fast_verified = (
            payload[-10:-2] == b"%08x" % zlib.crc32(body_bytes))
        if fast_verified:
            payload = body_bytes
    try:
        frame = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise MalformedFrameError(f"frame payload is not JSON: {e}") from e
    if not isinstance(frame, dict):
        raise MalformedFrameError(
            f"frame must decode to an object, got "
            f"{type(frame).__name__}")
    crc = frame.pop("crc", None)
    if crc is not None and not fast_verified:
        # Verify-and-strip: downstream handlers never see the field, so
        # strict field validators (the worker's submit whitelist) need
        # no knowledge of it. A crc-less frame is an OLD-format peer —
        # accepted unverified (mixed-fleet soft-degrade).
        want = f"{_frame_crc(frame):08x}"
        if crc != want:
            raise FrameCorruptError(
                f"frame crc mismatch: carried {crc!r}, content hashes "
                f"to {want!r} — bytes corrupted in transit "
                f"(type={frame.get('type')!r}, id={frame.get('id')!r})")
    if frame.get("type") not in FRAME_TYPES:
        raise MalformedFrameError(
            f"unknown frame type {frame.get('type')!r}")
    return frame


def read_frame(recv: Callable[[int], bytes]) -> Optional[Dict[str, Any]]:
    """Read one frame via ``recv(n) -> bytes`` (a ``socket.recv``-shaped
    callable: returns at MOST n bytes, b'' on EOF). Returns the decoded
    frame, or ``None`` on a clean EOF at a frame boundary. Raises
    ``TruncatedFrameError`` on EOF mid-frame, ``FrameTooLargeError``
    before reading an oversized payload, ``MalformedFrameError`` on
    garbage — typed, never a hang, never a partial-read corruption
    (either a whole frame is returned or the stream is declared bad)."""
    header = _read_exact(recv, _LEN.size, allow_clean_eof=True)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise FrameTooLargeError(
            f"frame length prefix {length} exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap — refusing to read the payload")
    payload = _read_exact(recv, length, allow_clean_eof=False)
    return decode_payload(payload)


def _read_exact(recv: Callable[[int], bytes], n: int,
                allow_clean_eof: bool) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = recv(n - len(buf))
        if not chunk:
            if allow_clean_eof and not buf:
                return None
            raise TruncatedFrameError(
                f"stream ended after {len(buf)} of {n} expected bytes")
        buf.extend(chunk)
    return bytes(buf)


async def read_frame_async(reader) -> Dict[str, Any]:
    """One frame from an ``asyncio.StreamReader`` — the async twin of
    ``read_frame``, sharing the same length-prefix validation and
    ``decode_payload`` so the framing invariants live in ONE place.
    Raises ``asyncio.IncompleteReadError`` on EOF (the async reader's
    native truncation signal) and the same typed ``WireError``
    subclasses otherwise."""
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise FrameTooLargeError(
            f"frame length prefix {length} exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap — refusing to read the payload")
    payload = await reader.readexactly(length)
    return decode_payload(payload)


def send_frame(sock: socket.socket, frame: Dict[str, Any]) -> None:
    """Blocking send of one whole frame (``sendall`` — no partial
    writes survive)."""
    sock.sendall(encode_frame(frame))


def recv_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Blocking read of one whole frame from a socket (see
    ``read_frame`` for the error contract)."""
    return read_frame(sock.recv)


# -- typed exceptions over the wire ---------------------------------------

#: Exception class names a worker may legitimately report. The router
#: reconstructs these TYPED (same class, same message) so the HTTP
#: status mapping — 429/503/504, Retry-After — is identical whether the
#: failure happened in-process or across the socket. Import is deferred
#: so wire.py stays jax-free for the codec unit tests.
_SCHEDULER_ERRORS = (
    "AdmissionRejectedError", "QuotaExceededError", "QueueFullError",
    "DeadlineExceededError", "EngineFailedError", "SlotQuarantinedError",
    "SchedulerClosedError", "RequestCancelledError",
    "RequestFailedError",
)


def exception_to_frame(req_id: Any, exc: BaseException) -> Dict[str, Any]:
    """Serialize a request failure as an ``error`` frame, preserving the
    class name and the admission-control ``retry_after_s`` hint."""
    frame: Dict[str, Any] = {
        "type": "error", "id": req_id,
        "error_type": type(exc).__name__, "message": str(exc),
    }
    retry = getattr(exc, "retry_after_s", None)
    if retry is not None:
        frame["retry_after_s"] = float(retry)
    return frame


def frame_to_exception(frame: Dict[str, Any]) -> BaseException:
    """Reconstruct the typed exception an ``error`` frame carries.
    Unknown/unmappable types degrade to ``EngineFailedError`` (retry is
    safe: the worker-side request died with its engine state) rather
    than losing the failure or inventing an untyped RuntimeError."""
    name = frame.get("error_type")
    msg = str(frame.get("message", "worker reported an error"))
    if name == "ValueError":
        return ValueError(msg)
    if name in _SCHEDULER_ERRORS:
        from . import scheduler as _sched
        cls = getattr(_sched, name, None)
        if cls is not None:
            if name in ("AdmissionRejectedError", "QuotaExceededError"):
                # both take (msg, retry_after_s) — the Retry-After hint
                # must survive the socket hop so the router's
                # cheapest-reject ladder and the HTTP 429 stay exact
                return cls(msg, retry_after_s=float(
                    frame.get("retry_after_s", 1.0)))
            return cls(msg)
    from .scheduler import EngineFailedError
    return EngineFailedError(f"{name}: {msg}")


def sampling_to_dict(sp: Any) -> Dict[str, Any]:
    """``SamplingParams`` → JSON-safe dict (dataclass-agnostic so wire
    stays import-light)."""
    return {
        "max_new_tokens": int(sp.max_new_tokens),
        "temperature": float(sp.temperature),
        "top_k": None if sp.top_k is None else int(sp.top_k),
        "top_p": None if sp.top_p is None else float(sp.top_p),
        "eos_token": None if sp.eos_token is None else int(sp.eos_token),
        "seed": int(sp.seed),
    }


def sampling_from_dict(d: Dict[str, Any]):
    from .engine import SamplingParams
    return SamplingParams(
        max_new_tokens=int(d.get("max_new_tokens", 32)),
        temperature=float(d.get("temperature", 1.0)),
        top_k=None if d.get("top_k") is None else int(d["top_k"]),
        top_p=None if d.get("top_p") is None else float(d["top_p"]),
        eos_token=(None if d.get("eos_token") is None
                   else int(d["eos_token"])),
        seed=int(d.get("seed", 0)))
