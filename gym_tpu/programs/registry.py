"""Unified device-program registry: one owner for every compiled program.

Before this module the repo compiled XLA programs in four unrelated
places — the trainer's per-fit ``jax.jit``, six module-global
``functools.lru_cache`` stores in ``serve/engine.py``, the persistent
compile cache wired by ``utils/compile_cache.py``, and the fleet
hot-swap's "warm global LRUs".  The registry collapses them into one
keyed store with three perf layers:

1. **Single-flight in-memory store.**  Programs are keyed by the
   canonical sha256 key from ``programs.keys`` (the same key the jaxpr
   auditor reports).  Two threads — two replicas, a warmup thread and a
   request, trainer and server — requesting the same key trigger
   exactly ONE build: the first holds the per-key build lock, the rest
   block on it and share the result.  Hits, builds, XLA compiles, disk
   hits and compile-seconds are counted and exported (``/stats``,
   ``serve.csv``, ``bench.py``).

2. **Persistent executable tier.**  ``enable_disk_tier`` points JAX's
   persistent compilation cache at a directory (owning what
   ``utils/compile_cache.py`` used to wire ad hoc) and installs a
   ``jax.monitoring`` listener for the cache's hit/miss events.  A
   registry build AOT-compiles the program (``jit(...).lower(*avals)
   .compile()``); with the disk tier enabled that compile deserializes
   a previously-persisted executable instead of running XLA, so a
   server process restart against the same config performs ZERO XLA
   compiles on its hot path — ``xla_compiles`` stays 0 and the restart
   drill in ``scripts/ci_serve.sh`` pins it.  A corrupt or stale disk
   entry is survivable twice over: JAX itself warns and recompiles on a
   deserialization error, and the registry additionally retries a
   failed build once with the cache bypassed.

3. **AOT compile + direct executable dispatch.**  Built entries store
   the ``jax.stages.Compiled`` executable and ``Program.__call__``
   invokes it directly — measured ~15x less per-dispatch host overhead
   than re-entering the ``jax.jit`` wrapper on this CPU backend, and it
   guarantees the executable used is exactly the one the registry
   compiled/warmed (the jit wrapper's own dispatch cache is a separate,
   unwarmed cache).  Programs whose call-site avals are not statically
   known (the trainer step) register through ``track_jit`` instead:
   same key space and counters, compile measured at first dispatch.

Capacity is bounded (LRU eviction of UNPINNED entries only): an engine
pins the programs it holds — via a weakref finalizer, so a dead engine
releases its pins — and eviction can therefore never drop a program a
live engine is dispatching through.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from .keys import program_key

PyTree = Any

# -- disk tier (persistent XLA executable cache) ---------------------------

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "gym_tpu", "xla_cache")

#: global persistent-cache event counters, fed by jax.monitoring. The
#: events are process-global (jax has one compilation cache), so the
#: listener and counters are module-level; registries read deltas under
#: the compile lock for exact attribution.
_DISK_EVENTS = {"hits": 0, "misses": 0}
_EVENTS_LOCK = threading.Lock()
_LISTENER_INSTALLED = False

#: serializes actual builds (lower+compile) across the process so a
#: build's persistent-cache hit/miss event delta is attributable to THAT
#: build — and because concurrent XLA compiles on a 2-core host contend
#: anyway. Single-flight already dedupes same-key builds; this only
#: orders different-key ones.
_COMPILE_LOCK = threading.Lock()


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    import jax.monitoring

    def _on_event(event: str, **kw) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            with _EVENTS_LOCK:
                _DISK_EVENTS["hits"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            with _EVENTS_LOCK:
                _DISK_EVENTS["misses"] += 1

    jax.monitoring.register_event_listener(_on_event)
    _LISTENER_INSTALLED = True


def _disk_events() -> Tuple[int, int]:
    with _EVENTS_LOCK:
        return _DISK_EVENTS["hits"], _DISK_EVENTS["misses"]


def disk_event_counters() -> Dict[str, int]:
    """Process-global persistent-cache hit/miss event counts (every XLA
    compile in the process, registry-owned or not). 0/0 until
    ``enable_disk_tier`` has installed the listener."""
    h, m = _disk_events()
    return {"xla_cache_hits": h, "xla_cache_misses": m}


def enable_disk_tier(cache_dir: Optional[str] = None, *,
                     min_compile_time_secs: Optional[float] = 0.0) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` and
    install the hit/miss listener the registry's compile counters read.

    Resolution order: explicit argument > ``GYM_TPU_PROGRAM_CACHE_DIR``
    > ``JAX_COMPILATION_CACHE_DIR`` > the gym-tpu default under
    ``~/.cache``.  ``min_compile_time_secs`` defaults to 0 (persist even
    sub-second compiles — the serving programs on small models compile
    fast but a cold start pays all of them at once; ``None`` leaves
    JAX's own ~1 s threshold untouched, the trainer-path default).
    Idempotent; returns the resolved directory."""
    import jax

    cache_dir = (cache_dir
                 or os.environ.get("GYM_TPU_PROGRAM_CACHE_DIR")
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or DEFAULT_CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    if min_compile_time_secs is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    # jax 0.4.x initializes the persistent cache AT MOST ONCE per
    # process, at the first compile. A server restores its checkpoint
    # (which compiles) before this function runs, so without a reset the
    # dir-less initialization is latched and the tier is silently dead —
    # the ci_serve restart drill caught exactly that. reset_cache()
    # clears the latch; the next compile re-initializes against
    # ``cache_dir``.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception as e:  # noqa: BLE001 — experimental API; degrade
        # loudly rather than crash server startup
        warnings.warn(f"program registry: could not reset jax's "
                      f"compilation-cache latch ({type(e).__name__}: "
                      f"{e}); the disk tier may be inert if anything "
                      f"compiled before enable_disk_tier()")
    _install_listener()
    return cache_dir


# -- program definitions ---------------------------------------------------


@dataclasses.dataclass
class ProgramDef:
    """One registrable device program: enough to (a) compute its
    canonical key without building anything and (b) build + AOT-compile
    it on demand.  ``args`` are pytrees of ``jax.ShapeDtypeStruct``
    templates — the exact avals every call site dispatches with (the
    registry stores the AOT executable, so call-site avals MUST match).
    ``builder()`` returns the jitted callable, donation already
    attached."""

    name: str
    family: str
    config: Dict[str, Any]
    args: Tuple[Any, ...]
    donate_args: Tuple[int, ...]
    builder: Callable[[], Callable]
    #: False skips the AOT ``lower().compile()`` and stores the raw
    #: builder result (programs that must trace lazily, e.g. under a
    #: mesh context the registry doesn't own)
    aot: bool = True

    def key(self) -> Tuple[str, str]:
        return program_key(self.name, self.config, self.args,
                           self.donate_args)


class Program:
    """Callable handle to a registry entry.  ``ensure()`` builds (or
    joins the single-flight build of) the executable; ``__call__``
    ensures then dispatches.  After the first ensure the executable is
    cached on the handle — the hot path never re-enters the registry."""

    __slots__ = ("_registry", "_key_hash", "_fn", "name")

    def __init__(self, registry: "ProgramRegistry", key_hash: str,
                 name: str):
        self._registry = registry
        self._key_hash = key_hash
        self._fn: Optional[Callable] = None
        self.name = name

    @property
    def key_hash(self) -> str:
        return self._key_hash

    @property
    def built(self) -> bool:
        return (self._fn is not None
                or self._registry._is_built(self._key_hash))

    def ensure(self) -> Callable:
        if self._fn is None:
            self._fn, _ = self._registry._ensure_built(self._key_hash)
        return self._fn

    def ensure_reporting(self) -> bool:
        """Ensure built; True iff THIS call ran the build.  The exact
        per-key compile observable — diffing a global counter around
        ``ensure()`` misattributes concurrent builds (warmup thread,
        sibling replicas) to this call site."""
        if self._fn is not None:
            return False
        self._fn, built_now = self._registry._ensure_built(self._key_hash)
        return built_now

    def __call__(self, *args):
        fn = self._fn
        if fn is None:
            fn = self.ensure()
        tid = threading.get_ident()
        _INFLIGHT[tid] = f"{self.name} [{self._key_hash[:12]}]"
        try:
            return fn(*args)
        finally:
            _INFLIGHT.pop(tid, None)


@dataclasses.dataclass
class _Entry:
    pdef: Optional[ProgramDef]
    name: str
    family: str
    fn: Optional[Callable] = None
    pins: int = 0
    build_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock)


# -- the registry ----------------------------------------------------------


class ProgramRegistry:
    """Keyed, bounded, single-flight store of compiled device programs.

    Thread-safe.  ``acquire`` registers a key (and returns a handle)
    without compiling; the build happens at ``ensure``/first call, or
    eagerly (``eager=True`` — what the warmup thread uses).  Counters:

    - ``hits``   — acquires/ensures answered by an already-built entry
    - ``builds`` — in-memory misses that ran a builder (the analogue of
      the retired ``lru_cache`` miss probes; ``compile_counter()``)
    - ``xla_compiles`` — builds whose compile actually ran XLA (with
      the disk tier warm this stays 0 across a process restart)
    - ``disk_hits`` — builds served by deserializing a persisted
      executable
    - ``compile_seconds`` — wall time inside builds (trace + compile
      or deserialize)
    """

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.RLock()
        self._store: "OrderedDict[str, _Entry]" = OrderedDict()
        self._hits = 0
        self._builds = 0
        self._xla_compiles = 0
        self._disk_hits = 0
        self._compile_seconds = 0.0
        self._evictions = 0

    # -- introspection ----------------------------------------------------

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hits": self._hits,
                "builds": self._builds,
                "xla_compiles": self._xla_compiles,
                "disk_hits": self._disk_hits,
                "compile_seconds": round(self._compile_seconds, 4),
                "evictions": self._evictions,
                "programs": len(self._store),
            }

    def keys(self) -> Dict[str, str]:
        """``{key_hash: program name}`` for every registered program."""
        with self._lock:
            return {k: e.name for k, e in self._store.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    # -- registration / acquisition ---------------------------------------

    def register(self, pdef: ProgramDef) -> str:
        """Record ``pdef``'s key without building; returns the key hash.
        The audit gate uses this to reconcile the auditor's key set with
        the registry's without compiling anything."""
        _canon, key_hash = pdef.key()
        with self._lock:
            self._register_locked(key_hash, pdef)
        return key_hash

    def _register_locked(self, key_hash: str, pdef: ProgramDef) -> None:
        ent = self._store.get(key_hash)
        if ent is None:
            self._store[key_hash] = _Entry(pdef=pdef, name=pdef.name,
                                           family=pdef.family)
            self._evict_over_capacity_locked(protect=key_hash)
        elif ent.pdef is None:
            ent.pdef = pdef

    def acquire(self, pdef: ProgramDef, *, eager: bool = False,
                pin_owner: Optional[object] = None) -> Program:
        """Handle for ``pdef``'s program.  ``eager=True`` builds before
        returning (single-flight).  ``pin_owner`` pins the entry against
        capacity eviction for the owner's lifetime (released by a
        weakref finalizer when the owner is collected).  Registration,
        pin and eviction happen atomically, so a pinned acquire into a
        fully-pinned store runs the store over capacity instead of
        evicting the program it is about to hand out."""
        _canon, key_hash = pdef.key()
        with self._lock:
            self._register_locked(key_hash, pdef)
            self._store.move_to_end(key_hash)
            if pin_owner is not None:
                self._pin_locked(key_hash, pin_owner)
            self._evict_over_capacity_locked(protect=key_hash)
        h = Program(self, key_hash, pdef.name)
        if eager:
            h.ensure()
        return h

    def pin(self, key_hash: str, owner: Optional[object] = None) -> None:
        with self._lock:
            self._pin_locked(key_hash, owner)

    def unpin(self, key_hash: str) -> None:
        with self._lock:
            ent = self._store.get(key_hash)
            if ent is not None and ent.pins > 0:
                ent.pins -= 1

    def _pin_locked(self, key_hash: str, owner: Optional[object]) -> None:
        ent = self._store[key_hash]
        ent.pins += 1
        if owner is not None:
            import weakref
            weakref.finalize(owner, self.unpin, key_hash)

    # -- build path --------------------------------------------------------

    def _is_built(self, key_hash: str) -> bool:
        with self._lock:
            ent = self._store.get(key_hash)
            return ent is not None and ent.fn is not None

    def _ensure_built(self, key_hash: str) -> Tuple[Callable, bool]:
        """Returns ``(callable, built_now)`` — ``built_now`` is True
        only for the one caller whose invocation actually ran the
        build (joiners and hits get False)."""
        with self._lock:
            ent = self._store.get(key_hash)
            if ent is None:
                raise KeyError(
                    f"program {key_hash} was evicted before it was "
                    f"built — re-acquire it from its ProgramDef")
            if ent.fn is not None:
                self._hits += 1
                self._store.move_to_end(key_hash)
                return ent.fn, False
            if ent.pdef is None:
                raise KeyError(
                    f"program {key_hash} ({ent.name}) was registered "
                    f"key-only — acquire it with a full ProgramDef")
            build_lock, pdef = ent.build_lock, ent.pdef
        with build_lock:                       # single flight
            with self._lock:
                if ent.fn is not None:
                    self._hits += 1
                    return ent.fn, False
            fn, compiled, disk_hit, dt = self._build(pdef)
            with self._lock:
                ent.fn = fn
                self._builds += 1
                self._xla_compiles += int(compiled)
                self._disk_hits += int(disk_hit)
                self._compile_seconds += dt
            return fn, True

    def _build(self, pdef: ProgramDef
               ) -> Tuple[Callable, bool, bool, float]:
        """Build + (optionally) AOT-compile one program under the global
        compile lock.  Returns ``(callable, ran_xla, disk_hit,
        seconds)``.  A failed AOT compile with the disk tier enabled is
        retried once with the persistent cache bypassed — a corrupt or
        stale disk entry must degrade to a fresh compile with a warning,
        never a crash."""
        with _COMPILE_LOCK:
            h0, m0 = _disk_events()
            t0 = time.perf_counter()
            fn = pdef.builder()
            if pdef.aot and hasattr(fn, "lower"):
                try:
                    fn = fn.lower(*pdef.args).compile()
                except Exception as e:  # noqa: BLE001 — see docstring
                    if not _LISTENER_INSTALLED:
                        raise
                    warnings.warn(
                        f"program registry: AOT compile of {pdef.name} "
                        f"failed ({type(e).__name__}: {e}); retrying "
                        f"with the persistent compile cache bypassed")
                    import jax
                    jax.config.update("jax_enable_compilation_cache",
                                      False)
                    try:
                        fn = pdef.builder().lower(*pdef.args).compile()
                    finally:
                        jax.config.update("jax_enable_compilation_cache",
                                          True)
            dt = time.perf_counter() - t0
            h1, m1 = _disk_events()
        if h1 == h0 and m1 == m0:
            # no persistent cache consulted (disk tier off, or aot=False
            # deferring the compile to first dispatch): count the build
            # as a compile — without a disk tier every build is one
            return fn, True, False, dt
        disk_hit = h1 > h0 and m1 == m0
        return fn, not disk_hit, disk_hit, dt

    # -- tracked (non-owned) programs --------------------------------------

    def track_jit(self, name: str, config: Dict[str, Any],
                  donate_args: Tuple[int, ...], fn: Callable,
                  family: str = "") -> Callable:
        """Register a jitted callable the registry cannot AOT-compile
        (the trainer step: its avals exist only at the first dispatch
        and it must trace under the runtime's mesh context).  The
        wrapper computes the canonical key from the FIRST call's live
        avals — so the key matches what the auditor computes from
        templates — and attributes that call's compile to the registry
        counters (build + xla-compile-or-disk-hit + seconds)."""
        state: Dict[str, Any] = {"first": True}
        tracker_lock = threading.Lock()

        def wrapped(*args):
            if not state["first"]:
                tid = threading.get_ident()
                _INFLIGHT[tid] = name
                try:
                    return fn(*args)
                finally:
                    _INFLIGHT.pop(tid, None)
            with tracker_lock:
                if not state["first"]:
                    tid = threading.get_ident()
                    _INFLIGHT[tid] = name
                    try:
                        return fn(*args)
                    finally:
                        _INFLIGHT.pop(tid, None)
                # key from aval TEMPLATES, not the live arrays: the
                # registry holds the ProgramDef for its lifetime, and
                # storing the first call's arguments would pin a full
                # copy of the training state (GBs at real sizes) in the
                # process-global registry forever. program_key reads
                # only shape/dtype, so templates key identically.
                import jax
                import numpy as _np
                args_tpl = tuple(
                    jax.tree.map(
                        lambda l: jax.ShapeDtypeStruct(
                            tuple(getattr(l, "shape", ())),
                            _np.dtype(getattr(l, "dtype", _np.float32))),
                        a) for a in args)
                pdef = ProgramDef(
                    name=name, family=family or name.split("[")[0],
                    config=config, args=args_tpl,
                    donate_args=donate_args,
                    builder=lambda: fn, aot=False)
                key_hash = self.register(pdef)
                with _COMPILE_LOCK:
                    h0, m0 = _disk_events()
                    t0 = time.perf_counter()
                    tid = threading.get_ident()
                    _INFLIGHT[tid] = name
                    try:
                        out = fn(*args)
                    finally:
                        _INFLIGHT.pop(tid, None)
                    dt = time.perf_counter() - t0
                    h1, m1 = _disk_events()
                with self._lock:
                    ent = self._store.get(key_hash)
                    if ent is not None:
                        ent.fn = fn
                    self._builds += 1
                    disk_hit = h1 > h0 and m1 == m0
                    self._disk_hits += int(disk_hit)
                    self._xla_compiles += int(not disk_hit)
                    self._compile_seconds += dt
                state["first"] = False
                return out

        wrapped.lower = getattr(fn, "lower", None)  # HLO-inspection tests
        return wrapped

    # -- eviction ----------------------------------------------------------

    def _evict_over_capacity_locked(self,
                                    protect: Optional[str] = None) -> None:
        """LRU-evict UNPINNED entries past capacity.  Pinned (in-use)
        programs, the key being registered right now (``protect``) and
        entries whose build is IN FLIGHT (build_lock held — evicting
        one would detach the building thread's _Entry and hand a second
        acquirer a fresh entry, duplicating the compile and crashing
        joiners with KeyError) are never evicted — if everything is
        held the store runs over capacity rather than dropping a live
        program."""
        while len(self._store) > self.capacity:
            victim = None
            for k, e in self._store.items():          # oldest first
                if (e.pins == 0 and k != protect
                        and not e.build_lock.locked()):
                    victim = k
                    break
            if victim is None:
                return
            del self._store[victim]
            self._evictions += 1


# -- in-flight dispatch tracking -------------------------------------------

#: thread ident -> program name for every registry-dispatched program
#: currently executing. Single dict ops (GIL-atomic) on the hot path —
#: no lock. Read by the watchdog's stack dump so a wedged dispatch
#: names the SPECIFIC compiled program, not just "inside jax".
_INFLIGHT: Dict[int, str] = {}


def inflight_programs() -> Dict[int, str]:
    """Snapshot of registry programs currently executing, keyed by
    thread ident. Empty when nothing is dispatching."""
    return dict(_INFLIGHT)


# -- module-level default registry ----------------------------------------

_DEFAULT = ProgramRegistry()


def default_registry() -> ProgramRegistry:
    """The process-wide registry every engine/trainer/server shares —
    program reuse across replicas, rebuilds and hot-swaps depends on
    them all resolving the same store."""
    return _DEFAULT


def compile_counter() -> int:
    """Monotonic count of in-memory program BUILDS in the default
    registry — the shared instrumentation probe replacing the old
    per-builder ``lru_cache.cache_info().misses`` sums.  A delta of 0
    across an operation means it was served entirely by already-built
    programs (the zero-recompile seams: supervisor failover, fleet
    hot-swap, trainer→server handoff)."""
    return _DEFAULT.counters()["builds"]


def xla_compile_counter() -> int:
    """Monotonic count of builds that actually ran XLA (disk-tier hits
    excluded) — the restart drill's ``programs_compiled`` observable."""
    return _DEFAULT.counters()["xla_compiles"]
