"""Canonical device-program keys — ONE key function for the whole repo.

A compiled XLA program is identified by ``(name × static config × input
shapes/dtypes × donation mask)``.  ``program_key`` renders that
descriptor as deterministic JSON plus its sha256[:16] hash — the key the
unified device-program registry (``programs.registry``) stores
executables under and the jaxpr auditor (``analysis/jaxpr_audit.py``)
reports.  Both import THIS function, so the audit's key set and the
registry's key set can only drift if a program's actual signature
drifts — which is exactly the recompile the guard exists to catch.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

PyTree = Any


def _leaf_avals(tree: PyTree) -> List[Tuple[Tuple[int, ...], str]]:
    out = []
    for leaf in jax.tree.leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(np.dtype(getattr(leaf, "dtype", np.float32)))
        out.append((shape, dtype))
    return out


def _jsonable_config(config: Dict[str, Any]) -> Dict[str, str]:
    return {str(k): repr(v) for k, v in sorted(config.items())}


def program_key(name: str, config: Dict[str, Any], args: Sequence[Any],
                donate_args: Sequence[int],
                out_avals: Optional[Sequence[Tuple]] = None
                ) -> Tuple[str, str]:
    """Canonical program key: ``(name × config × input shapes/dtypes ×
    donation mask)`` as a deterministic JSON string plus its sha256[:16]
    hash — the device-program-registry key. Two dispatches whose keys
    hash equal may share a compiled executable; two programs with the
    same ``name``/``config`` but different keys are a recompile."""
    desc = {
        "name": name,
        "config": _jsonable_config(config),
        "in_avals": [_leaf_avals(a) for a in args],
        "donated": sorted(int(i) for i in donate_args),
    }
    if out_avals is not None:
        desc["out_avals"] = list(out_avals)
    canon = json.dumps(desc, sort_keys=True, separators=(",", ":"))
    return canon, hashlib.sha256(canon.encode()).hexdigest()[:16]
