"""Collective redistribution programs for elastic membership changes.

Resharding a checkpointed ZeRO layout from K nodes onto K' is a handful
of small device programs over the flat parameter/moment vectors (the
arXiv 2112.01075 shape: redistribution as ONE compiled program, not a
host gather/scatter round-trip).  They are defined here as
``ProgramDef``s so ``gym_tpu.elastic`` acquires them through the shared
program registry — built once per (K→K', shapes) signature under a
canonical key, warm on every later resume at the same membership, and
enumerable by the jaxpr audit (``analysis/jaxpr_audit.py``) like every
other shipped program.

All defs use ``donate_args=()``: a reshard's input ([K, s]) and output
([K', s']) avals differ whenever the membership actually changes, so
donation could never alias, and an empty donation mask is trivially
clean under the audit's donation checks.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import ProgramDef


def elastic_shard_size(n: int, k: int) -> int:
    """ceil(n / k) — must match ``strategy.sharding.shard_size`` (which
    takes a pytree; this one takes the already-flattened length)."""
    return -(-n // k)


def reshard_flat_def(n: int, k_from: int, k_to: int,
                     dtype: Any = jnp.float32) -> ProgramDef:
    """[k_from, ceil(n/k_from)] flat shards → [k_to, ceil(n/k_to)]:
    drop the old pad tail, re-pad with zeros for the new shard size.
    One def covers every flat vector of the same (n, K→K') signature —
    params, Adam mu and nu all reuse the same executable."""
    s_from = elastic_shard_size(n, k_from)
    s_to = elastic_shard_size(n, k_to)
    dt = jnp.dtype(dtype)

    def _build():
        def fn(shards):
            flat = shards.reshape(-1)[:n]
            return jnp.pad(flat, (0, k_to * s_to - n)).reshape(k_to, s_to)
        return jax.jit(fn)

    return ProgramDef(
        name=f"elastic.reshard_flat[{k_from}->{k_to}]",
        family="elastic.reshard",
        config={"n": n, "k_to": k_to},
        args=(jax.ShapeDtypeStruct((k_from, s_from), dt),),
        donate_args=(),
        builder=_build,
    )


def replicate_rows_def(shape: Tuple[int, ...], k_from: int, k_to: int,
                       dtype: Any = jnp.float32) -> ProgramDef:
    """[k_from, *shape] node-replicated state → [k_to, *shape]: row 0
    repeated onto the new membership (rows are equal by construction —
    the caller, ``gym_tpu.elastic``, verifies that before dispatch)."""
    dt = jnp.dtype(dtype)

    def _build():
        def fn(x):
            return jnp.repeat(x[:1], k_to, axis=0)
        return jax.jit(fn)

    return ProgramDef(
        name=f"elastic.replicate_rows[{k_from}->{k_to}]",
        family="elastic.reshard",
        config={"k_to": k_to},
        args=(jax.ShapeDtypeStruct((k_from,) + tuple(shape), dt),),
        donate_args=(),
        builder=_build,
    )


def unshard_params_def(leaf_specs: Sequence[Tuple[Tuple[int, ...], Any]],
                       treedef, n: int, k_from: int,
                       k_to: int) -> ProgramDef:
    """ZeRO-2 param shards [k_from, ceil(n/k_from)] (f32) → the live
    stacked parameter tree ([k_to, *leaf_shape] per leaf, leaf dtypes
    restored).  ``leaf_specs`` is ``[(per_node_shape, dtype), ...]`` in
    tree-leaf order — the SAME order ``ravel_pytree`` flattens, which is
    how the shards were packed, so offsets line up exactly."""
    s_from = elastic_shard_size(n, k_from)
    specs = [(tuple(shape), jnp.dtype(dt)) for shape, dt in leaf_specs]
    sig = ";".join(f"{shape}:{dt}" for shape, dt in specs)

    def _build():
        def fn(shards):
            flat = shards.reshape(-1)[:n]
            out, off = [], 0
            for shape, dt in specs:
                sz = int(math.prod(shape)) if shape else 1
                leaf = flat[off:off + sz].reshape((1,) + shape).astype(dt)
                out.append(jnp.repeat(leaf, k_to, axis=0))
                off += sz
            return jax.tree.unflatten(treedef, out)
        return jax.jit(fn)

    return ProgramDef(
        name=f"elastic.unshard_params[{k_from}->{k_to}]",
        family="elastic.reshard",
        config={"n": n, "k_to": k_to, "tree": sig},
        args=(jax.ShapeDtypeStruct((k_from, s_from), jnp.float32),),
        donate_args=(),
        builder=_build,
    )


def elastic_program_defs() -> List[ProgramDef]:
    """The audit-facing elastic program set: fixed small signatures
    covering the reshard families (uneven K' in both directions, a grow
    and a shrink of the replicate path, and a ZeRO-2 param unshard).
    ``analysis.jaxpr_audit`` turns these into ProgramSpecs and the
    registry reconciliation registers exactly this set."""
    tiny_tree = {"b": np.zeros((5,), np.float32),
                 "w": np.zeros((3, 2), np.float32)}
    leaves, treedef = jax.tree.flatten(
        jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                     tiny_tree))
    specs = [(l.shape, l.dtype) for l in leaves]
    n = sum(int(math.prod(l.shape)) for l in leaves)  # 11: uneven for all K
    return [
        reshard_flat_def(n, 4, 3),
        reshard_flat_def(n, 3, 4),
        reshard_flat_def(n, 2, 3),
        replicate_rows_def((), 4, 3, jnp.int32),
        replicate_rows_def((5,), 3, 4),
        unshard_params_def(specs, treedef, n, 4, 3),
    ]
