"""The serving engine's device programs, as registry ``ProgramDef``s.

This is the single source of truth for every program the inference
engine dispatches — the bucketed prefill, the admit scatter, the fused
``decode_chunk`` scan, and the paged-KV family (prefix-aware paged
prefill, copy-on-write page copy, paged decode, fused draft+verify
speculative decode).  ``serve/engine.py`` acquires them through the
registry (replacing its six retired module-global ``lru_cache`` stores)
and ``analysis/jaxpr_audit.py`` enumerates them through the same
functions — so the auditor's key set and the registry's key set are the
same set by construction, and a program signature drifting between the
two is impossible rather than merely tested.

Each ``ProgramDef`` carries the EXACT argument avals its engine call
site dispatches with: the registry AOT-compiles against these templates
and stores the ``Compiled`` executable, so a mismatch fails loudly at
the first dispatch instead of silently recompiling.

The builder bodies are documented where the semantics live:
``serve/engine.py``'s module docstring (the program-set design) and the
per-builder docstrings below.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.nanogpt import GPT, GPTConfig, sample_logits
from .registry import ProgramDef

# -- aval templates --------------------------------------------------------


def _scalar(dt):
    return jax.ShapeDtypeStruct((), dt)


def _vec(n, dt):
    return jax.ShapeDtypeStruct((n,), dt)


_KEY_T = jax.ShapeDtypeStruct((2,), np.uint32)


def _qtag(cfg_tuple: tuple) -> str:
    """Name suffix for quantized-serving configs (ISSUE 11): the f32
    default keeps its historical names (grep-stable), while a quantized
    program's NAME carries its dtypes — the auditor's recompile guard
    treats same-name-different-key as a collision, so two dtype variants
    of one program must not share a name."""
    cfg = GPTConfig(*cfg_tuple)
    parts = []
    if cfg.weights_dtype != "f32":
        parts.append(f"w={cfg.weights_dtype}"
                     + ("+emb" if cfg.quant_embed else ""))
    if cfg.kv_dtype != "f32":
        parts.append(f"kv={cfg.kv_dtype}")
    return ("," + ",".join(parts)) if parts else ""


@functools.lru_cache(maxsize=64)
def _templates(cfg_tuple: tuple, batch: int, paged: bool):
    """``(params_tpl, cache_tpl)`` aval pytrees for a ``batch``-row
    engine cache under this config — host-side ``eval_shape`` only,
    nothing compiles.  Bounded lru: entries are tiny aval trees, keyed
    by full config, and 64 far exceeds the distinct (config × batch)
    pairs any process serves."""
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)
    dummy = jnp.zeros((batch, 1), jnp.int32)
    if paged:
        mb = cfg.block_size // cfg.page_size
        shapes = jax.eval_shape(
            lambda: model.init(
                {"params": jax.random.PRNGKey(0)}, dummy, train=False,
                block_table=jnp.zeros((batch, mb), jnp.int32),
                cache_pos=jnp.zeros((batch,), jnp.int32)))
    else:
        shapes = jax.eval_shape(
            lambda: model.init({"params": jax.random.PRNGKey(0)}, dummy,
                               train=False))
    return shapes["params"], shapes["cache"]


# -- builders (the jitted closures the registry compiles) ------------------


def build_prefill(cfg_tuple: tuple, bucket: int):
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)

    @jax.jit
    def prefill(params, tokens, true_len, key, temp, top_k, top_p):
        """tokens [1, bucket] right-padded; returns the sampled first
        token [1] and the filled single-row cache. The first token is
        sampled INSIDE the program (key schedule index 0) at the true
        last prompt position, so no per-``true_len`` slicing program
        exists outside this bucket's compile."""
        logits, varsc = model.apply({"params": params}, tokens,
                                    train=False, mutable=["cache"])
        last = jax.lax.dynamic_index_in_dim(logits, true_len - 1, axis=1,
                                            keepdims=False)   # [1, V]
        tok = sample_logits(last, jax.random.fold_in(key, 0),
                            temp, top_k, top_p)
        return tok, varsc["cache"]

    return prefill


def build_slot_admit(cfg_tuple: tuple, num_slots: int):
    # the engine cache is DONATED: it is multi-MB (num_slots ×
    # block_size × n_embd × 2 × n_layer) and threaded linearly through
    # the step loop — without donation every dispatch memcpys the whole
    # thing, which on CPU dominates the step
    @functools.partial(jax.jit, donate_argnums=(0,))
    def admit(cache, row_cache, slot, true_len):
        """Scatter a freshly prefilled single-row cache into slot ``slot``
        and rewind that slot's integer cursors to ``true_len`` (the
        prefill ran over the PADDED bucket, so its own cursor reads the
        bucket length; pad K/V beyond ``true_len`` stays in the row but is
        causally masked until each position is overwritten by decode)."""
        def leaf(c, n):
            if c.dtype == jnp.int32:     # per-row cursor ('i'/'pos') leaves
                return c.at[slot].set(true_len)
            return c.at[slot].set(n[0])

        return jax.tree.map(leaf, cache, row_cache)

    return admit


def build_slot_decode(cfg_tuple: tuple, num_slots: int, chunk: int):
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, tok, active, base_keys, gen_idx,
               remaining, eos, temp, top_k, top_p):
        """``chunk`` decode steps for the whole slot batch in ONE
        dispatch (a ``lax.scan``, amortizing per-dispatch overhead the
        way ``generate_fast``'s whole-request scan does). Each scanned
        step feeds every slot its current token and samples its next
        with its own key/params. Slot lifecycle bookkeeping runs ON
        DEVICE so no host round trip is needed mid-chunk: a slot that
        hits EOS or exhausts ``remaining`` flips inactive and freezes —
        its token and integer cursors stop advancing (no cache-overflow
        creep, no garbage emission; its masked compute is the price of
        the fixed shape until the next admit).

        Returns ``(toks [chunk, S], emitted [chunk, S], last_logits
        [S, V], final_tok, final_active, cache)`` — ``emitted`` marks
        which scanned steps each slot was active for; the host replays
        it to route tokens to requests."""
        def body(carry, _):
            cache, tok, act, gidx, rem, _lg = carry
            logits, varsc = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, mutable=["cache"])
            lg = logits[:, 0]                               # [S, V]
            keys = jax.vmap(jax.random.fold_in)(base_keys, gidx)
            nxt = jax.vmap(sample_logits)(lg, keys, temp, top_k, top_p)
            nxt = jnp.where(act, nxt, tok).astype(jnp.int32)
            new_cache = jax.tree.map(
                lambda n, o: jnp.where(act, n, o)
                if n.dtype == jnp.int32 else n,
                varsc["cache"], cache)
            emitted = act
            gidx = jnp.where(act, gidx + 1, gidx)
            rem = jnp.where(act, rem - 1, rem)
            done = act & ((rem <= 0) | ((eos >= 0) & (nxt == eos)))
            # last step's logits ride in the CARRY (teacher-forcing /
            # debug observable) — stacking [chunk, S, V] would move the
            # whole vocab per scanned step at GPT-2 vocab sizes
            return ((new_cache, nxt, act & ~done, gidx, rem, lg),
                    (nxt, emitted))

        lg0 = jnp.zeros((num_slots, cfg.vocab_size), jnp.float32)
        (cache, tok, active, gen_idx, remaining, lg), (toks, emitted) = \
            jax.lax.scan(body,
                         (cache, tok, active, gen_idx, remaining, lg0),
                         None, length=chunk)
        return toks, emitted, lg, tok, active, cache

    return decode


def build_paged_prefill(cfg_tuple: tuple, bucket: int):
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def prefill(params, cache, bt_row, start, tokens, true_suffix, key,
                temp, top_k, top_p):
        """Prefix-aware paged prefill: process only the SUFFIX tokens the
        prefix cache could not supply. ``tokens`` [1, bucket] is the
        right-padded suffix, ``start`` [1] the first suffix position
        (= the shared-prefix length; attention gathers the resident
        prefix K/V through ``bt_row``), ``true_suffix`` its unpadded
        length. Samples the request's first token (key-schedule index 0)
        at the true last prompt position and returns it with the updated
        pool — the pool is DONATED: suffix K/V scatter in place."""
        logits, varsc = model.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            mutable=["cache"], block_table=bt_row, cache_pos=start)
        last = jax.lax.dynamic_index_in_dim(logits, true_suffix - 1,
                                            axis=1, keepdims=False)  # [1,V]
        tok = sample_logits(last, jax.random.fold_in(key, 0),
                            temp, top_k, top_p)
        return tok, varsc["cache"]

    return prefill


def build_cow(cfg_tuple: tuple):
    """Copy page ``src`` → ``dst`` across every layer's K/V pool: the
    copy-on-write primitive for a shared block that must be appended
    into (re-forwarding its tokens into the shared page instead would
    perturb every other reader by the recompute's rounding)."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def cow(cache, src, dst):
        return jax.tree.map(lambda c: c.at[dst].set(c[src]), cache)

    return cow


def build_paged_decode(cfg_tuple: tuple, num_slots: int, chunk: int):
    """Paged twin of the slot decode: same fused ``decode_chunk`` scan
    and on-device lifecycle, but K/V flow through the page pool via each
    slot's block table and the per-row cursor is explicit carry state
    (``pos``) instead of a cache variable. Inactive rows have their
    tables redirected to the NULL page so their garbage writes can never
    touch a page that was freed and reallocated to a live slot."""
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, bt, tok, active, pos, base_keys, gen_idx,
               remaining, eos, temp, top_k, top_p):
        def body(carry, _):
            cache, tok, act, pos, gidx, rem, nanc, _lg = carry
            bt_eff = jnp.where(act[:, None], bt, 0)
            logits, varsc = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, mutable=["cache"], block_table=bt_eff,
                cache_pos=pos)
            lg = logits[:, 0]                           # [S, V]
            # quarantine is latched PER ITERATION while the row is
            # active: the null-page redirect means a finished row's
            # later iterations read clean garbage, so (unlike the
            # unpaged program) the LAST step's logits cannot witness a
            # poison that struck mid-chunk
            nanc = nanc | (act & ~jnp.isfinite(lg).all(axis=-1))
            keys = jax.vmap(jax.random.fold_in)(base_keys, gidx)
            nxt = jax.vmap(sample_logits)(lg, keys, temp, top_k, top_p)
            nxt = jnp.where(act, nxt, tok).astype(jnp.int32)
            emitted = act
            pos = jnp.where(act, pos + 1, pos)
            gidx = jnp.where(act, gidx + 1, gidx)
            rem = jnp.where(act, rem - 1, rem)
            done = act & ((rem <= 0) | ((eos >= 0) & (nxt == eos)))
            return ((varsc["cache"], nxt, act & ~done, pos, gidx, rem,
                     nanc, lg), (nxt, emitted))

        lg0 = jnp.zeros((num_slots, cfg.vocab_size), jnp.float32)
        nan0 = jnp.zeros((num_slots,), bool)
        (cache, tok, active, pos, gen_idx, remaining, nan_seen, lg), \
            (toks, emitted) = jax.lax.scan(
                body, (cache, tok, active, pos, gen_idx, remaining,
                       nan0, lg0), None, length=chunk)
        return toks, emitted, lg, tok, active, pos, nan_seen, cache

    return decode


def _ngram_draft(hist, hist_len, tok, gamma: int):
    """Vectorized n-gram (prompt-lookup) drafting: for each slot, find
    the most recent earlier occurrence of the current BIGRAM
    ``(hist[len-2], tok)`` in that slot's token history and propose the
    ``gamma`` tokens that followed it. No match (or a match with no
    continuation) falls back to repeating ``tok`` — correctness never
    depends on draft quality, only throughput does: the verify step
    samples every position from the true conditional with the request's
    own key schedule, so ANY draft sequence yields the exact
    non-speculative token stream."""
    s, length = hist.shape
    idx = jnp.arange(length - 1)
    a = jnp.take_along_axis(
        hist, jnp.clip(hist_len - 2, 0, length - 1)[:, None], axis=1)[:, 0]
    m = (hist[:, :-1] == a[:, None]) & (hist[:, 1:] == tok[:, None])
    # strictly BEFORE the current bigram (which always matches itself)
    m = m & (idx[None, :] + 1 < hist_len[:, None] - 1)
    has = m.any(axis=1)
    j = jnp.max(jnp.where(m, idx[None, :], -1), axis=1)   # latest match
    dpos = j[:, None] + 2 + jnp.arange(gamma)[None, :]
    d = jnp.take_along_axis(hist, jnp.clip(dpos, 0, length - 1), axis=1)
    ok = has[:, None] & (dpos < hist_len[:, None])
    return jnp.where(ok, d, tok[:, None]).astype(jnp.int32)


def build_spec_decode(cfg_tuple: tuple, num_slots: int, chunk: int,
                      gamma: int):
    """Self-drafting speculative decoding (arXiv 2302.01318), fused into
    the ``decode_chunk`` scan: each scanned iteration drafts ``gamma``
    tokens per slot by n-gram lookup over the slot's own token history,
    scores ``[tok, d_1..d_γ]`` in ONE batched ``γ+1``-token model call,
    then runs the vectorized accept/reject entirely on device.

    EXACTNESS (stronger than the usual greedy-only guarantee): position
    ``i``'s token is sampled from the true conditional
    ``p(· | prefix, accepted_{<i})`` with the request's own key
    ``fold_in(base, gen_idx+i)`` — the draft only decides how many of
    those samples one dispatch may keep (the leading run where
    ``sampled_i == draft_i``, plus one bonus token at the first
    mismatch). The emitted stream is therefore IDENTICAL to the
    non-speculative engine for EVERY sampling configuration, not just
    greedy. Rejected drafts need no page copy: the rollback is a cursor
    rewind — their K/V sit beyond the new cursor in slot-owned blocks,
    causally masked until overwritten (exactly how padded prefill K/V
    are retired)."""
    cfg = GPTConfig(*cfg_tuple)
    model = GPT(cfg)
    g1 = int(gamma) + 1

    @functools.partial(jax.jit, donate_argnums=(1,))
    def spec(params, cache, bt, hist, tok, active, pos, base_keys,
             gen_idx, remaining, eos, temp, top_k, top_p):
        sample_row = jax.vmap(sample_logits,
                              in_axes=(0, 0, None, None, None))

        def body(carry, _):
            cache, tok, act, pos, gidx, rem, hist, nanc, _lg = carry
            hist_len = pos + 1                # prompt + emitted count
            drafts = _ngram_draft(hist, hist_len, tok, gamma)   # [S, γ]
            inp = jnp.concatenate([tok[:, None], drafts], axis=1)
            bt_eff = jnp.where(act[:, None], bt, 0)
            logits, varsc = model.apply(
                {"params": params, "cache": cache}, inp, train=False,
                mutable=["cache"], block_table=bt_eff, cache_pos=pos)
            # latched per-iteration quarantine (see the paged decode
            # program) — position 0 only: later positions may be
            # LEGALLY NaN from the per-position window-overflow poison
            # on rejected drafts, while position 0 is always in-window
            # for an active row
            nanc = nanc | (act & ~jnp.isfinite(logits[:, 0]).all(axis=-1))
            idxs = gidx[:, None] + jnp.arange(g1)[None, :]
            keys = jax.vmap(jax.vmap(jax.random.fold_in,
                                     in_axes=(None, 0)))(base_keys, idxs)
            sampled = jax.vmap(sample_row)(logits, keys, temp, top_k,
                                           top_p)              # [S, γ+1]
            match = (sampled[:, :gamma] == drafts).astype(jnp.int32)
            acc = jnp.cumprod(match, axis=1).sum(axis=1)        # [S]
            m = acc + 1                       # leading matches + bonus
            pidx = jnp.arange(g1)[None, :]
            is_eos = (eos[:, None] >= 0) & (sampled == eos[:, None])
            eos_hit = is_eos & (pidx < m[:, None])
            any_eos = eos_hit.any(axis=1)
            m = jnp.where(any_eos, jnp.argmax(eos_hit, axis=1) + 1, m)
            m = jnp.minimum(m, rem)           # max-tokens cap
            m = jnp.where(act, m, 0)
            emit = (pidx < m[:, None]) & act[:, None]           # [S, γ+1]
            new_tok = jnp.take_along_axis(
                sampled, jnp.maximum(m - 1, 0)[:, None], axis=1)[:, 0]
            new_tok = jnp.where(act, new_tok, tok).astype(jnp.int32)
            rem = rem - m
            done = act & ((rem <= 0) | any_eos)
            # history grows by the emitted tokens so the NEXT iteration's
            # draft can match against them
            rows = jnp.arange(num_slots)[:, None]
            hpos = jnp.clip(hist_len[:, None] + pidx, 0,
                            cfg.block_size - 1)
            hist = hist.at[rows, hpos].set(
                jnp.where(emit, sampled, hist[rows, hpos]))
            lg = logits[:, 0]                 # teacher-forcing observable
            return ((varsc["cache"], new_tok, act & ~done, pos + m,
                     gidx + m, rem, hist, nanc, lg), (sampled, emit))

        lg0 = jnp.zeros((num_slots, cfg.vocab_size), jnp.float32)
        nan0 = jnp.zeros((num_slots,), bool)
        (cache, tok, active, pos, gen_idx, remaining, hist, nan_seen,
         lg), (toks, emit) = jax.lax.scan(
                body, (cache, tok, active, pos, gen_idx, remaining,
                       hist, nan0, lg0), None, length=chunk)
        return toks, emit, lg, tok, active, pos, nan_seen, cache

    return spec


# -- ProgramDefs -----------------------------------------------------------


def prefill_def(cfg_tuple: tuple, bucket: int) -> ProgramDef:
    params_tpl, _ = _templates(cfg_tuple, 1, False)
    return ProgramDef(
        name=f"serve.prefill[bucket={bucket}{_qtag(cfg_tuple)}]", family="serve.prefill",
        config={"config": cfg_tuple, "bucket": bucket},
        args=(params_tpl,
              jax.ShapeDtypeStruct((1, int(bucket)), np.int32),
              _scalar(np.int32), _KEY_T, _scalar(np.float32),
              _scalar(np.int32), _scalar(np.float32)),
        donate_args=(),
        builder=lambda: build_prefill(cfg_tuple, int(bucket)))


def slot_admit_def(cfg_tuple: tuple, num_slots: int) -> ProgramDef:
    _, row_cache_tpl = _templates(cfg_tuple, 1, False)
    _, slot_cache_tpl = _templates(cfg_tuple, num_slots, False)
    return ProgramDef(
        name=f"serve.admit[slots={num_slots}{_qtag(cfg_tuple)}]", family="serve.admit",
        config={"config": cfg_tuple, "num_slots": num_slots},
        args=(slot_cache_tpl, row_cache_tpl, _scalar(np.int32),
              _scalar(np.int32)),
        donate_args=(0,),
        builder=lambda: build_slot_admit(cfg_tuple, num_slots))


def slot_decode_def(cfg_tuple: tuple, num_slots: int,
                    chunk: int) -> ProgramDef:
    params_tpl, slot_cache_tpl = _templates(cfg_tuple, num_slots, False)
    s = num_slots
    return ProgramDef(
        name=f"serve.decode[slots={s},chunk={chunk}{_qtag(cfg_tuple)}]",
        family="serve.decode",
        config={"config": cfg_tuple, "num_slots": s,
                "decode_chunk": chunk},
        args=(params_tpl, slot_cache_tpl, _vec(s, np.int32),
              _vec(s, np.bool_), jax.ShapeDtypeStruct((s, 2), np.uint32),
              _vec(s, np.int32), _vec(s, np.int32), _vec(s, np.int32),
              _vec(s, np.float32), _vec(s, np.int32),
              _vec(s, np.float32)),
        donate_args=(1,),
        builder=lambda: build_slot_decode(cfg_tuple, s, chunk))


def _paged_cfg(cfg_tuple: tuple):
    cfg = GPTConfig(*cfg_tuple)
    if not cfg.page_size or not cfg.kv_pages:
        raise ValueError(
            "paged program defs need a config with page_size/kv_pages "
            "set (the engine's dataclasses.replace'd decode config)")
    mb = cfg.block_size // cfg.page_size
    pcfg = {"config": cfg_tuple, "page_size": cfg.page_size,
            "kv_pages": cfg.kv_pages}
    return cfg, mb, pcfg


def paged_prefill_def(cfg_tuple: tuple, bucket: int) -> ProgramDef:
    _cfg, mb, pcfg = _paged_cfg(cfg_tuple)
    params_tpl, pool_tpl = _templates(cfg_tuple, 1, True)
    return ProgramDef(
        name=f"serve.paged_prefill[bucket={bucket}{_qtag(cfg_tuple)}]",
        family="serve.paged_prefill",
        config={**pcfg, "bucket": bucket},
        args=(params_tpl, pool_tpl,
              jax.ShapeDtypeStruct((1, mb), np.int32),
              jax.ShapeDtypeStruct((1,), np.int32),
              jax.ShapeDtypeStruct((1, int(bucket)), np.int32),
              _scalar(np.int32), _KEY_T, _scalar(np.float32),
              _scalar(np.int32), _scalar(np.float32)),
        donate_args=(1,),
        builder=lambda: build_paged_prefill(cfg_tuple, int(bucket)))


def cow_def(cfg_tuple: tuple) -> ProgramDef:
    cfg, _mb, pcfg = _paged_cfg(cfg_tuple)
    _, pool_tpl = _templates(cfg_tuple, 1, True)
    return ProgramDef(
        name=f"serve.cow[page={cfg.page_size}{_qtag(cfg_tuple)}]", family="serve.cow",
        config=pcfg,
        args=(pool_tpl, _scalar(np.int32), _scalar(np.int32)),
        donate_args=(0,),
        builder=lambda: build_cow(cfg_tuple))


def paged_decode_def(cfg_tuple: tuple, num_slots: int,
                     chunk: int) -> ProgramDef:
    _cfg, mb, pcfg = _paged_cfg(cfg_tuple)
    params_tpl, pool_tpl = _templates(cfg_tuple, num_slots, True)
    s = num_slots
    return ProgramDef(
        name=f"serve.paged_decode[slots={s},chunk={chunk}{_qtag(cfg_tuple)}]",
        family="serve.paged_decode",
        config={**pcfg, "num_slots": s, "decode_chunk": chunk},
        args=(params_tpl, pool_tpl,
              jax.ShapeDtypeStruct((s, mb), np.int32),
              _vec(s, np.int32), _vec(s, np.bool_), _vec(s, np.int32),
              jax.ShapeDtypeStruct((s, 2), np.uint32),
              _vec(s, np.int32), _vec(s, np.int32), _vec(s, np.int32),
              _vec(s, np.float32), _vec(s, np.int32),
              _vec(s, np.float32)),
        donate_args=(1,),
        builder=lambda: build_paged_decode(cfg_tuple, s, chunk))


def spec_decode_def(cfg_tuple: tuple, num_slots: int, chunk: int,
                    gamma: int) -> ProgramDef:
    cfg, mb, pcfg = _paged_cfg(cfg_tuple)
    params_tpl, pool_tpl = _templates(cfg_tuple, num_slots, True)
    s = num_slots
    return ProgramDef(
        name=f"serve.spec_decode[slots={s},chunk={chunk},gamma={gamma}{_qtag(cfg_tuple)}]",
        family="serve.spec_decode",
        config={**pcfg, "num_slots": s, "decode_chunk": chunk,
                "gamma": gamma},
        args=(params_tpl, pool_tpl,
              jax.ShapeDtypeStruct((s, mb), np.int32),
              jax.ShapeDtypeStruct((s, cfg.block_size), np.int32),
              _vec(s, np.int32), _vec(s, np.bool_), _vec(s, np.int32),
              jax.ShapeDtypeStruct((s, 2), np.uint32),
              _vec(s, np.int32), _vec(s, np.int32), _vec(s, np.int32),
              _vec(s, np.float32), _vec(s, np.int32),
              _vec(s, np.float32)),
        donate_args=(1,),
        builder=lambda: build_spec_decode(cfg_tuple, s, chunk, gamma))
