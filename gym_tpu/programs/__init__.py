"""gym_tpu.programs — the unified device-program registry (ROADMAP 3).

One keyed, observable owner for every compiled XLA program the repo
dispatches: trainer steps, the serving engine's prefill/admit/decode
families, the paged/speculative programs, and the fleet hot-swap's warm
handoff.  See ``registry`` for the store, ``serve_defs`` for the engine
program definitions, ``warmup`` for background AOT precompilation, and
``keys`` for the canonical program key shared with the jaxpr auditor.
"""

from .elastic_defs import (elastic_program_defs, replicate_rows_def,
                           reshard_flat_def, unshard_params_def)
from .keys import program_key
from .registry import (DEFAULT_CACHE_DIR, Program, ProgramDef,
                       ProgramRegistry, compile_counter,
                       default_registry, disk_event_counters,
                       enable_disk_tier, xla_compile_counter)
from .warmup import WarmupThread, warm_engine_programs

__all__ = [
    "program_key", "ProgramDef", "Program", "ProgramRegistry",
    "default_registry", "compile_counter", "xla_compile_counter",
    "enable_disk_tier", "disk_event_counters", "DEFAULT_CACHE_DIR",
    "WarmupThread", "warm_engine_programs",
    "elastic_program_defs", "reshard_flat_def", "replicate_rows_def",
    "unshard_params_def",
]
