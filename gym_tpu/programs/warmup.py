"""Background AOT warmup: pay every compile OFF the request path.

The serving engine's compile set is bounded — the full power-of-two
prefill-bucket family (≤ ⌈log2(block_size)⌉ + 1 programs) plus one
decode/admit (unpaged) or paged-decode/CoW/spec (paged) program — but a
cold server still pays each of those compiles on the first request that
needs it, which is exactly where p99 TTFT lives.  ``WarmupThread`` walks
the engine's complete ``ProgramDef`` family through the registry in a
low-priority daemon thread at server construction, so by the time
traffic arrives every program is already an executable (from the disk
tier, a deserialization; cold, a real compile — either way off-path).

Single-flight makes the race benign: a request that needs a program the
warmup hasn't reached yet builds it itself (or joins the in-progress
build); nothing is ever compiled twice.  Order is chosen for traffic:
decode family first (needed immediately after the first admit), then
prefill buckets smallest-first (short prompts are the common cold-start
case and small buckets compile fastest).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .registry import ProgramDef, ProgramRegistry, default_registry


class WarmupThread(threading.Thread):
    """Daemon thread precompiling ``defs`` through ``registry``.  Query
    ``stats()`` for progress (``/stats`` exports it) or ``wait()`` to
    block until done (tests, the warmed bench arm)."""

    def __init__(self, defs: List[ProgramDef],
                 registry: Optional[ProgramRegistry] = None,
                 log=None):
        super().__init__(daemon=True, name="gym-tpu-program-warmup")
        self._defs = list(defs)
        # NOT `registry or ...`: ProgramRegistry defines __len__, so an
        # EMPTY registry is falsy and would silently be swapped for the
        # process default
        self._registry = (registry if registry is not None
                          else default_registry())
        self._log = log
        # NOT named _stop: threading.Thread.join() calls self._stop()
        # as a METHOD internally (CPython _wait_for_tstate_lock), so
        # shadowing it with an Event breaks join with a TypeError
        self._stop_evt = threading.Event()
        self._done = threading.Event()
        self.warmed = 0
        self.seconds = 0.0

    def run(self) -> None:
        t0 = time.perf_counter()
        try:
            for d in self._defs:
                if self._stop_evt.is_set():
                    break
                self._registry.acquire(d, eager=True)
                self.warmed += 1
                # yield between compiles: warmup is the lowest-priority
                # work in the process — a request-path build waiting on
                # the compile lock should win the next slot
                time.sleep(0)
        except Exception as e:  # noqa: BLE001 — warmup must never kill
            if self._log is not None:  # the server it is warming
                self._log(f"gym_tpu.programs: warmup aborted after "
                          f"{self.warmed}/{len(self._defs)} programs "
                          f"({type(e).__name__}: {e})\n")
        finally:
            self.seconds = time.perf_counter() - t0
            self._done.set()
            if self._log is not None and not self._stop_evt.is_set():
                self._log(f"gym_tpu.programs: warmup — {self.warmed}/"
                          f"{len(self._defs)} programs ready in "
                          f"{self.seconds:.2f}s\n")

    def stop(self) -> None:
        self._stop_evt.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def stats(self) -> Dict[str, object]:
        return {"total": len(self._defs), "warmed": self.warmed,
                "done": self._done.is_set(),
                "seconds": round(self.seconds, 3)}


def warm_engine_programs(engine, registry: Optional[ProgramRegistry]
                         = None, *, start: bool = True,
                         log=None) -> WarmupThread:
    """Warmup thread over ``engine``'s full program family
    (``InferenceEngine.warmup_defs``) — the fleet/server construction
    hook."""
    t = WarmupThread(engine.warmup_defs(), registry=registry, log=log)
    if start:
        t.start()
    return t
