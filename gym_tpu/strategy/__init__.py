"""Synchronization strategies (reference ``exogym/strategy/__init__.py``).

Each strategy is a pure (init, step) pair over param pytrees; collectives
run over the simulated-node mesh axes. Unlike the reference,
``SimpleReduceStrategy`` is exported here too (it was missing from the
reference's re-exports — SURVEY §2.1).
"""

from .base import Strategy
from .optim import OptimSpec, ensure_optim_spec
from .simple_reduce import SimpleReduceStrategy

__all__ = [
    "Strategy",
    "OptimSpec",
    "ensure_optim_spec",
    "SimpleReduceStrategy",
]
