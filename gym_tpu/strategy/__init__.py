"""Synchronization strategies (reference ``exogym/strategy/__init__.py``).

Each strategy is a pure (init, step) pair over param pytrees; collectives
run over the simulated-node mesh axes. Unlike the reference,
``SimpleReduceStrategy`` is exported here too (it was missing from the
reference's re-exports — SURVEY §2.1).
"""

from .base import CollectiveEvent, Strategy, StrategyLifecycleError
from .communicate_optimize import (CommunicateOptimizeStrategy,
                                   CommunicationModule)
from .compress import (Codec, CompressedLink, QuantizeCodec, TopKCodec,
                       link_key, make_codec)
from .demo import (DecoupledMomentumStrategy, DeMoOuterCommunicator,
                   DeMoStrategy)
from .diloco import DiLoCoCommunicator, DiLoCoStrategy
from .dynamiq import DynamiQStrategy
from .faults import alive_mask, masked_mean, participation_round
from .fedavg import AveragingCommunicator, FedAvgStrategy
from .noloco import NoLoCoCommunicator, NoLoCoStrategy
from .optim import OptimSpec, ensure_optim_spec
from .simple_reduce import SimpleReduceStrategy
from .zero_reduce import NodeCountMismatchError, ZeroReduceStrategy
from .sparta import (IndexSelector, PartitionedIndexSelector,
                     RandomIndexSelector, ShuffledSequentialIndexSelector,
                     SparseCommunicator, SPARTAStrategy)
from .sparta_diloco import SPARTADiLoCoStrategy

__all__ = [
    "Strategy",
    "StrategyLifecycleError",
    "NodeCountMismatchError",
    "CollectiveEvent",
    "OptimSpec",
    "ensure_optim_spec",
    "SimpleReduceStrategy",
    "ZeroReduceStrategy",
    "CommunicateOptimizeStrategy",
    "CommunicationModule",
    "DiLoCoStrategy",
    "DiLoCoCommunicator",
    "FedAvgStrategy",
    "AveragingCommunicator",
    "SPARTAStrategy",
    "SparseCommunicator",
    "IndexSelector",
    "RandomIndexSelector",
    "ShuffledSequentialIndexSelector",
    "PartitionedIndexSelector",
    "SPARTADiLoCoStrategy",
    "DeMoStrategy",
    "DecoupledMomentumStrategy",
    "DeMoOuterCommunicator",
    "NoLoCoStrategy",
    "NoLoCoCommunicator",
    "DynamiQStrategy",
    "Codec",
    "CompressedLink",
    "QuantizeCodec",
    "TopKCodec",
    "link_key",
    "make_codec",
    "alive_mask",
    "masked_mean",
    "participation_round",
]
