"""Declarative optimizer factory (reference ``exogym/strategy/optim.py``).

The reference ``OptimSpec`` holds a torch optimizer class + kwargs and maps
string names adam/adamw/sgd/rmsprop/adagrad (``optim.py:19-36``). Here the
spec resolves to an ``optax.GradientTransformation``; torch-style kwarg names
(``lr``, ``betas``, ``eps``, ``weight_decay``, ``momentum``, ``nesterov``) are
accepted so reference configs port verbatim. A learning-rate *scale* schedule
(see ``schedule.py``) multiplies the base lr.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Union

import optax

# torch defaults, per torch.optim docs (Adam/AdamW lr=1e-3, betas=(.9,.999),
# eps=1e-8, AdamW weight_decay=1e-2; SGD momentum=0; RMSprop lr=1e-2,
# alpha=0.99; Adagrad lr=1e-2).
_TORCH_DEFAULTS = {
    "adam": dict(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0),
    "adamw": dict(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2),
    "sgd": dict(lr=1e-3, momentum=0.0, nesterov=False, weight_decay=0.0),
    "rmsprop": dict(lr=1e-2, alpha=0.99, eps=1e-8, momentum=0.0,
                    weight_decay=0.0),
    "adagrad": dict(lr=1e-2, eps=1e-10, weight_decay=0.0),
}

ScheduleFn = Callable[[Any], Any]  # step -> lr multiplier


@dataclasses.dataclass
class OptimSpec:
    """Named optimizer + kwargs; ``build()`` returns an optax transform.

    Mirrors reference ``OptimSpec`` (``exogym/strategy/optim.py:10-39``) but
    is validated: unknown kwargs raise instead of being silently dropped
    (the silent-kwarg bug class called out in SURVEY §5.6).
    """

    name: str = "adamw"
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __init__(self, name: str = "adamw", **kwargs: Any):
        if callable(name):  # tolerate OptimSpec(optax.adamw, ...) style
            name = getattr(name, "__name__", str(name))
        name = str(name).lower()
        if name not in _TORCH_DEFAULTS:
            available = ", ".join(sorted(_TORCH_DEFAULTS))
            raise ValueError(
                f"Unknown optimizer '{name}'. Available options: {available}"
            )
        allowed = set(_TORCH_DEFAULTS[name]) | {"betas", "b1", "b2"}
        unknown = set(kwargs) - allowed
        if unknown:
            raise ValueError(
                f"Unknown kwargs for optimizer '{name}': {sorted(unknown)}"
            )
        self.name = name
        self.kwargs = dict(kwargs)

    @property
    def lr(self) -> float:
        return float(self.kwargs.get("lr", _TORCH_DEFAULTS[self.name]["lr"]))

    def build(self, lr_scale: Optional[ScheduleFn] = None) -> optax.GradientTransformation:
        cfg = {**_TORCH_DEFAULTS[self.name], **self.kwargs}
        base_lr = float(cfg["lr"])
        if lr_scale is None:
            lr: Union[float, Callable] = base_lr
        else:
            lr = lambda step: base_lr * lr_scale(step)  # noqa: E731

        if self.name in ("adam", "adamw"):
            b1, b2 = cfg.get("betas", (0.9, 0.999))
            b1 = cfg.get("b1", b1)
            b2 = cfg.get("b2", b2)
            wd = float(cfg["weight_decay"])
            if self.name == "adam":
                # torch Adam's weight_decay is L2 folded into the gradient
                # *before* the moment updates — i.e. add_decayed_weights
                # upstream of adam, not AdamW-style decoupled decay.
                tx = optax.adam(lr, b1=b1, b2=b2, eps=cfg["eps"])
                if wd:
                    tx = optax.chain(optax.add_decayed_weights(wd), tx)
                return tx
            return optax.adamw(lr, b1=b1, b2=b2, eps=cfg["eps"],
                               weight_decay=wd)
        if self.name == "sgd":
            mom = float(cfg["momentum"]) or None
            tx = optax.sgd(lr, momentum=mom, nesterov=bool(cfg["nesterov"]))
            if cfg["weight_decay"]:
                tx = optax.chain(
                    optax.add_decayed_weights(float(cfg["weight_decay"])), tx
                )
            return tx
        if self.name == "rmsprop":
            tx = optax.rmsprop(lr, decay=float(cfg["alpha"]), eps=cfg["eps"],
                               momentum=float(cfg["momentum"]) or None)
            if cfg["weight_decay"]:
                tx = optax.chain(
                    optax.add_decayed_weights(float(cfg["weight_decay"])), tx
                )
            return tx
        if self.name == "adagrad":
            tx = optax.adagrad(lr, eps=cfg["eps"])
            if cfg["weight_decay"]:
                tx = optax.chain(
                    optax.add_decayed_weights(float(cfg["weight_decay"])), tx
                )
            return tx
        raise ValueError(f"unknown optimizer {self.name!r}")

    def config(self) -> Dict[str, Any]:
        return {"optimizer": self.name, **self.kwargs}


def ensure_optim_spec(
    optim: Union[str, OptimSpec, None],
    default: Optional[OptimSpec] = None,
    **kwargs: Any,
) -> OptimSpec:
    """Coercion helper (reference ``optim.py:42-60``)."""
    if optim is None:
        return default if default is not None else OptimSpec("adamw", **kwargs)
    if isinstance(optim, str):
        return OptimSpec(optim, **kwargs)
    if isinstance(optim, OptimSpec):
        if kwargs:
            return OptimSpec(optim.name, **{**optim.kwargs, **kwargs})
        return optim
    raise TypeError(f"Expected str, OptimSpec, or None, got {type(optim)}")
