"""DiLoCo: two-level optimization (inner per-step, outer Nesterov every H).

Reference (``exogym/strategy/diloco.py``): inner AdamW every step; every H
steps all nodes average params, rank 0 keeps a CPU ``master_model``, sets the
outer pseudo-gradient ``master − averaged``, steps an outer
SGD(lr=0.7, nesterov, momentum=0.9) (``:26-28``, ``:62-71``), then broadcasts
the result from rank 0 (``:73-74``).

TPU-native restatement (SURVEY §7 "hard parts"): there is no cheap
"only rank 0 computes" in SPMD — instead the outer optimizer state (master
params + momentum) is *replicated* and the outer step is computed identically
on every node. The input is the psum-average (bitwise deterministic on TPU),
so replicas remain bit-identical and the reference's rank-0 broadcast
disappears — saving one full model broadcast per outer round
(comm: 2(K−1)/K·|θ| per H steps vs the reference's allreduce+broadcast).

``DiLoCoCommunicator`` is the communication-module form — the missing piece
that makes the SPARTA×DiLoCo combo real (the reference imports a nonexistent
``DiLoCoCommunicator``, ``sparta_diloco.py:6``).
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree

from .base import (CollectiveEvent, PyTree, StrategyLifecycleError,
                   tree_bytes, tree_num_params)
from .communicate_optimize import (CommunicateOptimizeStrategy,
                                   CommunicationModule)
from .compress import Codec, CompressedLink
from .optim import OptimSpec, ensure_optim_spec
from .sharding import pipe_unwrap, pipe_wrap, take_shard, unshard


class DiLoCoCommunicator(CommunicationModule):
    """Outer-loop model averaging + replicated Nesterov outer step.

    ``shard_outer=True`` stores each node's 1/K slice of the (otherwise
    bit-identical, replicated) master params + outer momentum — ZeRO
    applied to the OUTER optimizer. Valid because the outer step's input
    (the psum average) is identical on every node, so slicing commutes
    with the elementwise Nesterov update. Cuts the outer state from
    2·|θ| per node to 2·|θ|/K (at GPT-2 base × 4 nodes: 4 GB → 1 GB
    total), at the cost of an extra all_gather per outer round
    (3(K−1)/K·|θ| per H steps instead of 2(K−1)/K·|θ|)."""

    def __init__(
        self,
        H: int = 100,
        outer_optim_spec: Optional[Union[str, OptimSpec]] = None,
        shard_outer: bool = False,
        participation: float = 1.0,
        fault_seed: int = 5678,
        codec: Union[str, Codec, None] = None,
        codec_seed: int = 1206,
        error_feedback: Optional[bool] = None,
        **codec_kwargs,
    ):
        if not 0.0 < participation <= 1.0:
            raise ValueError(
                f"participation must be in (0, 1], got {participation}")
        if shard_outer and participation < 1.0:
            # a truly failed node could not serve its exclusive master
            # shard for the all_gather reassembly, so the fault model is
            # physically inconsistent with a node-sharded outer state
            raise ValueError(
                "shard_outer=True cannot be combined with participation<1: "
                "dead nodes would still have to serve their master shard. "
                "Use the replicated outer state for fault simulation."
            )
        self.H = int(H)
        self.shard_outer = bool(shard_outer)
        self.participation = float(participation)
        self.fault_seed = fault_seed
        # codec ORTHOGONAL to the outer loop (ISSUE 12): the outer
        # DELTA (params − master) ships compressed through a
        # CompressedLink with a per-node error-feedback residual carried
        # in the module state. Restricted to the replicated outer state
        # with full participation: a node-sharded master would also have
        # to shard the residual reassembly, and a dead node's residual
        # would silently freeze its error feedback — neither composition
        # is honest enough to ship unverified.
        self.link = CompressedLink(codec, seed=codec_seed,
                                   error_feedback=error_feedback,
                                   **codec_kwargs)
        if self.link.compressed and self.shard_outer:
            raise ValueError(
                "codec cannot be combined with shard_outer=True: the "
                "compressed outer delta needs the replicated outer state")
        if self.link.compressed and self.participation < 1.0:
            raise ValueError(
                "codec cannot be combined with participation<1: a dead "
                "node's error-feedback residual would silently freeze")
        self.outer_optim_spec = ensure_optim_spec(
            outer_optim_spec,
            OptimSpec("sgd", lr=0.7, nesterov=True, momentum=0.9),
        )
        self.outer_tx = self.outer_optim_spec.build()

    def init(self, params: PyTree) -> PyTree:
        if not self.shard_outer:
            return {
                "master": jax.tree.map(jnp.array, params),
                "outer_opt": self.outer_tx.init(params),
                **self.link.init(tree_num_params(params)),
            }
        if self._ctx is None:
            raise StrategyLifecycleError(
                "shard_outer=True needs the mesh: pass ctx to make_init_fn "
                "(the Trainer does) or call strategy.bind_ctx(runtime.ctx)")
        # init runs inside the node program (NodeRuntime.init_state), so
        # the node index is live and each node keeps only its own slice.
        # Dtype follows the params (sharding.take_shard), so the sharded
        # Nesterov arithmetic is comparable with the replicated path for
        # any parameter dtype. Under pipeline parallelism the slice covers
        # THIS STAGE's param view — pipe-varying (sharding.pipe_wrap).
        my, _, _ = take_shard(params, self._ctx.num_nodes,
                              self._ctx.node_index())
        return pipe_wrap({"master": my, "outer_opt": self.outer_tx.init(my)},
                         self._ctx)

    def communicate(self, params, mstate, step, ctx):
        k = ctx.num_nodes
        psize = float(tree_bytes(params))
        if self.shard_outer:
            mstate = pipe_unwrap(mstate, ctx)

        def _avg_and_alive(params):
            """Round average + this node's participation flag. With
            participation < 1 (simulated failures, ``strategy/faults.py``)
            only alive nodes' params enter the outer pseudo-gradient; the
            outer master/momentum update stays replicated-identical on
            EVERY node (the alive mask is shared-PRNG), so dead nodes'
            outer state cannot drift — they just skip the param sync and
            rejoin with stale local params."""
            from .faults import masked_mean, participation_round
            _, me_alive, group = participation_round(
                self.fault_seed, step, self.participation, ctx)
            if self.participation >= 1.0:
                return ctx.pmean(params), me_alive, group
            return (masked_mean(params, me_alive.astype(jnp.float32), ctx),
                    me_alive, group)

        def outer_replicated(params, mstate):
            avg, me_alive, group = _avg_and_alive(params)
            master = mstate["master"]
            # outer pseudo-gradient: master − averaged (reference :43-45)
            pseudo = jax.tree.map(jnp.subtract, master, avg)
            updates, outer_opt = self.outer_tx.update(
                pseudo, mstate["outer_opt"], master
            )
            master = optax.apply_updates(master, updates)
            # all nodes sync to the new master (reference :47-49, :73-74 —
            # but without the broadcast: the computation is replicated);
            # a dead node misses the sync and keeps its local params
            from .faults import ring_bytes, sync_alive
            new_params = sync_alive(master, params, me_alive)
            comm = me_alive * ring_bytes(group, psize)
            return (new_params,
                    {"master": master, "outer_opt": outer_opt}, comm)

        def outer_sharded(params, mstate):
            avg, me_alive, group = _avg_and_alive(params)
            avg_my, unravel, n = take_shard(avg, k, ctx.node_index())
            pseudo = mstate["master"] - avg_my
            updates, outer_opt = self.outer_tx.update(
                pseudo, mstate["outer_opt"], mstate["master"]
            )
            master = optax.apply_updates(mstate["master"], updates)
            # every node's shard is valid regardless of aliveness (the
            # sharded outer state is slices of a replicated-identical
            # master), so the all_gather reassembly is fault-agnostic;
            # only the final param sync respects the alive mask
            assembled = unshard(ctx, master, n, unravel)
            new_params = jax.tree.map(
                lambda m, p: jnp.where(me_alive, m, p), assembled, params
            )
            comm = (me_alive * 3.0 * (group - 1)
                    / jnp.maximum(group, 1) * psize)
            return (new_params,
                    {"master": master, "outer_opt": outer_opt}, comm)

        def outer_compressed(params, mstate):
            """The codec path: each node compresses its OUTER DELTA
            (params − master) through the link — with error feedback,
            the dropped/rounded mass re-enters the next round's delta —
            and the round average is reassembled as
            ``master + mean(deltâ)``. The master is replicated and the
            pmean is a collective, so the reconstruction (and hence the
            outer Nesterov step) stays bit-identical on every node; only
            each node's rounding noise is node-specific (per-node
            ``link_key``, folded from the node index)."""
            flat_p, unravel = ravel_pytree(params)
            flat_m, _ = ravel_pytree(mstate["master"])
            delta = flat_p.astype(jnp.float32) - flat_m.astype(jnp.float32)
            key = self.link.key(step, hop=0, node=ctx.node_index())
            lstate = ({"ef_residual": mstate["ef_residual"]}
                      if self.link.error_feedback else {})
            delta_hat, lstate = self.link.send(delta, lstate, key)
            avg_flat = flat_m.astype(jnp.float32) + ctx.pmean(delta_hat)
            avg = jax.tree.map(lambda a, p: a.astype(p.dtype),
                               unravel(avg_flat), params)
            master = mstate["master"]
            pseudo = jax.tree.map(jnp.subtract, master, avg)
            updates, outer_opt = self.outer_tx.update(
                pseudo, mstate["outer_opt"], master)
            master = optax.apply_updates(master, updates)
            comm = 2.0 * (k - 1) / k * self.link.wire_bytes(delta.size)
            return (master,
                    {"master": master, "outer_opt": outer_opt, **lstate},
                    jnp.asarray(comm, jnp.float32))

        def skip(params, mstate):
            return params, mstate, jnp.zeros(())

        if self.link.compressed:
            outer = outer_compressed
        else:
            outer = outer_sharded if self.shard_outer else outer_replicated
        do = jnp.logical_and(step % self.H == 0, step > 0)
        params, mstate, comm = jax.lax.cond(do, outer, skip, params, mstate)
        if self.shard_outer:
            mstate = pipe_wrap(mstate, ctx)
        return params, mstate, comm

    def comm_events(self, step: int, params: PyTree,
                    num_nodes: int) -> List[CollectiveEvent]:
        if num_nodes <= 1 or not (step % self.H == 0 and step > 0):
            return []
        psize = float(tree_bytes(params))
        if self.link.compressed:
            # compressed round average of the outer delta: declared at
            # the codec's honest wire bytes; the emulation pmeans the
            # reconstructed dense f32 delta, bounded by emulated_bytes
            n = tree_num_params(params)
            return [CollectiveEvent(
                "all_reduce", self.link.wire_bytes(n), num_nodes,
                label="outer_delta_compressed",
                emulated_bytes=4.0 * n)]
        if self.shard_outer:
            # round average + the extra all_gather that reassembles the
            # sharded master: 3(K−1)/K·|θ| total (participation<1 is
            # rejected with shard_outer at construction)
            return [
                CollectiveEvent("all_reduce", psize, num_nodes,
                                label="outer_avg"),
                CollectiveEvent("all_gather", psize, num_nodes,
                                label="outer_master"),
            ]
        from .faults import host_participation, mean_ring_tx
        group, frac = host_participation(self.fault_seed, step, num_nodes,
                                         self.participation)
        tx = None if frac >= 1.0 else mean_ring_tx(group, frac, psize)
        return [CollectiveEvent("all_reduce", psize, group,
                                label="outer_avg", tx_bytes=tx)]

    def config(self):
        cfg = {"module": "DiLoCoCommunicator", "H": self.H,
               "outer_optimizer": self.outer_optim_spec.name,
               "outer_lr": self.outer_optim_spec.lr}
        if self.shard_outer:
            cfg["shard_outer"] = True
        if self.participation < 1.0:
            cfg["participation"] = self.participation
        if self.link.compressed:
            cfg.update(self.link.config())
        return cfg


class DiLoCoStrategy(CommunicateOptimizeStrategy):
    """Inner optimizer (default AdamW) + DiLoCo outer loop
    (reference ``diloco.py:14-89``; ``optim_spec`` names the inner optimizer
    for consistency with the reference signature)."""

    def __init__(
        self,
        optim_spec: Optional[Union[str, OptimSpec]] = None,
        outer_optim_spec: Optional[Union[str, OptimSpec]] = None,
        H: int = 100,
        max_norm: Optional[float] = None,
        lr_scheduler=None,
        lr_scheduler_kwargs=None,
        shard_outer: bool = False,
        participation: float = 1.0,
        codec: Union[str, Codec, None] = None,
        error_feedback: Optional[bool] = None,
        **codec_kwargs,
    ):
        self.H = int(H)
        super().__init__(
            communication_modules=[
                DiLoCoCommunicator(H=H, outer_optim_spec=outer_optim_spec,
                                   shard_outer=shard_outer,
                                   participation=participation,
                                   codec=codec,
                                   error_feedback=error_feedback,
                                   **codec_kwargs)
            ],
            inner_optim=ensure_optim_spec(optim_spec, OptimSpec("adamw")),
            max_norm=max_norm,
            lr_scheduler=lr_scheduler,
            lr_scheduler_kwargs=lr_scheduler_kwargs,
        )

    def config(self):
        cfg = super().config()
        cfg["H"] = self.H
        return cfg
