"""DiLoCo: two-level optimization (inner per-step, outer Nesterov every H).

Reference (``exogym/strategy/diloco.py``): inner AdamW every step; every H
steps all nodes average params, rank 0 keeps a CPU ``master_model``, sets the
outer pseudo-gradient ``master − averaged``, steps an outer
SGD(lr=0.7, nesterov, momentum=0.9) (``:26-28``, ``:62-71``), then broadcasts
the result from rank 0 (``:73-74``).

TPU-native restatement (SURVEY §7 "hard parts"): there is no cheap
"only rank 0 computes" in SPMD — instead the outer optimizer state (master
params + momentum) is *replicated* and the outer step is computed identically
on every node. The input is the psum-average (bitwise deterministic on TPU),
so replicas remain bit-identical and the reference's rank-0 broadcast
disappears — saving one full model broadcast per outer round
(comm: 2(K−1)/K·|θ| per H steps vs the reference's allreduce+broadcast).

``DiLoCoCommunicator`` is the communication-module form — the missing piece
that makes the SPARTA×DiLoCo combo real (the reference imports a nonexistent
``DiLoCoCommunicator``, ``sparta_diloco.py:6``).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import optax

from .base import PyTree, tree_bytes
from .communicate_optimize import (CommunicateOptimizeStrategy,
                                   CommunicationModule)
from .optim import OptimSpec, ensure_optim_spec


class DiLoCoCommunicator(CommunicationModule):
    """Outer-loop model averaging + replicated Nesterov outer step."""

    def __init__(
        self,
        H: int = 100,
        outer_optim_spec: Optional[Union[str, OptimSpec]] = None,
    ):
        self.H = int(H)
        self.outer_optim_spec = ensure_optim_spec(
            outer_optim_spec,
            OptimSpec("sgd", lr=0.7, nesterov=True, momentum=0.9),
        )
        self.outer_tx = self.outer_optim_spec.build()

    def init(self, params: PyTree) -> PyTree:
        return {
            "master": jax.tree.map(jnp.array, params),
            "outer_opt": self.outer_tx.init(params),
        }

    def communicate(self, params, mstate, step, ctx):
        k = ctx.num_nodes
        psize = float(tree_bytes(params))

        def outer(params, mstate):
            avg = ctx.pmean(params)
            master = mstate["master"]
            # outer pseudo-gradient: master − averaged (reference :43-45)
            pseudo = jax.tree.map(jnp.subtract, master, avg)
            updates, outer_opt = self.outer_tx.update(
                pseudo, mstate["outer_opt"], master
            )
            master = optax.apply_updates(master, updates)
            # all nodes sync to the new master (reference :47-49, :73-74 —
            # but without the broadcast: the computation is replicated)
            comm = jnp.asarray(2.0 * (k - 1) / max(k, 1) * psize)
            return master, {"master": master, "outer_opt": outer_opt}, comm

        def skip(params, mstate):
            return params, mstate, jnp.zeros(())

        do = jnp.logical_and(step % self.H == 0, step > 0)
        return jax.lax.cond(do, outer, skip, params, mstate)

    def config(self):
        return {"module": "DiLoCoCommunicator", "H": self.H,
                "outer_optimizer": self.outer_optim_spec.name,
                "outer_lr": self.outer_optim_spec.lr}


class DiLoCoStrategy(CommunicateOptimizeStrategy):
    """Inner optimizer (default AdamW) + DiLoCo outer loop
    (reference ``diloco.py:14-89``; ``optim_spec`` names the inner optimizer
    for consistency with the reference signature)."""

    def __init__(
        self,
        optim_spec: Optional[Union[str, OptimSpec]] = None,
        outer_optim_spec: Optional[Union[str, OptimSpec]] = None,
        H: int = 100,
        max_norm: Optional[float] = None,
        lr_scheduler=None,
        lr_scheduler_kwargs=None,
    ):
        self.H = int(H)
        super().__init__(
            communication_modules=[
                DiLoCoCommunicator(H=H, outer_optim_spec=outer_optim_spec)
            ],
            inner_optim=ensure_optim_spec(optim_spec, OptimSpec("adamw")),
            max_norm=max_norm,
            lr_scheduler=lr_scheduler,
            lr_scheduler_kwargs=lr_scheduler_kwargs,
        )

    def config(self):
        cfg = super().config()
        cfg["H"] = self.H
        return cfg
