"""Collective-payload codecs: quantization + top-k with error feedback.

The compressed-collective strategies (DynamiQ, arXiv:2602.08923) change
*what goes on the wire*, not the synchronization pattern: the same
reduce-scatter / all-gather hops run, but every hop's payload is
quantized (int8/int4 stochastic rounding, per-tile scale) or sparsified
(top-k with error feedback). This module is that codec layer, shared by
any strategy that wants it:

- every codec is a ``compress(x, key) -> payload`` /
  ``decompress(payload, n) -> x̂`` pair over a FLAT f32 vector, jit-clean
  (static shapes, no host callbacks), with the PRNG key supplied by the
  caller — strategies fold a *shared* key from ``(seed, step, hop)`` so
  every node draws the same stochastic-rounding noise schedule and the
  host trace can replay it;
- ``wire_bytes(n)`` is the honest accounting hook: the bytes this codec
  would put on a real wire for an ``n``-element payload, INCLUDING the
  side-channel (per-tile scales, top-k indices). ``comm_events`` declares
  these compressed bytes while the SPMD emulation moves dense f32 — the
  same realized-vs-moved split SPARTA pioneered (its masked exchange
  moves |θ| dense, prices the mask), which the static verifier
  (``analysis/trace_check.py``) accepts only when the folded metric
  matches the declaration byte-for-byte;
- top-k error feedback is the STRATEGY's job (the residual is training
  state, not codec state): ``Codec.error_feedback`` just says whether the
  strategy should carry one;
- ``CompressedLink`` (ISSUE 12) packages the codec + the error-feedback
  recursion + the ``link_key`` discipline into the one wire path every
  outer-loop strategy shares — DiLoCo outer deltas, NoLoCo gossip
  exchanges, decoupled-momentum all-reduces and DynamiQ's two hops all
  compress through it.

Pure functions over arrays — unit-tested round-trip in
``tests/test_compress.py`` (error decays under error feedback, bit-exact
decompress for lossless configs, wire accounting).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Payload = Tuple[jnp.ndarray, ...]


class Codec(abc.ABC):
    """A lossy (or lossless) codec for a flat f32 vector."""

    #: does the owning strategy need to carry an error-feedback residual?
    error_feedback: bool = False

    @abc.abstractmethod
    def compress(self, x: jnp.ndarray, key) -> Payload:
        """``x``: flat ``[n]`` f32 → payload arrays (static shapes)."""

    @abc.abstractmethod
    def decompress(self, payload: Payload, n: int) -> jnp.ndarray:
        """Payload → flat ``[n]`` f32 reconstruction."""

    @abc.abstractmethod
    def wire_bytes(self, n: int) -> float:
        """Honest wire bytes for an ``n``-element payload (data + scales
        / indices). This is what ``comm_events`` declares and what the
        ``comm_bytes`` metric accounts — NOT the dense bytes the SPMD
        emulation moves."""

    def roundtrip(self, x: jnp.ndarray, key) -> jnp.ndarray:
        """``decompress(compress(x))`` — the in-graph form strategies
        use (the payload never leaves the device in the emulation; only
        its *size* matters for accounting)."""
        return self.decompress(self.compress(x, key), int(x.size))

    @abc.abstractmethod
    def config(self) -> Dict[str, Any]:
        """Static knobs for run configs / program keys."""


@dataclasses.dataclass(frozen=True)
class QuantizeCodec(Codec):
    """int8/int4 quantization with per-tile max-abs scale.

    ``stochastic=True`` rounds with shared-PRNG uniform noise
    (``floor(q + u)``, ``u ~ U[0,1)`` — unbiased: ``E[round] = q``), so
    the codec noise averages out across nodes/steps instead of biasing
    the gradient; ``stochastic=False`` is deterministic
    round-to-nearest. Values are stored as int8 whatever ``bits`` (the
    4-bit pack is a wire-format detail); ``wire_bytes`` accounts the
    true ``bits``/element plus one f32 scale per tile.
    """

    bits: int = 8
    tile: int = 256
    stochastic: bool = True

    def __post_init__(self):
        if self.bits not in (4, 8):
            raise ValueError(f"bits must be 4 or 8, got {self.bits}")
        if self.tile < 1:
            raise ValueError(f"tile must be >= 1, got {self.tile}")

    @property
    def qmax(self) -> int:
        return (1 << (self.bits - 1)) - 1   # 127 / 7

    def _tiles(self, n: int) -> int:
        return -(-n // self.tile)

    def compress(self, x: jnp.ndarray, key) -> Payload:
        n = x.size
        t = self._tiles(n)
        xt = jnp.pad(x.astype(jnp.float32),
                     (0, t * self.tile - n)).reshape(t, self.tile)
        amax = jnp.max(jnp.abs(xt), axis=1, keepdims=True)
        scale = jnp.where(amax > 0, amax / self.qmax, 1.0)
        q = xt / scale
        if self.stochastic:
            u = jax.random.uniform(key, xt.shape)
            q = jnp.floor(q + u)
        else:
            q = jnp.round(q)
        q = jnp.clip(q, -self.qmax, self.qmax).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def decompress(self, payload: Payload, n: int) -> jnp.ndarray:
        q, scale = payload
        return (q.astype(jnp.float32) * scale).reshape(-1)[:n]

    def wire_bytes(self, n: int) -> float:
        t = self._tiles(n)
        return t * self.tile * self.bits / 8.0 + t * 4.0

    def config(self) -> Dict[str, Any]:
        return {"codec": f"int{self.bits}", "tile": self.tile,
                "stochastic": self.stochastic}


@dataclasses.dataclass(frozen=True)
class TopKCodec(Codec):
    """Top-k magnitude sparsification over the flat vector.

    Keeps the ``max(1, round(frac · n))`` largest-|x| entries as
    (int32 index, f32 value) pairs; everything else decodes to zero.
    Biased (unlike stochastic rounding), so the owning strategy MUST
    carry an error-feedback residual (``error_feedback=True``): the
    dropped mass re-enters next step's payload instead of vanishing
    (Stich et al., arXiv:1809.07599 — the standard EF-SGD recipe).
    ``frac >= 1`` keeps everything — a lossless configuration whose
    decompress is bit-exact (pinned in tests).

    Selection delegates to ``ops/topk_compress.py:topk_compress`` — the
    repo's ONE top-k kernel (the DeMo chunk compressor): on TPU it packs
    the chunk index into |value|'s low mantissa bits and selects via a
    single-array ``approx_max_k`` (recall 1.0) instead of a paired sort.
    The returned VALUES are exact (gathered from x itself, pinned by the
    parity test in tests/test_compress.py); only near-equal-|magnitude|
    tie order may differ from a paired sort, which a lossy compressor
    does not define anyway.
    """

    frac: float = 0.01
    error_feedback: bool = True

    def __post_init__(self):
        if not 0.0 < self.frac:
            raise ValueError(f"frac must be positive, got {self.frac}")

    def k_of(self, n: int) -> int:
        return max(1, min(int(round(self.frac * n)), n))

    def compress(self, x: jnp.ndarray, key) -> Payload:
        del key  # deterministic selection
        from ..ops.topk_compress import topk_compress
        k = self.k_of(x.size)
        idx, val = topk_compress(x.astype(jnp.float32)[None], k)
        return idx[0], val[0]

    def decompress(self, payload: Payload, n: int) -> jnp.ndarray:
        idx, val = payload
        return jnp.zeros((n,), jnp.float32).at[idx].set(val)

    def wire_bytes(self, n: int) -> float:
        return self.k_of(n) * 8.0   # int32 idx + f32 val

    def config(self) -> Dict[str, Any]:
        return {"codec": "topk", "frac": self.frac}


def make_codec(spec: Union[str, Codec, None], **kwargs) -> Codec:
    """``"int8"`` / ``"int4"`` / ``"topk"`` / a Codec instance → Codec.
    ``None`` defaults to int8 (the DynamiQ headline configuration)."""
    if isinstance(spec, Codec):
        return spec
    name = "int8" if spec is None else str(spec)
    if name == "int8":
        return QuantizeCodec(bits=8, **kwargs)
    if name == "int4":
        return QuantizeCodec(bits=4, **kwargs)
    if name == "topk":
        return TopKCodec(**kwargs)
    raise ValueError(
        f"unknown codec {spec!r}; expected 'int8', 'int4', 'topk' or a "
        f"Codec instance")


def hop_keys(seed: int, step, n_hops: int = 2):
    """The shared-PRNG rounding keys for one step's compressed hops:
    every node folds the SAME ``(seed, step)`` so the stochastic
    rounding schedule is node-agreed without communication (the SPARTA
    mask trick applied to codec noise). Works with a traced ``step``
    inside jit and with a concrete one on the host."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    return jax.random.split(key, n_hops)


def link_key(seed: int, step, hop: int = 0, node=None):
    """The ``CompressedLink`` key derivation: fold the base seed with the
    step, then the hop index, then (for hops where each node compresses
    its OWN payload — gossip exchanges, per-node outer deltas) the node
    index. The chain guarantees no key is ever reused between hops of one
    step or between gossip partners within a step, while staying fully
    deterministic from ``(seed, step, hop, node)`` alone — two runs of
    the same seed produce bit-identical compressed exchanges, and the
    host trace can replay any key without communication. ``step`` and
    ``node`` may be traced (inside jit) or concrete (host twin)."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
    key = jax.random.fold_in(key, hop)
    if node is not None:
        key = jax.random.fold_in(key, node)
    return key


class CompressedLink:
    """One outer-loop communication hop as compress → wire → decompress.

    The orthogonal-composition layer (ISSUE 12): any strategy that ships
    a flat f32 payload over the (emulated) wire — DiLoCo's outer delta,
    NoLoCo's gossip exchange, the decoupled-momentum all-reduce,
    DynamiQ's two all-reduce hops — wraps the payload in a link instead
    of calling codecs inline, and gets for free:

    - **codec dispatch** incl. the dense passthrough (``codec=None`` /
      ``"dense"``): an uncompressed strategy is the same code path with
      an identity link, so ``codec`` becomes a config axis, not a fork;
    - **persistent error-feedback residual** (Stich et al. 1809.07599):
      ``encode`` adds the residual to the payload before compression and
      returns the new residual (``send − delivered``) for the strategy
      to carry in its STATE — training state, sharded/replicated like
      the params, checkpointed and restored across ``fit(resume=...)``
      with everything else. Default ON for every lossy codec (aggressive
      int4/top-k outer deltas do not converge without it — the ablation
      is test-asserted); ``error_feedback=False`` is the ablation knob;
    - **key discipline** (``link_key``): per-step, per-hop, per-node
      rounding keys derived from the strategy's base seed — no key reuse
      between gossip partners within a step, bit-reproducible across
      runs;
    - **honest wire accounting**: ``wire_bytes(n)`` is what the owning
      strategy's ``comm_events`` declares (and its jitted ``comm_bytes``
      metric reports) while the SPMD emulation moves dense f32 — the
      realized-vs-moved split the static verifier reconciles, with
      ``emulated_bytes`` bounding the dense side.
    """

    def __init__(self, codec: Union[str, Codec, None] = None,
                 seed: int = 0, error_feedback: Optional[bool] = None,
                 **codec_kwargs):
        if codec is None or codec == "dense":
            if codec_kwargs:
                raise ValueError(
                    f"codec kwargs {sorted(codec_kwargs)} given for the "
                    f"dense (identity) link")
            self.codec: Optional[Codec] = None
        else:
            self.codec = make_codec(codec, **codec_kwargs)
        self.seed = int(seed)
        if error_feedback is None:
            # EF default-on for every lossy codec: quantization's
            # stochastic rounding is unbiased but its per-round variance
            # still compounds through the outer loop; top-k is biased
            # outright. The residual costs one f32 vector of state.
            error_feedback = self.codec is not None
        self.error_feedback = bool(error_feedback) and self.codec is not None

    @property
    def compressed(self) -> bool:
        return self.codec is not None

    # -- state ------------------------------------------------------------

    def init(self, n: int) -> Dict[str, jnp.ndarray]:
        """The link's contribution to the owning strategy's state: the
        error-feedback residual (empty when the link carries none)."""
        if not self.error_feedback:
            return {}
        return {"ef_residual": jnp.zeros((int(n),), jnp.float32)}

    # -- keys -------------------------------------------------------------

    def key(self, step, hop: int = 0, node=None):
        """Per-(step, hop[, node]) rounding key — see ``link_key``."""
        return link_key(self.seed, step, hop, node)

    # -- the wire ---------------------------------------------------------

    def encode(self, x: jnp.ndarray, residual, key):
        """One payload through the link: ``(delivered, new_residual)``.

        ``delivered`` is what the receiving end reconstructs (for the
        dense link, ``x`` itself — the payload and its reconstruction
        coincide). ``residual=None`` means the caller carries no
        residual for this hop (dense link, or a strategy like decoupled
        momentum whose momentum buffer IS the residual); otherwise the
        EF recursion runs: ``send = x + residual``,
        ``new_residual = send − delivered``."""
        if self.codec is None:
            return x, residual
        send = x if residual is None else x + residual
        x_hat = self.codec.roundtrip(send, key)
        return x_hat, (None if residual is None else send - x_hat)

    def send(self, x: jnp.ndarray, lstate: Dict[str, jnp.ndarray], key):
        """Dict-state form of ``encode`` over the ``init`` layout: pulls
        the residual out of ``lstate``, returns the delivered payload and
        the updated ``lstate``."""
        residual = lstate["ef_residual"] if self.error_feedback else None
        x_hat, new_residual = self.encode(x, residual, key)
        if not self.error_feedback:
            return x_hat, lstate
        return x_hat, dict(lstate, ef_residual=new_residual)

    # -- accounting -------------------------------------------------------

    def wire_bytes(self, n: int) -> float:
        """Honest wire bytes for an ``n``-element payload: the codec's
        accounting, or dense f32 for the identity link."""
        if self.codec is None:
            return 4.0 * n
        return self.codec.wire_bytes(n)

    def config(self) -> Dict[str, Any]:
        if self.codec is None:
            return {"codec": "dense"}
        cfg = dict(self.codec.config())
        cfg["link_error_feedback"] = self.error_feedback
        return cfg
